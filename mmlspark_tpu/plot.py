"""Plotting helpers: confusion matrix + ROC.

Reference: src/plot/src/main/python/plot.py:17-40+ (`confusionMatrix` and
ROC helpers over a scored DataFrame, matplotlib/sklearn). Here the numerics
come from `automl.metrics` (pure numpy/JAX) and matplotlib only renders;
both functions also return the computed arrays so headless callers can skip
rendering entirely (ax=False).
"""

from __future__ import annotations

import numpy as np

from .automl.metrics import auc as _auc, roc_curve as _roc_curve
from .core.schema import Table

__all__ = ["confusion_matrix", "plot_confusion_matrix", "plot_roc"]


def confusion_matrix(table: Table, label_col: str = "label",
                     prediction_col: str = "scored_labels") -> np.ndarray:
    """(K, K) counts with rows = true class, cols = predicted class."""
    y = np.asarray(table[label_col], np.float64)
    p = np.asarray(table[prediction_col], np.float64)
    classes = np.unique(np.concatenate([y, p]))
    k = len(classes)
    yi = np.searchsorted(classes, y)
    pi = np.searchsorted(classes, p)
    m = np.zeros((k, k), np.int64)
    np.add.at(m, (yi, pi), 1)
    return m


def _axes(ax):
    """ax=False -> no rendering; ax=None -> a fresh standalone Figure axes
    (no pyplot state, no global-backend mutation — callers own the figure
    via ax.figure)."""
    if ax is False:
        return None
    if ax is not None:
        return ax
    from matplotlib.backends.backend_agg import FigureCanvasAgg
    from matplotlib.figure import Figure

    fig = Figure()
    FigureCanvasAgg(fig)
    return fig.add_subplot()


def plot_confusion_matrix(table: Table, label_col: str = "label",
                          prediction_col: str = "scored_labels", ax=None):
    """Reference plot.confusionMatrix (plot.py:17-30). Returns (matrix, ax);
    pass ax=False to skip rendering."""
    m = confusion_matrix(table, label_col, prediction_col)
    ax = _axes(ax)
    if ax is not None:
        ax.imshow(m, cmap="Blues")
        for (i, j), v in np.ndenumerate(m):
            ax.text(j, i, str(v), ha="center", va="center")
        ax.set_xlabel("predicted")
        ax.set_ylabel("true")
        ax.set_title("confusion matrix")
    return m, ax


def plot_roc(table: Table, label_col: str = "label",
             scores_col: str = "scores", ax=None):
    """Reference plot ROC helper (plot.py:32-40+). Returns
    ((fpr, tpr, thresholds), auc_value, ax); pass ax=False to skip
    rendering."""
    y = np.asarray(table[label_col], np.float64)
    s = np.asarray(table[scores_col], np.float64)
    fpr, tpr, thr = _roc_curve(y, s)
    auc_value = _auc(y, s)
    ax = _axes(ax)
    if ax is not None:
        ax.plot(fpr, tpr, label=f"AUC = {auc_value:.3f}")
        ax.plot([0, 1], [0, 1], linestyle="--", linewidth=0.8)
        ax.set_xlabel("false positive rate")
        ax.set_ylabel("true positive rate")
        ax.legend()
        ax.set_title("ROC")
    return (fpr, tpr, thr), auc_value, ax
