"""Durable serving journal: checkpoint/restart recovery for batch-mode
serving.

Reference contract: DistributedHTTPSource implements getOffset/getBatch/
commit with batch trimming and documents `checkpointLocation` recovery
(DistributedHTTPSource.scala:308-343, docs/mmlspark-serving.md:50-52) — a
restarted streaming query replays uncommitted requests, and every accepted
request is processed EXACTLY ONCE by the pipeline.

TPU-framework redesign: one append-only JSONL journal per checkpoint dir.
Two record types — `accept` (written when the HTTP frontend parks a
request) and `reply` (written when the scoring path completes it). The
invariant the journal maintains is the reference's: `accepts - replies` is
exactly the set of in-flight requests, under crashes at any point.
Duplicate replies are suppressed at the journal (exactly-once), and
`compact()` is the commit-trimming analogue — fully answered pairs are
dropped once both records are on disk.

The original TCP connection cannot survive a process restart (true in the
reference too — Spark holds the HTTP exchange in memory); what recovery
guarantees is that the accepted request still flows through the handler
and its reply is durably recorded, retrievable via `reply_of`.
"""

from __future__ import annotations

import base64
import json
import os
import threading

from ..observability.sanitizer import allow_blocking, make_lock
from .schema import HTTPRequestData, HTTPResponseData
from ..utils.storage import atomic_write

__all__ = ["ServingJournal"]


class ServingJournal:
    """Append-only accept/reply log under `checkpoint_dir/journal.jsonl`."""

    FILENAME = "journal.jsonl"

    def __init__(self, checkpoint_dir: str):
        self.dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.path = os.path.join(checkpoint_dir, self.FILENAME)
        self._lock = make_lock("ServingJournal._lock")
        self._accepts: dict[str, HTTPRequestData] = {}
        self._replies: dict[str, HTTPResponseData] = {}
        self._load()
        self._fh = open(self.path, "a", encoding="utf-8")

    # -- state ----------------------------------------------------------- #

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        good_end = 0     # byte offset just past the last intact record
        with open(self.path, "rb") as fh:
            for raw in fh:
                if not raw.endswith(b"\n"):
                    break                       # torn tail (no newline)
                line = raw.strip()
                if not line:
                    good_end += len(raw)
                    continue
                try:
                    rec = json.loads(line.decode("utf-8"))
                except (json.JSONDecodeError, UnicodeDecodeError):
                    # torn/corrupt record from a crash mid-append:
                    # everything before it is intact, the torn record's
                    # request was never acknowledged durably — stop here
                    break
                good_end += len(raw)
                self._apply(rec)
        # drop the torn tail ON DISK, not just in memory: appending after
        # a partial line would fuse the next record onto it and a later
        # restart would lose everything from that point on
        if good_end < os.path.getsize(self.path):
            with open(self.path, "r+b") as fh:
                fh.truncate(good_end)

    def _apply(self, rec: dict) -> None:
        if rec.get("t") == "accept":
            self._accepts[rec["id"]] = HTTPRequestData(
                method=rec.get("method", "POST"),
                url=rec.get("url", ""),
                headers=rec.get("headers", {}),
                entity=base64.b64decode(rec["entity"])
                if rec.get("entity") is not None else None,
            )
        elif rec.get("t") == "reply":
            self._replies[rec["id"]] = HTTPResponseData(
                status_code=rec.get("status", 0),
                reason=rec.get("reason", ""),
                headers=rec.get("headers", {}),
                entity=base64.b64decode(rec["entity"])
                if rec.get("entity") is not None else None,
            )

    def _append(self, rec: dict) -> None:
        # Write + flush under the caller's lock (preserves record order);
        # the durability fsync happens in _sync() AFTER the lock is
        # released — group commit.
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def _sync(self) -> None:
        # fsync flushes the whole fd, so records flushed by other threads
        # between our _append and this call ride along for free.
        fh = self._fh
        try:
            os.fsync(fh.fileno())
        except (OSError, ValueError):
            # fd replaced or closed by a concurrent compact()/close();
            # the compacted file is already durable (atomic_write fsyncs
            # before rename), so there is nothing left to sync.
            pass

    # -- recording ------------------------------------------------------- #

    def record_accept(self, ex_id: str, req: HTTPRequestData) -> None:
        with self._lock:
            self._accepts[ex_id] = req
            self._append({
                "t": "accept", "id": ex_id, "method": req.method,
                "url": req.url, "headers": dict(req.headers or {}),
                "entity": base64.b64encode(req.entity).decode()
                if req.entity is not None else None,
            })
        self._sync()

    def record_reply(self, ex_id: str, resp: HTTPResponseData) -> bool:
        """Record a reply; False (and no write) if `ex_id` was already
        answered — the exactly-once guard."""
        with self._lock:
            if ex_id in self._replies:
                return False
            self._replies[ex_id] = resp
            self._append({
                "t": "reply", "id": ex_id,
                "status": resp.status_code, "reason": resp.reason,
                "headers": dict(resp.headers or {}),
                "entity": base64.b64encode(resp.entity).decode()
                if resp.entity is not None else None,
            })
        self._sync()
        return True

    # -- queries --------------------------------------------------------- #

    def unanswered(self) -> dict[str, HTTPRequestData]:
        """Accepted requests with no recorded reply (the replay set)."""
        with self._lock:
            return {i: r for i, r in self._accepts.items()
                    if i not in self._replies}

    def replied(self, ex_id: str) -> bool:
        with self._lock:
            return ex_id in self._replies

    def reply_of(self, ex_id: str) -> HTTPResponseData | None:
        with self._lock:
            return self._replies.get(ex_id)

    def max_id(self) -> int:
        """Largest integer id on record (server id counters resume past it
        so restart never reuses a journaled id)."""
        with self._lock:
            ids = [int(i) for i in
                   list(self._accepts) + list(self._replies)
                   if str(i).isdigit()]
        return max(ids, default=-1)

    # -- commit trimming -------------------------------------------------- #

    def compact(self) -> int:
        """Drop fully answered accept/reply pairs from disk (the
        reference's commit() batch trimming). Returns pairs trimmed.
        Atomic via `utils.storage.atomic_write` (tmp + fsync + rename
        + dir-fsync)."""
        with self._lock:
            answered = [i for i in self._accepts if i in self._replies]
            for i in answered:
                del self._accepts[i]
                del self._replies[i]
            # replies without accepts can't exist (reply() requires the
            # pending exchange), so the rewrite is accepts-only
            lines = [json.dumps({
                "t": "accept", "id": i, "method": r.method,
                "url": r.url, "headers": dict(r.headers or {}),
                "entity": base64.b64encode(r.entity).decode()
                if r.entity is not None else None,
            }) + "\n" for i, r in self._accepts.items()]
            self._fh.close()
            # stop-the-world by design: recorders must stay excluded
            # across the rewrite or their appends land on the replaced fd
            with allow_blocking("journal compact rewrite"):
                atomic_write(self.path, "".join(lines))
            self._fh = open(self.path, "a", encoding="utf-8")
            return len(answered)

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except OSError:
                pass
