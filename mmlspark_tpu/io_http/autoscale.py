"""FleetAutoscaler: SLO-signal-driven replica scaling + self-healing.

The ROADMAP's "autoscaled replica fleet behind a routing gateway" arc:
`SLOEngine.signals()` (observability/slo.py) already distills the fleet
aggregate into the scaling inputs — queue depth, p99 latency, shed rate,
burn rate — and `ServingFleet` grew `scale_to`/`respawn`. This module is
the controller between them:

  * scale UP one replica when any pressure signal crosses its threshold
  * scale DOWN one replica only after `hysteresis_ticks` CONSECUTIVE
    calm ticks — a single quiet sample never sheds capacity
  * a `cooldown_s` window after every scale action blocks further
    scaling in either direction, so up/down cannot flap even when the
    signals oscillate around a threshold
  * self-healing runs BEFORE scaling and OUTSIDE the cooldown: a
    crashed replica (`fleet.dead_slots()`) is respawned immediately —
    healing restores the approved capacity, it does not change it

Everything runs on the injectable clock; chaos tests drive `tick()` by
hand on a FakeClock with zero real waiting.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable

from ..observability.sanitizer import make_rlock
from ..resilience.policy import SYSTEM_CLOCK

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """Drives `fleet` between `min_replicas` and `max_replicas` from SLO
    signals.

    `signals` is an `SLOEngine` (its `.signals()` is polled after an
    `.evaluate()` refresh) or any zero-arg callable returning the same
    dict: {queue_depth, p99_latency_s, shed_rate, burn_rate, ...}.
    """

    def __init__(
        self,
        fleet,
        signals: Any,
        min_replicas: int = 1,
        max_replicas: int = 4,
        up_queue_depth: float = 8.0,
        up_p99_s: float = 0.5,
        up_shed_rate: float = 0.05,
        up_burn_rate: float = 10.0,
        down_fraction: float = 0.5,
        hysteresis_ticks: int = 3,
        cooldown_s: float = 30.0,
        clock: Any = None,
        metrics: Any = None,
        extra_up: "dict[str, float] | None" = None,
        timeline: Any = None,
        trend_window_s: float = 60.0,
        up_queue_slope: "float | None" = None,
        up_p99_slope: "float | None" = None,
    ):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}")
        self.fleet = fleet
        self._signals = signals
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_queue_depth = float(up_queue_depth)
        self.up_p99_s = float(up_p99_s)
        self.up_shed_rate = float(up_shed_rate)
        self.up_burn_rate = float(up_burn_rate)
        # domain-specific up-thresholds beyond the four serving SLOs:
        # {signal_key: threshold} — elastic training adds step-time p99
        # and straggler-wait here; extra signals obey the same
        # down_fraction calm band as the built-ins
        self.extra_up = {k: float(v) for k, v in (extra_up or {}).items()}
        # trend signals from the telemetry timeline (a TimelineStore or
        # a TimelineRecorder): windowed least-squares slope of queue
        # depth and p99 over `trend_window_s`, so scaling acts on where
        # the fleet is HEADED, not only on where it is. Opt-in via the
        # slope thresholds (units/second); trends only push UP — a
        # falling queue never sheds capacity by itself, the calm band
        # still owns scale-down.
        self.timeline = getattr(timeline, "store", timeline)
        self.trend_window_s = float(trend_window_s)
        self.up_queue_slope = (float(up_queue_slope)
                               if up_queue_slope is not None else None)
        self.up_p99_slope = (float(up_p99_slope)
                             if up_p99_slope is not None else None)
        # calm = every signal under down_fraction * its up threshold —
        # the hysteresis BAND between the up and down trigger points
        self.down_fraction = float(down_fraction)
        self.hysteresis_ticks = int(hysteresis_ticks)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        # RLock: tick() holds it while calling heal(), which is also a
        # public entry point and takes it itself
        self._lock = make_rlock("FleetAutoscaler._lock")
        self._calm_ticks = 0
        self._last_action = "none"
        self._last_action_t = float("-inf")
        self._last_signals: dict = {}
        self._last_reasons: list[str] = []
        self.events: collections.deque = collections.deque(maxlen=64)
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        from ..observability.metrics import get_registry

        reg = metrics if metrics is not None else get_registry()
        self._g_target = reg.gauge(
            "mmlspark_tpu_autoscaler_target_replicas_count",
            "replica count the autoscaler is holding the fleet at")
        self._g_calm = reg.gauge(
            "mmlspark_tpu_autoscaler_calm_ticks_count",
            "consecutive calm ticks toward a scale-down")
        self._c_events = reg.counter(
            "mmlspark_tpu_autoscaler_scale_events_total",
            "scale actions taken, by direction",
            labels=("direction",))
        self._g_qslope = reg.gauge(
            "mmlspark_tpu_autoscaler_queue_slope_rate",
            "windowed least-squares slope of fleet queue depth (per s)")
        self._g_pslope = reg.gauge(
            "mmlspark_tpu_autoscaler_p99_slope_rate",
            "half-window delta of serving p99 latency (seconds per s)")
        self._g_target.set(self.fleet.n_live)

    # -- signal plumbing ------------------------------------------------ #

    def read_signals(self) -> dict:
        src = self._signals
        if hasattr(src, "signals"):
            # SLOEngine: refresh burn-rate windows, then read
            try:
                src.evaluate()
            except Exception:  # noqa: BLE001 — stale windows beat a crash
                pass
            sig = src.signals()
        else:
            sig = src()
        sig = dict(sig)
        sig.update(self._trend())
        return sig

    def _trend(self) -> dict:
        """Timeline trend signals: queue-depth slope over the trend
        window plus the half-window-to-half-window p99 delta rate. Empty
        (and pressure-neutral) without a timeline or while the history
        is still shorter than the window."""
        tl = self.timeline
        if tl is None:
            return {}
        w = self.trend_window_s
        try:
            at = tl.last_time()
            if at is None:
                return {}
            qs = tl.slope("mmlspark_tpu_serving_queue_depth", w, at=at)
            half = w / 2.0
            p99_now = tl.quantile_over(
                "mmlspark_tpu_serving_latency_seconds", 0.99, half,
                at=at)
            p99_then = tl.quantile_over(
                "mmlspark_tpu_serving_latency_seconds", 0.99, half,
                at=at - half)
            ps = (p99_now - p99_then) / half if half > 0 else 0.0
        except Exception:  # noqa: BLE001 — trends are advisory inputs
            return {}
        self._g_qslope.set(qs)
        self._g_pslope.set(ps)
        return {"queue_depth_slope": qs, "p99_latency_slope": ps}

    def _pressure(self, sig: dict) -> list[str]:
        """Which up-thresholds the current signals cross (empty = calm
        enough to COUNT toward a scale-down when fully under the band)."""
        reasons = []
        if sig.get("queue_depth", 0.0) > self.up_queue_depth:
            reasons.append("queue_depth")
        p99 = sig.get("p99_latency_s", 0.0)
        if p99 == p99 and p99 > self.up_p99_s:  # NaN-safe
            reasons.append("p99_latency")
        if sig.get("shed_rate", 0.0) > self.up_shed_rate:
            reasons.append("shed_rate")
        if sig.get("burn_rate", 0.0) > self.up_burn_rate:
            reasons.append("burn_rate")
        if (self.up_queue_slope is not None
                and sig.get("queue_depth_slope", 0.0)
                > self.up_queue_slope):
            reasons.append("queue_depth_slope")
        if (self.up_p99_slope is not None
                and sig.get("p99_latency_slope", 0.0) > self.up_p99_slope):
            reasons.append("p99_latency_slope")
        for key, threshold in self.extra_up.items():
            v = sig.get(key, 0.0)
            if v == v and v > threshold:  # NaN-safe
                reasons.append(key)
        return reasons

    def _calm(self, sig: dict) -> bool:
        f = self.down_fraction
        p99 = sig.get("p99_latency_s", 0.0)
        if p99 != p99:
            p99 = 0.0
        if not (sig.get("queue_depth", 0.0) <= self.up_queue_depth * f
                and p99 <= self.up_p99_s * f
                and sig.get("shed_rate", 0.0) <= self.up_shed_rate * f
                and sig.get("burn_rate", 0.0) <= self.up_burn_rate * f):
            return False
        if (self.up_queue_slope is not None
                and sig.get("queue_depth_slope", 0.0)
                > self.up_queue_slope * f):
            return False
        if (self.up_p99_slope is not None
                and sig.get("p99_latency_slope", 0.0)
                > self.up_p99_slope * f):
            return False
        for key, threshold in self.extra_up.items():
            v = sig.get(key, 0.0)
            if v != v:
                v = 0.0
            if v > threshold * f:
                return False
        return True

    # -- control loop --------------------------------------------------- #

    def _record(self, action: str, detail: str) -> None:
        now = self.clock.monotonic()
        self._last_action = action
        self._last_action_t = now
        self.events.append({"t": now, "action": action, "detail": detail,
                            "n_live": self.fleet.n_live})
        self._c_events.labels(direction=action).inc()
        # the same transition lands in the black box so a postmortem
        # shows WHEN capacity moved relative to the trigger
        from ..observability.recorder import get_recorder

        get_recorder().record_transition(
            "autoscaler", action, detail=detail, n_live=self.fleet.n_live)

    def heal(self) -> list[int]:
        """Respawn every crashed (non-retired) slot. Runs outside the
        cooldown: healing restores approved capacity, it is not a
        scaling decision."""
        healed = []
        with self._lock:
            for slot in self.fleet.dead_slots():
                try:
                    self.fleet.respawn(slot)
                    healed.append(slot)
                    self._record("respawn", f"slot {slot}")
                except Exception as e:  # noqa: BLE001 — keep healing others
                    self.events.append({
                        "t": self.clock.monotonic(),
                        "action": "respawn_failed",
                        "detail": f"slot {slot}: {e}",
                        "n_live": self.fleet.n_live})
        return healed

    def in_cooldown(self) -> bool:
        return (self.clock.monotonic() - self._last_action_t
                < self.cooldown_s)

    def tick(self) -> str:
        """One control step: heal, read signals, maybe scale by ±1.
        Returns the action taken ("respawn" reports healing even when no
        scaling happened)."""
        with self._lock:
            healed = self.heal()
            sig = self.read_signals()
            self._last_signals = sig
            reasons = self._pressure(sig)
            self._last_reasons = reasons
            n = self.fleet.n_live
            action = "respawn" if healed else "none"
            if reasons:
                self._calm_ticks = 0
                if n < self.max_replicas and not self.in_cooldown():
                    self.fleet.scale_to(n + 1)
                    self._record("up", ",".join(reasons))
                    action = "up"
            elif self._calm(sig):
                self._calm_ticks += 1
                if (self._calm_ticks >= self.hysteresis_ticks
                        and n > self.min_replicas
                        and not self.in_cooldown()):
                    self.fleet.scale_to(n - 1)
                    self._record("down",
                                 f"calm x{self._calm_ticks}")
                    self._calm_ticks = 0
                    action = "down"
            else:
                # inside the hysteresis band: neither direction moves
                self._calm_ticks = 0
            self._g_target.set(self.fleet.n_live)
            self._g_calm.set(self._calm_ticks)
            return action

    def state(self) -> dict:
        """Snapshot for GET /autoscaler and tools/diagnose.py."""
        with self._lock:
            cooldown_left = max(
                0.0, self.cooldown_s
                - (self.clock.monotonic() - self._last_action_t))
            return {
                "n_live": self.fleet.n_live,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "calm_ticks": self._calm_ticks,
                "hysteresis_ticks": self.hysteresis_ticks,
                "cooldown_s": self.cooldown_s,
                "cooldown_remaining_s": (cooldown_left
                                         if cooldown_left != float("inf")
                                         else 0.0),
                "last_action": self._last_action,
                "pressure": list(self._last_reasons),
                "signals": dict(self._last_signals),
                "events": list(self.events)[-8:],
            }

    # -- background loop ------------------------------------------------ #

    def start(self, interval_s: float = 5.0) -> "FleetAutoscaler":
        """Tick on a background thread every `interval_s` (through the
        injectable clock). Tests drive tick() directly instead."""
        def _loop():
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the loop must survive
                    pass
                self.clock.sleep(interval_s)

        self._thread = threading.Thread(target=_loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
