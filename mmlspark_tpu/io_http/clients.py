"""HTTP clients: retry/backoff + concurrency.

Reference: src/io/http/src/main/scala/HTTPClients.scala:19-151 — retry with
exponential backoff and 429 Retry-After handling (:64-105),
`SingleThreadedHTTPClient` and `AsyncHTTPClient` (sliding window of Futures,
Clients.scala:102-116 + AsyncUtils.bufferedAwait). Here: urllib on threads;
the async window is utils.async_utils.buffered_map.

Retry semantics are delegated to resilience.policy.RetryPolicy (one
implementation for the whole package); the legacy `retries`/`backoff_ms`
arguments build an equivalent fixed-ladder policy. An optional
resilience.CircuitBreaker short-circuits a dead endpoint to a synthetic
503 instead of burning the backoff budget per request.
"""

from __future__ import annotations

import urllib.error
import urllib.request
from typing import Iterable, Sequence

from ..observability.tracing import current_traceparent
from ..resilience.breaker import CircuitBreaker
from ..resilience.policy import (RetryPolicy, is_retryable_exception,
                                 is_retryable_status)
from ..utils.async_utils import buffered_map
from .schema import HTTPRequestData, HTTPResponseData

__all__ = ["http_send", "HTTPClient"]


def _legacy_policy(retries: int, backoff_ms: Sequence[float]) -> RetryPolicy:
    """The pre-resilience contract: `retries` total attempts walking the
    `backoff_ms` ladder (HTTPClients.scala's hard-coded schedule)."""
    return RetryPolicy(max_retries=max(retries, 1) - 1,
                       backoffs_ms=list(backoff_ms))


def _breaker_open_response(breaker: CircuitBreaker) -> HTTPResponseData:
    """Synthetic local 503 while the circuit is open — same shape as a
    server-side overload answer, so error_col/fallback paths need no
    special case."""
    return HTTPResponseData(
        503, f"circuit open: {breaker.name or 'endpoint'}",
        headers={"Retry-After": f"{breaker.retry_after_s():.3f}"},
        entity=None,
    )


def http_send(
    req: HTTPRequestData,
    timeout: float = 60.0,
    retries: int = 3,
    backoff_ms: Sequence[int] = (100, 500, 1000),
    policy: "RetryPolicy | None" = None,
    breaker: "CircuitBreaker | None" = None,
) -> HTTPResponseData:
    """One request with the reference's retry semantics
    (HTTPClients.scala:64-105): retry on 429/5xx/connection errors, honor
    Retry-After (capped by the policy — an adversarial `Retry-After: 1e9`
    must not hang the pipeline thread), back off between attempts."""
    if policy is None:
        policy = _legacy_policy(retries, backoff_ms)
    if breaker is not None and not breaker.allow():
        return _breaker_open_response(breaker)
    # W3C trace propagation: when a span is active, stamp (or REPLACE —
    # per-hop parent-id semantics) the traceparent header so the server
    # side binds its request span into this trace. No active span leaves
    # the caller's own headers untouched.
    headers = dict(req.headers or {})
    traceparent = current_traceparent()
    if traceparent is not None:
        headers = {k: v for k, v in headers.items()
                   if k.lower() != "traceparent"}
        headers["traceparent"] = traceparent
    sess = policy.session()
    last_exc: Exception | None = None
    while True:
        try:
            r = urllib.request.Request(
                req.url, data=req.entity, headers=headers,
                method=req.method,
            )
            with urllib.request.urlopen(r, timeout=timeout) as resp:
                if breaker is not None:
                    breaker.record_success()
                return HTTPResponseData(
                    status_code=resp.status,
                    reason=getattr(resp, "reason", "") or "",
                    headers=dict(resp.headers),
                    entity=resp.read(),
                )
        except urllib.error.HTTPError as e:
            body = e.read()
            if is_retryable_status(e.code):
                if breaker is not None:
                    breaker.record_failure()
                if sess.should_retry():
                    retry_after = e.headers.get("Retry-After")
                    try:
                        retry_after_s = (float(retry_after)
                                         if retry_after is not None else None)
                    except ValueError:
                        retry_after_s = None
                    sess.backoff(retry_after_s=retry_after_s)
                    continue
            elif breaker is not None:
                # non-retryable 4xx: the endpoint answered — it is healthy
                breaker.record_success()
            return HTTPResponseData(
                status_code=e.code, reason=str(e.reason),
                headers=dict(e.headers), entity=body,
            )
        except Exception as e:  # noqa: BLE001 — connection-level retry
            last_exc = e
            if breaker is not None:
                breaker.record_failure()
            if is_retryable_exception(e) and sess.should_retry():
                sess.backoff()
                continue
            return HTTPResponseData(
                status_code=0, reason=str(last_exc), entity=None)


class HTTPClient:
    """Batched sender. concurrency>1 = the reference's AsyncHTTPClient
    sliding window; 1 = SingleThreadedHTTPClient."""

    def __init__(self, concurrency: int = 1, timeout: float = 60.0,
                 retries: int = 3, policy: "RetryPolicy | None" = None,
                 breaker: "CircuitBreaker | None" = None):
        self.concurrency = concurrency
        self.timeout = timeout
        self.retries = retries
        self.policy = policy
        self.breaker = breaker

    def send_all(self, reqs: Iterable[HTTPRequestData]) -> list[HTTPResponseData]:
        fn = lambda r: http_send(  # noqa: E731
            r, timeout=self.timeout, retries=self.retries,
            policy=self.policy, breaker=self.breaker)
        if self.concurrency <= 1:
            return [fn(r) for r in reqs]
        return list(buffered_map(fn, list(reqs), self.concurrency))
