"""HTTP clients: retry/backoff + concurrency.

Reference: src/io/http/src/main/scala/HTTPClients.scala:19-151 — retry with
exponential backoff and 429 Retry-After handling (:64-105),
`SingleThreadedHTTPClient` and `AsyncHTTPClient` (sliding window of Futures,
Clients.scala:102-116 + AsyncUtils.bufferedAwait). Here: pooled keep-alive
http.client connections on threads (`_ConnectionPool`); the async window is
utils.async_utils.buffered_map.

Retry semantics are delegated to resilience.policy.RetryPolicy (one
implementation for the whole package); the legacy `retries`/`backoff_ms`
arguments build an equivalent fixed-ladder policy. An optional
resilience.CircuitBreaker short-circuits a dead endpoint to a synthetic
503 instead of burning the backoff budget per request.

`TargetPool` is the one request-spreading primitive for multi-replica
targets (the reference's load balancer in front of per-executor servers):
a mutable set of base URLs with a per-URL breaker, manual eject/admit on
top of breaker state, in-flight accounting, and three pick strategies —
round-robin, least-loaded, and consistent hash on a caller key.
`HTTPClient(urls=[...])` and io_http.gateway.ServingGateway both route
through it, so replica failover has exactly one tested implementation.
"""

from __future__ import annotations

import hashlib
import http.client
import itertools
import threading
import urllib.parse
from typing import Iterable, Sequence

from ..observability.sanitizer import make_lock
from ..observability.tracing import current_traceparent
from ..resilience.breaker import BreakerRegistry, CircuitBreaker
from ..resilience.policy import (RetryPolicy, is_retryable_exception,
                                 is_retryable_status)
from ..utils.async_utils import buffered_map
from .schema import HTTPRequestData, HTTPResponseData

__all__ = ["http_send", "HTTPClient", "TargetPool"]


def _legacy_policy(retries: int, backoff_ms: Sequence[float]) -> RetryPolicy:
    """The pre-resilience contract: `retries` total attempts walking the
    `backoff_ms` ladder (HTTPClients.scala's hard-coded schedule)."""
    return RetryPolicy(max_retries=max(retries, 1) - 1,
                       backoffs_ms=list(backoff_ms))


def _breaker_open_response(breaker: CircuitBreaker) -> HTTPResponseData:
    """Synthetic local 503 while the circuit is open — same shape as a
    server-side overload answer, so error_col/fallback paths need no
    special case."""
    return HTTPResponseData(
        503, f"circuit open: {breaker.name or 'endpoint'}",
        headers={"Retry-After": f"{breaker.retry_after_s():.3f}"},
        entity=None,
    )


class _ConnectionPool:
    """Process-wide keep-alive socket pool keyed by (scheme, host, port).

    Every `http_send` borrows a connection here instead of opening a
    fresh TCP socket per request — the three-way handshake was the
    single biggest fixed cost on the sub-millisecond serving path. Idle
    connections per endpoint are capped (`max_per_host`); a release over
    the cap closes the socket instead of pooling it, so a burst against
    many replicas cannot accumulate unbounded open sockets.

    A borrowed connection is exclusively owned until released, so no
    locking is needed around the exchange itself — only the idle lists
    are guarded."""

    def __init__(self, max_per_host: int = 8):
        self.max_per_host = max_per_host
        self._idle: "dict[tuple, list[http.client.HTTPConnection]]" = {}
        self._lock = make_lock("_ConnectionPool._lock")
        self.creates = 0
        self.reuses = 0
        self.stale_retries = 0

    @staticmethod
    def _new(scheme: str, host: str, port: int,
             timeout: float) -> http.client.HTTPConnection:
        cls = (http.client.HTTPSConnection if scheme == "https"
               else http.client.HTTPConnection)
        return cls(host, port, timeout=timeout)

    def acquire(self, scheme: str, host: str, port: int, timeout: float
                ) -> "tuple[http.client.HTTPConnection, bool]":
        """(connection, reused) — reused=True means the socket has served
        a previous exchange and may have been closed server-side since."""
        key = (scheme, host, port)
        with self._lock:
            idle = self._idle.get(key)
            while idle:
                conn = idle.pop()
                if conn.sock is not None:
                    try:
                        # a locally closed fd is detectable for free —
                        # skip it; only remotely half-closed sockets ever
                        # reach the stale-retry path in _send_once
                        conn.sock.settimeout(timeout)
                    except OSError:
                        conn.close()
                        continue
                    self.reuses += 1
                    return conn, True
                conn.close()
            self.creates += 1
        return self._new(scheme, host, port, timeout), False

    def release(self, scheme: str, host: str, port: int,
                conn: http.client.HTTPConnection) -> None:
        if conn.sock is None:
            return
        with self._lock:
            idle = self._idle.setdefault((scheme, host, port), [])
            if len(idle) < self.max_per_host:
                idle.append(conn)
                return
        conn.close()

    def close_all(self) -> None:
        with self._lock:
            conns = [c for idle in self._idle.values() for c in idle]
            self._idle.clear()
        for c in conns:
            c.close()

    def stats(self) -> dict:
        with self._lock:
            idle = sum(len(v) for v in self._idle.values())
        return {"idle": idle, "creates": self.creates,
                "reuses": self.reuses, "stale_retries": self.stale_retries,
                "max_per_host": self.max_per_host}


_POOL = _ConnectionPool()


def connection_pool_stats() -> dict:
    """Live keep-alive pool counters (idle sockets, creates vs reuses,
    stale-socket retries) — surfaced by diagnose --serving."""
    return _POOL.stats()


def configure_connection_pool(max_per_host: int) -> None:
    """Resize the per-endpoint idle-socket cap (existing idle sockets
    above the new cap drain as they are next released)."""
    _POOL.max_per_host = int(max_per_host)


def _header(headers: dict, name: str) -> "str | None":
    low = name.lower()
    for k, v in headers.items():
        if k.lower() == low:
            return v
    return None


def _send_once(method: str, url: str, body: "bytes | None",
               headers: dict, timeout: float) -> HTTPResponseData:
    """One HTTP exchange over a pooled keep-alive connection. Returns a
    response for ANY status the server answers with — status policy
    (retryable vs not) stays in http_send.

    Stale-socket retry-once: a connection-level failure on a REUSED
    socket before the status line arrives means the server closed an
    idle keep-alive connection — a normal race, not an endpoint failure
    — so the exchange transparently replays ONCE on a brand-new socket.
    Fresh-socket failures (and anything after the status line) propagate
    to the caller's retry/breaker logic unchanged."""
    parts = urllib.parse.urlsplit(url)
    scheme = parts.scheme or "http"
    host = parts.hostname or ""
    port = parts.port or (443 if scheme == "https" else 80)
    path = parts.path or "/"
    if parts.query:
        path = f"{path}?{parts.query}"
    conn, reused = _POOL.acquire(scheme, host, port, timeout)
    for attempt in (0, 1):
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
        except Exception:
            conn.close()
            if reused and attempt == 0:
                _POOL.stale_retries += 1
                conn, reused = _POOL._new(scheme, host, port, timeout), False
                continue
            raise
        try:
            # the body must be fully drained before the socket can carry
            # the next exchange; a mid-body failure is a REAL failure
            # (the server answered, then died) — no transparent replay
            entity = resp.read()
        except Exception:
            conn.close()
            raise
        if resp.will_close:
            conn.close()
        else:
            _POOL.release(scheme, host, port, conn)
        return HTTPResponseData(
            status_code=resp.status,
            reason=resp.reason or "",
            headers=dict(resp.getheaders()),
            entity=entity,
        )
    raise RuntimeError("unreachable")  # pragma: no cover


def http_send(
    req: HTTPRequestData,
    timeout: float = 60.0,
    retries: int = 3,
    backoff_ms: Sequence[int] = (100, 500, 1000),
    policy: "RetryPolicy | None" = None,
    breaker: "CircuitBreaker | None" = None,
) -> HTTPResponseData:
    """One request with the reference's retry semantics
    (HTTPClients.scala:64-105): retry on 429/5xx/connection errors, honor
    Retry-After (capped by the policy — an adversarial `Retry-After: 1e9`
    must not hang the pipeline thread), back off between attempts.

    Transport: pooled keep-alive connections (`_ConnectionPool`), so
    repeated sends to the same endpoint skip the TCP handshake. Breaker
    and retry accounting are UNCHANGED from the one-socket-per-request
    era: a stale reused socket replays once transparently inside
    `_send_once` without touching the breaker, while genuine connection
    failures still record_failure and surface as status 0."""
    if policy is None:
        policy = _legacy_policy(retries, backoff_ms)
    if breaker is not None and not breaker.allow():
        return _breaker_open_response(breaker)
    # W3C trace propagation: when a span is active, stamp (or REPLACE —
    # per-hop parent-id semantics) the traceparent header so the server
    # side binds its request span into this trace. No active span leaves
    # the caller's own headers untouched.
    headers = dict(req.headers or {})
    traceparent = current_traceparent()
    if traceparent is not None:
        headers = {k: v for k, v in headers.items()
                   if k.lower() != "traceparent"}
        headers["traceparent"] = traceparent
    sess = policy.session()
    last_exc: Exception | None = None
    while True:
        try:
            resp = _send_once(req.method, req.url, req.entity, headers,
                              timeout)
        except Exception as e:  # noqa: BLE001 — connection-level retry
            last_exc = e
            if breaker is not None:
                breaker.record_failure()
            if is_retryable_exception(e) and sess.should_retry():
                sess.backoff()
                continue
            return HTTPResponseData(
                status_code=0, reason=str(last_exc), entity=None)
        if resp.status_code >= 400 and is_retryable_status(resp.status_code):
            if breaker is not None:
                breaker.record_failure()
            if sess.should_retry():
                retry_after = _header(resp.headers, "Retry-After")
                try:
                    retry_after_s = (float(retry_after)
                                     if retry_after is not None else None)
                except ValueError:
                    retry_after_s = None
                sess.backoff(retry_after_s=retry_after_s)
                continue
            return resp
        if breaker is not None:
            # any answered status — including a non-retryable 4xx — means
            # the endpoint is healthy
            breaker.record_success()
        return resp


class _Target:
    """Per-URL pool state: in-flight count + manual health gate."""

    __slots__ = ("url", "inflight", "ejected", "eject_reason")

    def __init__(self, url: str):
        self.url = url
        self.inflight = 0
        self.ejected = False
        self.eject_reason = ""


def _stable_hash(s: str) -> int:
    """Process-independent 64-bit hash (builtin hash() is salted per
    process — a consistent-hash ring must agree across restarts)."""
    return int.from_bytes(
        hashlib.blake2b(s.encode(), digest_size=8).digest(), "big")


class _Lease:
    """Context manager pairing pick with in-flight accounting."""

    __slots__ = ("_pool", "url")

    def __init__(self, pool: "TargetPool", url: str):
        self._pool = pool
        self.url = url

    def __enter__(self) -> str:
        return self.url

    def __exit__(self, *exc) -> None:
        self._pool._release(self.url)


class TargetPool:
    """The one request-spreading primitive over a mutable set of replica
    base URLs (the reference's load balancer in front of per-executor
    servers, SURVEY.md §3.4). Thread-safe.

    Health is layered: each URL gets a per-endpoint CircuitBreaker (from
    `breakers`, a resilience.BreakerRegistry), and an independent manual
    eject/admit gate for probe-driven control (the gateway ejects on a
    failed /readyz and re-admits after probe success). A target is *live*
    when it is admitted AND its breaker is not open — half-open targets
    stay live so breaker probe traffic can heal them.

    Pick strategies:
      round_robin   next live target after a rotating cursor
      least_loaded  live target with the fewest in-flight leases
      hash          consistent hash of `key` over a virtual-node ring,
                    with a sticky binding remembered per key — a key
                    keeps its target until that target leaves the live
                    set (stateful/session-affine handlers). The ring
                    alone is NOT enough for stickiness: admitting a new
                    replica moves ~1/N of the ring, so a bare rehash
                    would silently re-home established streams onto a
                    replica that may not even speak their schema.
    """

    VNODES = 32  # virtual nodes per target on the hash ring
    STICKY_MAX = 65536  # remembered key bindings before oldest-first drop

    def __init__(self, urls: Sequence[str] = (),
                 breakers: "BreakerRegistry | None" = None,
                 clock=None, **breaker_kw):
        if breakers is None:
            from ..resilience.policy import SYSTEM_CLOCK

            breakers = BreakerRegistry(
                clock=clock if clock is not None else SYSTEM_CLOCK,
                **breaker_kw)
        self.breakers = breakers
        self._lock = make_lock("TargetPool._lock")
        self._targets: dict[str, _Target] = {}
        self._sticky: dict[str, str] = {}   # routing key -> bound url
        self._rr = itertools.count()
        for u in urls:
            self.add(u)

    # -- membership ----------------------------------------------------- #

    def add(self, url: str) -> None:
        with self._lock:
            if url not in self._targets:
                self._targets[url] = _Target(url)

    def remove(self, url: str) -> None:
        with self._lock:
            self._targets.pop(url, None)

    @property
    def urls(self) -> list[str]:
        with self._lock:
            return list(self._targets)

    # -- health gating -------------------------------------------------- #

    def eject(self, url: str, reason: str = "") -> bool:
        """Take a member out of rotation without forgetting it (breaker
        open / failed readiness probe). Returns True if state changed."""
        with self._lock:
            t = self._targets.get(url)
            if t is None or t.ejected:
                return False
            t.ejected = True
            t.eject_reason = reason
            return True

    def admit(self, url: str) -> bool:
        """Return an ejected member to rotation (adds it first if it is
        not yet a member — the rolling-swap admission path)."""
        with self._lock:
            t = self._targets.get(url)
            if t is None:
                t = self._targets[url] = _Target(url)
                return True
            changed = t.ejected
            t.ejected = False
            t.eject_reason = ""
            return changed

    def breaker_for(self, url: str) -> CircuitBreaker:
        return self.breakers.breaker_for(url)

    def _is_live(self, t: _Target) -> bool:
        return not t.ejected and \
            self.breakers.breaker_for(t.url).state != "open"

    def live(self) -> list[str]:
        with self._lock:
            targets = list(self._targets.values())
        return [t.url for t in targets if self._is_live(t)]

    # -- picking + accounting ------------------------------------------- #

    def pick(self, strategy: str = "round_robin", key: "str | None" = None,
             exclude: Sequence[str] = ()) -> "str | None":
        """One live target URL (None when the live set minus `exclude` is
        empty). `hash` strategy requires `key`."""
        with self._lock:
            targets = list(self._targets.values())
        live = [t for t in targets
                if t.url not in exclude and self._is_live(t)]
        if not live:
            return None
        if strategy == "hash" and key is not None:
            # sticky first: an established key stays home as long as its
            # replica is live, no matter how membership churns around it
            live_urls = {t.url for t in live}
            with self._lock:
                bound = self._sticky.get(key)
            if bound in live_urls:
                return bound
            ring = sorted(
                (_stable_hash(f"{t.url}#{v}"), t.url)
                for t in live for v in range(self.VNODES))
            point = _stable_hash(key)
            url = next((u for h, u in ring if h >= point), ring[0][1])
            with self._lock:
                self._sticky[key] = url
                while len(self._sticky) > self.STICKY_MAX:
                    self._sticky.pop(next(iter(self._sticky)))
            return url
        if strategy == "least_loaded":
            return min(live, key=lambda t: t.inflight).url
        # round_robin (and the hash strategy with no key)
        return live[next(self._rr) % len(live)].url

    def lease(self, url: str) -> _Lease:
        """In-flight accounting around one forwarded request — the
        least_loaded signal. Use as a context manager."""
        with self._lock:
            t = self._targets.get(url)
            if t is not None:
                t.inflight += 1
        return _Lease(self, url)

    def _release(self, url: str) -> None:
        with self._lock:
            t = self._targets.get(url)
            if t is not None and t.inflight > 0:
                t.inflight -= 1

    def inflight(self, url: str) -> int:
        with self._lock:
            t = self._targets.get(url)
            return t.inflight if t is not None else 0

    def states(self) -> dict[str, dict]:
        """The routing table: per-URL live/ejected/in-flight/breaker
        state (tools/diagnose.py prints this)."""
        with self._lock:
            targets = list(self._targets.values())
        return {t.url: {
            "live": self._is_live(t),
            "ejected": t.ejected,
            "eject_reason": t.eject_reason,
            "inflight": t.inflight,
            "breaker": self.breakers.breaker_for(t.url).state,
        } for t in targets}

    # -- sending -------------------------------------------------------- #

    @staticmethod
    def _rebase(req: HTTPRequestData, base: str) -> HTTPRequestData:
        """Point `req` at `base`, keeping its path+query: requests carry
        a path (or a full URL whose path is reused) and the pool decides
        the host."""
        path = req.url or "/"
        split = urllib.parse.urlsplit(path)
        if split.netloc:
            path = urllib.parse.urlunsplit(
                ("", "", split.path or "/", split.query, ""))
        return HTTPRequestData(
            method=req.method, url=urllib.parse.urljoin(base, path),
            headers=req.headers, entity=req.entity)

    def send(self, req: HTTPRequestData, timeout: float = 60.0,
             policy: "RetryPolicy | None" = None,
             strategy: str = "round_robin", key: "str | None" = None,
             retry_connect: bool = True,
             on_failover=None, target: "str | None" = None) -> HTTPResponseData:
        """Route one request to a picked live target. On a CONNECTION
        failure (status 0 — no HTTP answer, so resending is safe even
        mid-POST) the request is retried once against a different live
        target: a crashed replica costs a retry, not an error.
        `on_failover(url, resp)` observes the failed first attempt.

        `target` pins the request to one specific member instead of
        picking: lease accounting and the per-URL breaker still apply,
        but there is no failover — a claim/heartbeat protocol addressed
        to worker X must fail, not silently reach worker Y. The target
        must be a pool member (a directed send is still a routing
        decision, so membership is the authority); an unknown or
        ejected target answers 503 without a network attempt."""
        if target is not None:
            with self._lock:
                t = self._targets.get(target)
            if t is None or not self._is_live(t):
                return HTTPResponseData(
                    503, "target not live", entity=None,
                    headers={"Retry-After": "1"})
            with self.lease(target):
                return http_send(self._rebase(req, target), timeout=timeout,
                                 policy=policy,
                                 breaker=self.breaker_for(target))
        tried: list[str] = []
        resp = HTTPResponseData(503, "no live targets", entity=None,
                                headers={"Retry-After": "1"})
        for _ in range(2 if retry_connect else 1):
            url = self.pick(strategy=strategy, key=key, exclude=tried)
            if url is None and tried:
                # failover found no OTHER live target: retry the failed
                # one rather than erroring a request a recovering replica
                # could still serve
                url = self.pick(strategy=strategy, key=key)
            if url is None:
                return resp
            with self.lease(url):
                resp = http_send(self._rebase(req, url), timeout=timeout,
                                 policy=policy,
                                 breaker=self.breaker_for(url))
            if resp.status_code != 0:
                return resp
            tried.append(url)
            if on_failover is not None:
                on_failover(url, resp)
        return resp


class HTTPClient:
    """Batched sender. concurrency>1 = the reference's AsyncHTTPClient
    sliding window; 1 = SingleThreadedHTTPClient.

    `urls=[...]` turns on round-robin spreading over a replica set via a
    TargetPool (per-URL breakers, connection-failure failover to another
    replica) — the client-side version of the gateway's routing, for
    callers that talk to `ServingFleet.urls` directly. Each request's
    own `url` contributes only its path."""

    def __init__(self, concurrency: int = 1, timeout: float = 60.0,
                 retries: int = 3, policy: "RetryPolicy | None" = None,
                 breaker: "CircuitBreaker | None" = None,
                 urls: "Sequence[str] | None" = None,
                 pool: "TargetPool | None" = None):
        self.concurrency = concurrency
        self.timeout = timeout
        self.retries = retries
        self.policy = policy
        self.breaker = breaker
        if pool is None and urls:
            pool = TargetPool(urls)
        self.pool = pool

    def send_all(self, reqs: Iterable[HTTPRequestData]) -> list[HTTPResponseData]:
        if self.pool is not None:
            fn = lambda r: self.pool.send(  # noqa: E731
                r, timeout=self.timeout, policy=self.policy)
        else:
            fn = lambda r: http_send(  # noqa: E731
                r, timeout=self.timeout, retries=self.retries,
                policy=self.policy, breaker=self.breaker)
        if self.concurrency <= 1:
            return [fn(r) for r in reqs]
        return list(buffered_map(fn, list(reqs), self.concurrency))
