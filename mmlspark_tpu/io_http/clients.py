"""HTTP clients: retry/backoff + concurrency.

Reference: src/io/http/src/main/scala/HTTPClients.scala:19-151 — retry with
exponential backoff and 429 Retry-After handling (:64-105),
`SingleThreadedHTTPClient` and `AsyncHTTPClient` (sliding window of Futures,
Clients.scala:102-116 + AsyncUtils.bufferedAwait). Here: urllib on threads;
the async window is utils.async_utils.buffered_map.
"""

from __future__ import annotations

import time
import urllib.error
import urllib.request
from typing import Iterable, Sequence

from ..utils.async_utils import buffered_map
from .schema import HTTPRequestData, HTTPResponseData

__all__ = ["http_send", "HTTPClient"]


def http_send(
    req: HTTPRequestData,
    timeout: float = 60.0,
    retries: int = 3,
    backoff_ms: Sequence[int] = (100, 500, 1000),
) -> HTTPResponseData:
    """One request with the reference's retry semantics
    (HTTPClients.scala:64-105): retry on 429/5xx/connection errors, honor
    Retry-After, exponential-ish backoff list."""
    last_exc: Exception | None = None
    for attempt in range(max(retries, 1)):
        try:
            r = urllib.request.Request(
                req.url, data=req.entity, headers=req.headers,
                method=req.method,
            )
            with urllib.request.urlopen(r, timeout=timeout) as resp:
                return HTTPResponseData(
                    status_code=resp.status,
                    reason=getattr(resp, "reason", "") or "",
                    headers=dict(resp.headers),
                    entity=resp.read(),
                )
        except urllib.error.HTTPError as e:
            body = e.read()
            if e.code == 429 or 500 <= e.code < 600:
                retry_after = e.headers.get("Retry-After")
                if attempt + 1 < retries:
                    if retry_after is not None:
                        try:
                            time.sleep(float(retry_after))
                        except ValueError:
                            pass
                    else:
                        time.sleep(backoff_ms[min(attempt, len(backoff_ms) - 1)] / 1e3)
                    continue
            return HTTPResponseData(
                status_code=e.code, reason=str(e.reason),
                headers=dict(e.headers), entity=body,
            )
        except Exception as e:  # noqa: BLE001 — connection-level retry
            last_exc = e
            if attempt + 1 < retries:
                time.sleep(backoff_ms[min(attempt, len(backoff_ms) - 1)] / 1e3)
                continue
    return HTTPResponseData(status_code=0, reason=str(last_exc), entity=None)


class HTTPClient:
    """Batched sender. concurrency>1 = the reference's AsyncHTTPClient
    sliding window; 1 = SingleThreadedHTTPClient."""

    def __init__(self, concurrency: int = 1, timeout: float = 60.0,
                 retries: int = 3):
        self.concurrency = concurrency
        self.timeout = timeout
        self.retries = retries

    def send_all(self, reqs: Iterable[HTTPRequestData]) -> list[HTTPResponseData]:
        fn = lambda r: http_send(r, timeout=self.timeout, retries=self.retries)  # noqa: E731
        if self.concurrency <= 1:
            return [fn(r) for r in reqs]
        return list(buffered_map(fn, list(reqs), self.concurrency))
