"""HTTP on Tables + Serving.

Reference modules replaced: src/io/http/ — the client stack (HTTPSchema,
HTTPTransformer, SimpleHTTPTransformer, parsers, retrying clients,
batchers), Spark Serving (HTTPSource/DistributedHTTPSource/HTTPSourceV2
continuous serving), PartitionConsolidator, PowerBIWriter, and the
Cognitive-Services-style typed REST stages.
"""

from .schema import (
    HTTPRequestData,
    HTTPResponseData,
    parse_request,
    make_reply,
)
from .clients import http_send, HTTPClient, TargetPool
from .transformer import (
    HTTPTransformer,
    DistributedHTTPTransformer,
    SimpleHTTPTransformer,
    JSONInputParser,
    JSONOutputParser,
    StringOutputParser,
    CustomInputParser,
    CustomOutputParser,
)
from .forwarding import ForwardingOptions, PortForward, establish_forward
from .journal import ServingJournal
from .serving import MicroBatchQuery, ServingFleet, ServingServer, serve_model
from .gateway import ServingGateway
from .autoscale import FleetAutoscaler
from .consolidator import PartitionConsolidator
from .powerbi import PowerBIWriter
from .cognitive import (
    CognitiveServiceBase,
    TextSentiment,
    LanguageDetector,
    EntityDetector,
    KeyPhraseExtractor,
    NER,
    OCR,
    RecognizeText,
    RecognizeDomainSpecificContent,
    GenerateThumbnails,
    TagImage,
    DescribeImage,
    AnalyzeImage,
    DetectFace,
    FindSimilarFace,
    GroupFaces,
    IdentifyFaces,
    VerifyFaces,
    BingImageSearch,
)
from .search import AzureSearchWriter

__all__ = [
    "HTTPRequestData",
    "HTTPResponseData",
    "parse_request",
    "make_reply",
    "http_send",
    "HTTPClient",
    "TargetPool",
    "HTTPTransformer",
    "DistributedHTTPTransformer",
    "SimpleHTTPTransformer",
    "JSONInputParser",
    "JSONOutputParser",
    "StringOutputParser",
    "CustomInputParser",
    "CustomOutputParser",
    "MicroBatchQuery",
    "ServingJournal",
    "ServingFleet",
    "ServingServer",
    "ServingGateway",
    "FleetAutoscaler",
    "serve_model",
    "PartitionConsolidator",
    "PowerBIWriter",
    "CognitiveServiceBase",
    "TextSentiment",
    "LanguageDetector",
    "EntityDetector",
    "KeyPhraseExtractor",
    "NER",
    "OCR",
    "RecognizeText",
    "RecognizeDomainSpecificContent",
    "GenerateThumbnails",
    "TagImage",
    "DescribeImage",
    "AnalyzeImage",
    "DetectFace",
    "FindSimilarFace",
    "GroupFaces",
    "IdentifyFaces",
    "VerifyFaces",
    "BingImageSearch",
    "AzureSearchWriter",
]
