"""PowerBIWriter — push Table rows to a PowerBI streaming dataset URL.

Reference: src/io/powerbi/src/main/scala/PowerBIWriter.scala:25-112 — batch
`write` (:98) and streaming `stream` (:94) both POST JSON row arrays through
an HTTPTransformer."""

from __future__ import annotations

import json
from typing import Any, Iterable

import numpy as np

from ..core.schema import Table
from .clients import HTTPClient
from .schema import HTTPRequestData

__all__ = ["PowerBIWriter"]


class PowerBIWriter:
    @staticmethod
    def write(table: Table, url: str, batch_size: int = 100,
              concurrency: int = 1, client: HTTPClient | None = None) -> int:
        """POST rows as JSON arrays in batches; returns request count.
        (PowerBIWriter.write, PowerBIWriter.scala:98-107)."""
        rows = []
        for row in table.rows():
            clean = {}
            for k, v in row.items():
                if isinstance(v, np.generic):
                    v = v.item()
                elif isinstance(v, np.ndarray):
                    v = v.tolist()
                elif isinstance(v, bytes):
                    continue
                clean[k] = v
            rows.append(clean)
        reqs = [
            HTTPRequestData.from_json(url, rows[i : i + batch_size])
            for i in range(0, len(rows), batch_size)
        ]
        client = client or HTTPClient(concurrency=concurrency)
        resps = client.send_all(reqs)
        bad = [r for r in resps if not r.ok]
        if bad:
            raise IOError(
                f"PowerBI write: {len(bad)}/{len(resps)} batches failed "
                f"(first: {bad[0].status_code} {bad[0].reason})"
            )
        return len(reqs)

    @staticmethod
    def stream(tables: Iterable[Table], url: str, **kw) -> int:
        """Streaming variant: one write per incoming micro-batch table
        (PowerBIWriter.stream, PowerBIWriter.scala:94)."""
        n = 0
        for t in tables:
            n += PowerBIWriter.write(t, url, **kw)
        return n
