"""ServingGateway: the routing brain in front of a ServingFleet.

Reference: Spark Serving's distributed mode puts a LOAD BALANCER in front
of the per-executor servers (SURVEY.md §3.4, HTTPSourceV2's routing table
keyed by ServiceInfo) — the reference leaves the balancer to the cloud;
here it is a first-class, chaos-tested component:

  * routes each POST to a live replica — least-loaded by in-flight count
    by default, or consistent-hash on a routing-key header so stateful
    handlers keep session affinity
  * spreads through io_http.clients.TargetPool (per-replica circuit
    breakers + manual eject/admit), so the gateway and direct
    `HTTPClient(urls=...)` callers share ONE tested failover primitive
  * a replica crash costs a RETRY, not an error: a connection failure
    (status 0 — no HTTP answer was produced, so resending is safe)
    hedges once against a different replica and ejects the dead one
  * `probe_all()` ejects replicas whose /readyz fails (or whose breaker
    is open) and re-admits them after probe success; wire it to a clock
    loop with `start_probing()` or call it directly from tests
  * tracks `ServingFleet` membership live via `attach_fleet` (scale-ups,
    respawns and rolling swaps admit/eject atomically at the pool)
  * optional `checkpoint_dir` journals every accept/reply at the gateway
    (io_http.journal exactly-once semantics), so a mid-soak crash can
    neither lose nor double-answer a journaled request

Everything waits through the injectable clock; chaos tests drive the
whole ejection/re-admission cycle on a FakeClock with zero real sleeps.
"""

from __future__ import annotations

import itertools
import json
import multiprocessing
import os
import socket
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Any, Callable

from ..observability.sanitizer import make_lock
from ..resilience.policy import RetryPolicy, SYSTEM_CLOCK
from .clients import TargetPool
from .schema import HTTPRequestData, HTTPResponseData
from .serving import SingleSegmentHandler

__all__ = ["ServingGateway", "GatewayTier"]

_GW_SEQ = itertools.count()

# hop-by-hop headers never forwarded either direction (RFC 9110 §7.6.1)
_HOP_HEADERS = frozenset((
    "connection", "keep-alive", "proxy-authenticate", "proxy-authorization",
    "te", "trailer", "transfer-encoding", "upgrade", "host",
    "content-length",
))


class ServingGateway:
    """HTTP front that routes to the live replicas of a serving fleet.

    `urls` seeds the routing pool; `attach_fleet(fleet)` keeps it in sync
    with a live `ServingFleet`. `routing_key_header` (default
    `x-routing-key`) switches a request to consistent-hash routing.
    """

    def __init__(
        self,
        urls=(),
        host: str = "127.0.0.1",
        port: int = 0,
        strategy: str = "least_loaded",
        routing_key_header: str = "x-routing-key",
        timeout_s: float = 30.0,
        hedge: bool = True,
        checkpoint_dir: "str | None" = None,
        clock: Any = None,
        metrics: Any = None,
        policy: "RetryPolicy | None" = None,
        pool: "TargetPool | None" = None,
        probe_timeout_s: float = 2.0,
        exemplars: bool = True,
        flight_recorder_dir: "str | None" = None,
        recorder: Any = None,
        timeline_dir: "str | None" = None,
        timeline_interval_s: float = 5.0,
        reuse_port: bool = False,
        worker_label: "str | None" = None,
        **breaker_kw,
    ):
        if strategy not in ("least_loaded", "round_robin", "hash"):
            raise ValueError(f"unknown routing strategy {strategy!r}")
        self.host, self.port = host, port
        # gateway-tier membership: reuse_port binds the listener with
        # SO_REUSEPORT so N worker processes share ONE port (the kernel
        # balances accepted connections across them); worker_label tags
        # this process's requests in the per-worker counter
        self.reuse_port = bool(reuse_port)
        self.worker_label = worker_label
        self.strategy = strategy
        self.routing_key_header = routing_key_header.lower()
        self.timeout_s = timeout_s
        # hedge=False turns off the connection-failure retry for callers
        # whose requests are NOT idempotent (side-effecting handlers)
        self.hedge = hedge
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.probe_timeout_s = probe_timeout_s
        self.pool = pool if pool is not None else TargetPool(
            urls, clock=self.clock, **breaker_kw)
        if pool is not None:
            for u in urls:
                self.pool.add(u)
        # forwarding does NOT retry in-place (no backoff sleeps on the
        # gateway thread): retryable failures surface immediately and the
        # hedge/breaker layer decides what happens next
        self.policy = policy if policy is not None else RetryPolicy(
            max_retries=0, clock=self.clock)
        # exactly-once accept/reply journal at the gateway boundary
        self.journal = None
        self._id_counter = itertools.count()
        if checkpoint_dir is not None:
            from .journal import ServingJournal

            self.journal = ServingJournal(checkpoint_dir)
            self._id_counter = itertools.count(self.journal.max_id() + 1)
        self._server: ThreadingHTTPServer | None = None
        self._probe_thread: "threading.Thread | None" = None
        self._stop = threading.Event()
        self._fleet = None
        self.autoscaler = None
        self.exemplars = bool(exemplars)
        self._init_metrics(metrics)
        # black-box flight recorder: admit/eject transitions and routed
        # requests land in the ring; `flight_recorder_dir` arms triggered
        # dumps (SLO burn via the driver, drain on stop())
        if recorder is None and flight_recorder_dir:
            from ..observability.recorder import FlightRecorder

            recorder = FlightRecorder(dump_dir=flight_recorder_dir,
                                      process=f"gateway-{self.server_label}")
        self.recorder = recorder
        # opt-in metrics history: the gateway samples its OWN registry
        # (routing counters, inflight, latency) into segment files so
        # `diagnose.py --history` can replay a routing incident
        self.timeline = None
        if timeline_dir is not None:
            from ..observability.timeline import TimelineRecorder

            self.timeline = TimelineRecorder(
                timeline_dir, self.metrics, clock=self.clock,
                interval_s=timeline_interval_s, recorder=recorder)

    # -- metrics -------------------------------------------------------- #

    def _init_metrics(self, metrics) -> None:
        from ..observability.metrics import get_registry

        self.metrics = metrics if metrics is not None else get_registry()
        self.server_label = f"gw{next(_GW_SEQ)}"
        lbl = {"server": self.server_label}
        self._c_requests = self.metrics.counter(
            "mmlspark_tpu_gateway_requests_total",
            "requests routed through the gateway, by outcome",
            labels=("server", "outcome"))
        self._c_hedges = self.metrics.counter(
            "mmlspark_tpu_gateway_hedged_retries_total",
            "connection-failed requests retried on another replica",
            labels=("server",)).labels(**lbl)
        self._c_ejections = self.metrics.counter(
            "mmlspark_tpu_gateway_ejections_total",
            "replicas taken out of rotation, by reason",
            labels=("server", "reason"))
        self._c_admissions = self.metrics.counter(
            "mmlspark_tpu_gateway_admissions_total",
            "replicas (re)admitted into rotation",
            labels=("server",)).labels(**lbl)
        # which routing strategy placed each request — "hash" counts the
        # sticky (x-routing-key) traffic, e.g. SAR consistent-hash-by-user,
        # separately from the default strategy's
        self._c_routed = self.metrics.counter(
            "mmlspark_tpu_gateway_routed_total",
            "requests placed on a replica, by routing strategy",
            labels=("server", "strategy"))
        self._g_live = self.metrics.gauge(
            "mmlspark_tpu_gateway_replicas_live_count",
            "replicas currently in rotation",
            labels=("server",)).labels(**lbl)
        self._g_live_ratio = self.metrics.gauge(
            "mmlspark_tpu_gateway_live_replicas_ratio",
            "live replicas / known replicas (1.0 = fully healthy)",
            labels=("server",)).labels(**lbl)
        self._g_inflight = self.metrics.gauge(
            "mmlspark_tpu_gateway_inflight_depth",
            "requests currently forwarded and awaiting a replica reply",
            labels=("server",)).labels(**lbl)
        self._h_latency = self.metrics.histogram(
            "mmlspark_tpu_gateway_latency_seconds",
            "gateway latency, request read to reply written",
            labels=("server",), exemplars=self.exemplars).labels(**lbl)
        # tier accounting: each worker process counts its own requests
        # under its worker label, so a scrape across the tier shows the
        # kernel's SO_REUSEPORT balance directly
        self._c_worker = None
        if self.worker_label is not None:
            self._c_worker = self.metrics.counter(
                "mmlspark_tpu_gateway_worker_requests_total",
                "requests handled per gateway-tier worker process",
                labels=("worker",)).labels(worker=self.worker_label)
        self._update_pool_gauges()

    def _update_pool_gauges(self) -> None:
        states = self.pool.states()
        live = sum(1 for s in states.values() if s["live"])
        self._g_live.set(live)
        self._g_live_ratio.set(live / len(states) if states else 0.0)
        self._g_inflight.set(
            sum(s["inflight"] for s in states.values()))

    def _recorder(self):
        """The gateway's flight recorder, or the process default (armed
        but dumping nowhere until someone configures a dump_dir)."""
        if self.recorder is not None:
            return self.recorder
        from ..observability.recorder import get_recorder

        return get_recorder()

    # -- membership ----------------------------------------------------- #

    def admit(self, url: str) -> None:
        """Put `url` into rotation (atomic at the pool: the next pick
        already sees it). Counted even when already admitted — rolling
        swap uses the admission stream as its audit trail."""
        self.pool.admit(url)
        self._c_admissions.inc()
        self._recorder().record_transition("gateway", "admit", url=url)
        self._update_pool_gauges()

    def eject(self, url: str, reason: str = "manual") -> None:
        if self.pool.eject(url, reason):
            self._c_ejections.labels(
                server=self.server_label, reason=reason).inc()
            self._recorder().record_transition("gateway", "eject", url=url,
                                               reason=reason)
        self._update_pool_gauges()

    def remove(self, url: str) -> None:
        """Forget `url` entirely (a retired/dead replica, not a sick one)."""
        self.pool.remove(url)
        self._update_pool_gauges()

    def attach_fleet(self, fleet) -> "ServingGateway":
        """Track a ServingFleet's membership: current `urls` seed the
        pool, later scale/respawn/swap events admit/remove live."""
        self._fleet = fleet
        for u in fleet.urls:
            self.admit(u)

        def _on_change(event: str, url: str) -> None:
            if event == "added":
                self.admit(url)
            elif event == "removed":
                self.remove(url)

        fleet.watch(_on_change)
        return self

    def attach_autoscaler(self, autoscaler) -> "ServingGateway":
        """Expose an autoscaler's state under GET /autoscaler (the
        diagnose snapshot reads it alongside /routes)."""
        self.autoscaler = autoscaler
        return self

    # -- probing -------------------------------------------------------- #

    def _probe(self, url: str) -> bool:
        """One replica's /readyz — True = ready. Connection failures and
        non-200s both count as not ready."""
        import http.client
        import urllib.parse

        u = urllib.parse.urlsplit(url)
        conn = None
        try:
            conn = http.client.HTTPConnection(
                u.hostname, u.port, timeout=self.probe_timeout_s)
            conn.request("GET", "/readyz")
            r = conn.getresponse()
            r.read()
            return r.status == 200
        except (OSError, http.client.HTTPException):
            return False
        finally:
            if conn is not None:
                conn.close()

    def probe_all(self) -> dict[str, bool]:
        """Probe every known replica: eject the not-ready (and the
        breaker-open), re-admit ejected replicas whose probe succeeds.
        Returns {url: ready}. Chaos tests call this directly; production
        wires it to a clock loop via start_probing()."""
        results: dict[str, bool] = {}
        for url, st in self.pool.states().items():
            ready = self._probe(url)
            results[url] = ready
            if not ready and not st["ejected"]:
                # a breaker-open replica is already out of rotation; the
                # explicit ejection keeps /routes' audit trail honest
                # about WHY it is out
                reason = "breaker" if st["breaker"] == "open" else "readyz"
                self.eject(url, reason=reason)
            elif ready and st["ejected"]:
                self.admit(url)
        self._update_pool_gauges()
        return results

    def start_probing(self, interval_s: float = 1.0) -> None:
        """Background probe loop on the injectable clock."""
        def _loop():
            while not self._stop.is_set():
                try:
                    self.probe_all()
                except Exception:  # noqa: BLE001 — probing must not die
                    pass
                self.clock.sleep(interval_s)

        self._probe_thread = threading.Thread(target=_loop, daemon=True)
        self._probe_thread.start()

    # -- forwarding ----------------------------------------------------- #

    def forward(self, req: HTTPRequestData,
                key: "str | None" = None) -> HTTPResponseData:
        """Route one request: pick a live replica (hash when `key` is
        given), forward, hedge once on connection failure. A request no
        live replica could take answers 503; both attempts dying on
        connection errors answers 502."""
        strategy = "hash" if key is not None else self.strategy
        self._c_routed.labels(server=self.server_label,
                              strategy=strategy).inc()

        def _on_failover(url: str, _resp) -> None:
            self._c_hedges.inc()
            self.eject(url, reason="connect")

        resp = self.pool.send(
            req, timeout=self.timeout_s, policy=self.policy,
            strategy=strategy, key=key, retry_connect=self.hedge,
            on_failover=_on_failover)
        if resp.status_code == 0:
            # every attempt died at the connection level: the client gets
            # a real HTTP answer (502), never a dropped socket
            resp = HTTPResponseData(
                502, f"no replica reachable: {resp.reason}",
                headers={"Retry-After": "1"}, entity=None)
        return resp

    # -- HTTP surface --------------------------------------------------- #

    def routes(self) -> dict:
        """The routing table: per-replica pool state + strategy — what
        GET /routes serves and tools/diagnose.py prints."""
        states = self.pool.states()
        return {
            "strategy": self.strategy,
            "routing_key_header": self.routing_key_header,
            "hedge": self.hedge,
            "n_targets": len(states),
            "n_live": sum(1 for s in states.values() if s["live"]),
            "strategy_requests": {
                vals[1]: int(c.value)
                for vals, c in self._c_routed.children()
                if vals[0] == self.server_label},
            "targets": states,
        }

    def start(self) -> "ServingGateway":
        outer = self

        class Handler(SingleSegmentHandler):
            protocol_version = "HTTP/1.1"
            timeout = 5.0
            body_timeout = 60.0

            def do_POST(self):  # noqa: N802 — http.server API
                self.connection.settimeout(self.body_timeout)
                try:
                    self._handle_post()
                finally:
                    self.connection.settimeout(self.timeout)

            def _handle_post(self):
                if self.headers.get("Transfer-Encoding"):
                    self.send_response(411)
                    self.send_header("Content-Length", "0")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.close_connection = True
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                t0 = time.perf_counter()
                headers = {k: v for k, v in self.headers.items()
                           if k.lower() not in _HOP_HEADERS}
                key = self.headers.get(outer.routing_key_header)
                req = HTTPRequestData("POST", self.path, headers, body)
                ex_id = None
                if outer.journal is not None:
                    ex_id = str(next(outer._id_counter))
                    outer.journal.record_accept(ex_id, req)
                # parent the forward on the caller's trace so the merged
                # fleet trace reads client -> gateway -> replica
                from ..observability.tracing import get_tracer

                tracer = get_tracer()
                remote = tracer.extract(self.headers.get("traceparent"))
                with tracer.start_span("gateway.request", parent=remote,
                                       path=self.path,
                                       server=outer.server_label) as span:
                    resp = outer.forward(req, key=key)
                if outer.journal is not None:
                    outer.journal.record_reply(ex_id, resp)
                status = resp.status_code or 500
                outcome = ("ok" if 200 <= status < 400 else
                           "unrouted" if status in (502, 503) else "error")
                outer._c_requests.labels(server=outer.server_label,
                                         outcome=outcome).inc()
                if outer._c_worker is not None:
                    outer._c_worker.inc()
                self.send_response(status)
                entity = resp.entity or b""
                for k, v in (resp.headers or {}).items():
                    if k.lower() not in _HOP_HEADERS:
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(entity)))
                self.end_headers()
                if entity:
                    self.wfile.write(entity)
                elapsed = time.perf_counter() - t0
                trace_id = getattr(span, "trace_id", 0)
                tid = format(trace_id, "032x") if trace_id else ""
                ex = ({"trace_id": tid, "route": "gateway"}
                      if outer.exemplars and tid else None)
                outer._h_latency.observe(elapsed, exemplar=ex)
                rec = outer._recorder()
                rec.record_request(trace_id=tid, route="gateway",
                                   queue_depth=outer.routes()["n_live"],
                                   latency_s=elapsed, status=status,
                                   outcome=outcome)
                rec.maybe_tick(outer.metrics)
                outer._update_pool_gauges()

            def _reply_json(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    payload = outer.metrics.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if path == "/routes":
                    self._reply_json(200, outer.routes())
                    return
                if path == "/autoscaler":
                    if outer.autoscaler is None:
                        self._reply_json(404, {"error": "no autoscaler"})
                    else:
                        self._reply_json(200, outer.autoscaler.state())
                    return
                if path == "/healthz":
                    self._reply_json(200, {
                        "status": "ok", "routes": outer.routes()["n_live"]})
                    return
                if path == "/readyz":
                    n_live = outer.routes()["n_live"]
                    self._reply_json(200 if n_live else 503,
                                     {"ready": n_live > 0,
                                      "n_live": n_live})
                    return
                self._reply_json(404, {"error": "unknown path"})

            def log_message(self, *a):
                pass

        server_cls = _ReusePortServer if self.reuse_port \
            else ThreadingHTTPServer
        self._server = server_cls((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        if self.timeline is not None:
            self.timeline.start()
        return self

    def worker_stats(self) -> dict:
        """This process's tier-worker snapshot (GatewayTier aggregates
        one per worker into the /workers table)."""
        states = self.pool.states()
        return {
            "worker": self.worker_label,
            "pid": os.getpid(),
            "port": self.port,
            "requests": (int(self._c_worker.value)
                         if self._c_worker is not None else 0),
            "outcomes": {vals[1]: int(c.value)
                         for vals, c in self._c_requests.children()
                         if vals[0] == self.server_label},
            "n_live": sum(1 for s in states.values() if s["live"]),
        }

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"

    def stop(self) -> None:
        self._stop.set()
        if self.timeline is not None:
            try:
                self.timeline.sample()       # the shutdown-edge sample
            except Exception:  # noqa: BLE001 — telemetry stays optional
                pass
            self.timeline.stop()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self.journal is not None:
            self.journal.close()
        if self.recorder is not None:
            try:
                self.recorder.trigger_dump("drain", force=True)
            except Exception:
                pass


class _ReusePortServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that joins an SO_REUSEPORT listener group:
    every gateway-tier worker binds the SAME (host, port) and the kernel
    load-balances accepted connections across the listening sockets —
    no user-space distributor process on the data path."""

    def server_bind(self):
        if not hasattr(socket, "SO_REUSEPORT"):  # pragma: no cover
            raise OSError("SO_REUSEPORT is not available on this platform")
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


def _gateway_tier_worker(conn, index: int, host: str, port: int,
                         urls, checkpoint_dir, gateway_kw) -> None:
    """Tier-worker process entry: one full ServingGateway bound into the
    shared-port listener group, driven by the parent over a pipe
    (membership broadcasts, stats polls, graceful stop)."""
    import signal

    gw = ServingGateway(
        urls=urls, host=host, port=port, reuse_port=True,
        worker_label=f"w{index}", checkpoint_dir=checkpoint_dir,
        **gateway_kw).start()
    # a SIGTERM'd (or SIGKILL'd) worker exits without ceremony: the
    # journal shard is append-only with torn-tail recovery, so the
    # respawned worker replays exactly-once state from disk
    signal.signal(signal.SIGTERM, lambda *_: os._exit(0))
    conn.send(("ready", gw.port, os.getpid()))
    try:
        while True:
            try:
                cmd = conn.recv()
            except (EOFError, OSError):
                break
            op = cmd[0]
            if op == "stop":
                break
            if op == "admit":
                gw.admit(cmd[1])
            elif op == "remove":
                gw.remove(cmd[1])
            elif op == "stats":
                conn.send(gw.worker_stats())
    finally:
        gw.stop()
        conn.close()


class GatewayTier:
    """N gateway worker PROCESSES sharing one port via SO_REUSEPORT —
    the multi-process front tier a single-process gateway caps out on.

    * the parent reserves the shared port with a bound-but-never-
      listening SO_REUSEPORT placeholder socket (held for the tier's
      lifetime, so the port cannot be stolen between worker restarts);
      only LISTENING sockets join the kernel's balance group, so the
      placeholder never receives a connection
    * each worker is a full `ServingGateway` (same TargetPool breakers,
      hedging, consistent-hash stickiness — the blake2b ring is
      deterministic, so every worker maps a routing key to the SAME
      replica with no cross-process coordination)
    * fleet membership propagates through the watch protocol: the parent
      subscribes once via `attach_fleet` and broadcasts admit/remove to
      every worker pipe
    * the accept/reply journal shards per worker
      (`checkpoint_dir/worker-N`): any single worker's death loses
      nothing — its shard replays on respawn, and no two workers ever
      contend on one journal file
    * `kill_worker`/`respawn_worker` are the chaos hooks the bench's
      kill-window drill drives; a killed worker's in-flight connections
      reset, which clients absorb with a status-0-safe resend
    * a small control server (`control_url`, parent process) serves
      GET /workers for `diagnose.py --gateway` — the shared data port
      deliberately serves ONLY gateway traffic
    """

    def __init__(self, urls=(), n_workers: int = 2,
                 host: str = "127.0.0.1", port: int = 0,
                 checkpoint_dir: "str | None" = None,
                 start_timeout_s: float = 30.0,
                 **gateway_kw):
        if n_workers < 1:
            raise ValueError("a gateway tier needs at least one worker")
        self.host = host
        self.port = port
        self.n_workers = int(n_workers)
        self.checkpoint_dir = checkpoint_dir
        self.start_timeout_s = start_timeout_s
        # everything here crosses the spawn boundary — keep it picklable
        # (no live metrics registries / recorders; workers build their own)
        self.gateway_kw = dict(gateway_kw)
        self._members: "list[str]" = list(urls)
        self._ctx = multiprocessing.get_context("spawn")
        self._procs: "list[Any]" = [None] * self.n_workers
        self._pipes: "list[Any]" = [None] * self.n_workers
        self._pids: "list[int | None]" = [None] * self.n_workers
        # one lock per worker pipe: stats polls and membership broadcasts
        # interleave from different threads but each pipe is half-duplex
        self._pipe_locks = [make_lock(f"GatewayTier.pipe{i}")
                            for i in range(self.n_workers)]
        self._reserve: "socket.socket | None" = None
        self._control: "ThreadingHTTPServer | None" = None
        self._fleet = None

    # -- lifecycle ------------------------------------------------------ #

    def _shard_dir(self, index: int) -> "str | None":
        if self.checkpoint_dir is None:
            return None
        return os.path.join(self.checkpoint_dir, f"worker-{index}")

    def _spawn(self, index: int) -> None:
        parent_conn, child_conn = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=_gateway_tier_worker,
            args=(child_conn, index, self.host, self.port,
                  list(self._members), self._shard_dir(index),
                  self.gateway_kw),
            daemon=True)
        proc.start()
        child_conn.close()
        if not parent_conn.poll(self.start_timeout_s):
            proc.kill()
            raise TimeoutError(f"gateway worker {index} failed to start")
        msg = parent_conn.recv()
        if msg[0] != "ready" or msg[1] != self.port:
            proc.kill()
            raise RuntimeError(f"gateway worker {index} bad handshake: {msg}")
        self._procs[index] = proc
        self._pipes[index] = parent_conn
        self._pids[index] = msg[2]

    def start(self) -> "GatewayTier":
        if not hasattr(socket, "SO_REUSEPORT"):  # pragma: no cover
            raise OSError("GatewayTier requires SO_REUSEPORT")
        # reserve the shared port BEFORE any worker exists: bound with
        # SO_REUSEPORT (so workers can join) but never listen()ed (so the
        # kernel never routes a connection here)
        self._reserve = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._reserve.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._reserve.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._reserve.bind((self.host, self.port))
        self.port = self._reserve.getsockname()[1]
        for i in range(self.n_workers):
            self._spawn(i)
        self._start_control()
        return self

    # -- membership ----------------------------------------------------- #

    def _command(self, index: int, cmd: tuple, reply: bool = False):
        pipe = self._pipes[index]
        proc = self._procs[index]
        if pipe is None or proc is None or not proc.is_alive():
            return None
        with self._pipe_locks[index]:
            try:
                pipe.send(cmd)
                if reply:
                    if not pipe.poll(self.start_timeout_s):
                        return None
                    return pipe.recv()
            except (BrokenPipeError, EOFError, OSError):
                return None
        return None

    def _broadcast(self, cmd: tuple) -> None:
        for i in range(self.n_workers):
            self._command(i, cmd)

    def admit(self, url: str) -> None:
        if url not in self._members:
            self._members.append(url)
        self._broadcast(("admit", url))

    def remove(self, url: str) -> None:
        if url in self._members:
            self._members.remove(url)
        self._broadcast(("remove", url))

    def attach_fleet(self, fleet) -> "GatewayTier":
        """Track a ServingFleet: seed every worker with the current
        membership, then forward watch events to all worker pipes."""
        self._fleet = fleet
        for u in fleet.urls:
            self.admit(u)

        def _on_change(event: str, url: str) -> None:
            if event == "added":
                self.admit(url)
            elif event == "removed":
                self.remove(url)

        fleet.watch(_on_change)
        return self

    # -- chaos hooks ---------------------------------------------------- #

    def kill_worker(self, index: int) -> None:
        """SIGKILL one worker — the kill-window drill. The shared port
        keeps serving through the surviving listeners immediately."""
        proc = self._procs[index]
        if proc is not None and proc.is_alive():
            proc.kill()
            proc.join(timeout=5)

    def respawn_worker(self, index: int) -> None:
        """Refill a dead worker slot: same index, same journal shard —
        the new process replays the shard's exactly-once state."""
        proc = self._procs[index]
        if proc is not None and proc.is_alive():
            raise RuntimeError(f"worker {index} is still alive")
        pipe = self._pipes[index]
        if pipe is not None:
            pipe.close()
        self._spawn(index)

    # -- observability -------------------------------------------------- #

    def workers(self) -> "list[dict]":
        """One row per worker slot: alive + the worker's own counters
        (None stats for a dead worker — the row still shows the death)."""
        rows = []
        for i in range(self.n_workers):
            proc = self._procs[i]
            alive = bool(proc is not None and proc.is_alive())
            stats = self._command(i, ("stats",), reply=True) if alive \
                else None
            rows.append({
                "index": i, "alive": alive, "pid": self._pids[i],
                "journal_shard": self._shard_dir(i),
                "stats": stats,
            })
        return rows

    def _start_control(self) -> None:
        outer = self

        class Control(SingleSegmentHandler):
            protocol_version = "HTTP/1.1"

            def _reply_json(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?", 1)[0]
                if path == "/workers":
                    self._reply_json(200, {
                        "tier": True, "host": outer.host,
                        "port": outer.port,
                        "n_workers": outer.n_workers,
                        "members": list(outer._members),
                        "workers": outer.workers(),
                    })
                    return
                if path == "/healthz":
                    alive = sum(1 for p in outer._procs
                                if p is not None and p.is_alive())
                    self._reply_json(200 if alive else 503, {
                        "status": "ok" if alive else "dead",
                        "alive": alive, "n_workers": outer.n_workers})
                    return
                self._reply_json(404, {"error": "unknown path"})

            def log_message(self, *a):
                pass

        self._control = ThreadingHTTPServer((self.host, 0), Control)
        threading.Thread(target=self._control.serve_forever,
                         daemon=True).start()

    @property
    def url(self) -> str:
        """The shared data port every client targets."""
        return f"http://{self.host}:{self.port}/"

    @property
    def control_url(self) -> str:
        """The parent's control endpoint (GET /workers) for diagnose."""
        assert self._control is not None, "tier not started"
        return f"http://{self.host}:{self._control.server_address[1]}/"

    def stop(self) -> None:
        for i in range(self.n_workers):
            self._command(i, ("stop",))
        for proc in self._procs:
            if proc is not None:
                proc.join(timeout=5)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=5)
        for pipe in self._pipes:
            if pipe is not None:
                try:
                    pipe.close()
                except OSError:
                    pass
        if self._control is not None:
            self._control.shutdown()
            self._control.server_close()
            self._control = None
        if self._reserve is not None:
            self._reserve.close()
            self._reserve = None
