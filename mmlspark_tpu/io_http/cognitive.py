"""Cognitive-Services-style typed REST transformers.

Reference: src/io/http/src/main/scala/cognitive/ — `CognitiveServicesBase`
(CognitiveServiceBase.scala:247-305: builds Lambda → SimpleHTTPTransformer →
DropColumns pipeline), `ServiceParam`/`HasServiceParams` (:25-148, the
scalar-or-column params — mirrored by core.params.ServiceParam), and the
typed stages: TextAnalytics (TextAnalytics.scala:31-258), ComputerVision
(ComputerVision.scala:157-460), Face (Face.scala:19-347).

The request/response wire formats follow the reference's Azure API bodies so
a reference user's integration code ports directly; `url` points anywhere
(tests use a local fake service — live cloud endpoints are simply a
different url + subscription_key).
"""

from __future__ import annotations

import json
from typing import Any, Callable

from ..core.params import HasOutputCol, Param, ServiceParam
from ..core.pipeline import Transformer
from ..core.schema import Table
from ..core.serialize import register_stage
from .clients import HTTPClient
from .schema import HTTPRequestData, HTTPResponseData

__all__ = [
    "CognitiveServiceBase",
    "TextSentiment",
    "LanguageDetector",
    "EntityDetector",
    "KeyPhraseExtractor",
    "OCR",
    "AnalyzeImage",
    "DetectFace",
]


class CognitiveServiceBase(HasOutputCol, Transformer):
    """Shared plumbing: build one request per row from ServiceParams, send
    with retry/concurrency, parse JSON (CognitiveServiceBase.scala:247-305)."""

    url = Param(None, "service endpoint URL", ptype=str, required=True)
    subscription_key = Param(None, "api key (header)", ptype=str)
    output_col = Param("response", "parsed output column", ptype=str)
    error_col = Param(None, "error column (None = raise)", ptype=str)
    concurrency = Param(1, "in-flight requests", ptype=int)
    timeout = Param(60.0, "request timeout (s)", ptype=float)

    handler: Callable | None = None  # test hook: request -> HTTPResponseData

    # subclasses build the per-row request body
    def _row_body(self, row_vals: dict[str, Any], i: int) -> Any:
        raise NotImplementedError

    def _headers(self) -> dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.get("subscription_key"):
            h["Ocp-Apim-Subscription-Key"] = self.get("subscription_key")
        return h

    def _service_values(self, table: Table) -> dict[str, list[Any]]:
        vals = {}
        for name, p in self._params.items():
            if isinstance(p, ServiceParam):
                v = p.resolve(self, table)
                if v is not None:
                    vals[name] = v
        return vals

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        sv = self._service_values(table)
        reqs = []
        for i in range(n):
            row_vals = {k: v[i] for k, v in sv.items()}
            body = self._row_body(row_vals, i)
            reqs.append(HTTPRequestData.from_json(
                self.get("url"), body, headers=self._headers()
            ))
        if self.handler is not None:
            resps = [self.handler(r) for r in reqs]
        else:
            client = HTTPClient(concurrency=self.get("concurrency"),
                                timeout=self.get("timeout"))
            resps = client.send_all(reqs)
        parsed, errors = [], []
        for r in resps:
            if isinstance(r, HTTPResponseData) and r.ok:
                parsed.append(self._parse(r))
                errors.append(None)
            else:
                parsed.append(None)
                errors.append({"status_code": getattr(r, "status_code", 0),
                               "reason": getattr(r, "reason", "")})
        if self.get("error_col"):
            table = table.with_column(self.get("error_col"), errors)
        elif any(e is not None for e in errors):
            first = next(e for e in errors if e is not None)
            raise IOError(f"cognitive service error: {first}")
        return table.with_column(self.get("output_col"), parsed)

    def _parse(self, resp: HTTPResponseData) -> Any:
        return resp.json()


class _TextAnalyticsBase(CognitiveServiceBase):
    """documents[] body shape (TextAnalytics.scala:31-120)."""

    text = ServiceParam(None, "text to analyze (scalar or column)")
    language = ServiceParam("en", "language hint")

    def _row_body(self, row_vals, i):
        return {"documents": [{
            "id": str(i),
            "language": row_vals.get("language", "en"),
            "text": row_vals.get("text", ""),
        }]}

    def _parse(self, resp):
        docs = (resp.json() or {}).get("documents", [])
        return docs[0] if docs else None


@register_stage
class TextSentiment(_TextAnalyticsBase):
    """Reference: TextSentiment (TextAnalytics.scala:214-258). Output: the
    document's sentiment payload (score field)."""


@register_stage
class LanguageDetector(_TextAnalyticsBase):
    """Reference: LanguageDetector (TextAnalytics.scala:122-160)."""

    def _row_body(self, row_vals, i):
        return {"documents": [{"id": str(i), "text": row_vals.get("text", "")}]}


@register_stage
class EntityDetector(_TextAnalyticsBase):
    """Reference: EntityDetector (TextAnalytics.scala:162-190)."""


@register_stage
class KeyPhraseExtractor(_TextAnalyticsBase):
    """Reference: KeyPhraseExtractor (TextAnalytics.scala:192-212)."""


class _VisionBase(CognitiveServiceBase):
    """image url-or-bytes body (ComputerVision.scala:157-220)."""

    image_url = ServiceParam(None, "image URL (scalar or column)")
    image_bytes = ServiceParam(None, "raw image bytes (column)")

    def _row_body(self, row_vals, i):
        if row_vals.get("image_url"):
            return {"url": row_vals["image_url"]}
        data = row_vals.get("image_bytes")
        if data is None:
            raise ValueError("need image_url or image_bytes")
        import base64

        return {"data": base64.b64encode(bytes(data)).decode()}


@register_stage
class OCR(_VisionBase):
    """Reference: OCR (ComputerVision.scala:157-190)."""

    detect_orientation = Param(True, "detect text orientation", ptype=bool)


@register_stage
class AnalyzeImage(_VisionBase):
    """Reference: AnalyzeImage (ComputerVision.scala:300-360)."""

    visual_features = Param(["Categories"], "feature list")

    def _row_body(self, row_vals, i):
        body = _VisionBase._row_body(self, row_vals, i)
        body["visualFeatures"] = list(self.get("visual_features"))
        return body


@register_stage
class DetectFace(_VisionBase):
    """Reference: DetectFace (Face.scala:19-80)."""

    return_face_landmarks = Param(False, "include landmarks", ptype=bool)
    return_face_attributes = Param([], "attribute list")

    def _row_body(self, row_vals, i):
        body = _VisionBase._row_body(self, row_vals, i)
        body["returnFaceLandmarks"] = bool(self.get("return_face_landmarks"))
        if self.get("return_face_attributes"):
            body["returnFaceAttributes"] = ",".join(self.get("return_face_attributes"))
        return body
