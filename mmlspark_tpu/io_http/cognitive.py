"""Cognitive-Services-style typed REST transformers.

Reference: src/io/http/src/main/scala/cognitive/ — `CognitiveServicesBase`
(CognitiveServiceBase.scala:247-305: builds Lambda → SimpleHTTPTransformer →
DropColumns pipeline), `ServiceParam`/`HasServiceParams` (:25-148, the
scalar-or-column params — mirrored by core.params.ServiceParam), and the
typed stages: TextAnalytics (TextAnalytics.scala:31-258), ComputerVision
(ComputerVision.scala:157-460), Face (Face.scala:19-347).

The request/response wire formats follow the reference's Azure API bodies so
a reference user's integration code ports directly; `url` points anywhere
(tests use a local fake service — live cloud endpoints are simply a
different url + subscription_key).
"""

from __future__ import annotations

import json
from typing import Any, Callable

from ..core.params import HasOutputCol, Param, ServiceParam
from ..core.pipeline import Transformer
from ..core.schema import Table
from ..core.serialize import register_stage
from ..resilience.policy import SYSTEM_CLOCK
from .clients import HTTPClient
from .schema import HTTPRequestData, HTTPResponseData

__all__ = [
    "CognitiveServiceBase",
    "TextSentiment",
    "LanguageDetector",
    "EntityDetector",
    "KeyPhraseExtractor",
    "NER",
    "OCR",
    "RecognizeText",
    "GenerateThumbnails",
    "TagImage",
    "DescribeImage",
    "AnalyzeImage",
    "DetectFace",
    "FindSimilarFace",
    "GroupFaces",
    "IdentifyFaces",
    "VerifyFaces",
    "BingImageSearch",
]


class CognitiveServiceBase(HasOutputCol, Transformer):
    """Shared plumbing: build one request per row from ServiceParams, send
    with retry/concurrency, parse JSON (CognitiveServiceBase.scala:247-305)."""

    url = Param(None, "service endpoint URL", ptype=str, required=True)
    subscription_key = Param(None, "api key (header)", ptype=str)
    output_col = Param("response", "parsed output column", ptype=str)
    error_col = Param(None, "error column (None = raise)", ptype=str)
    concurrency = Param(1, "in-flight requests", ptype=int)
    timeout = Param(60.0, "request timeout (s)", ptype=float)
    retries = Param(3, "retry attempts (429/5xx/conn)", ptype=int)

    handler: Callable | None = None  # test hook: request -> HTTPResponseData
    # optional resilience wiring (runtime attrs, not serialized): an open
    # breaker answers synthetic 503s locally, which flow into error_col
    # (or the raise path) like any other service failure
    retry_policy = None
    breaker = None
    clock = SYSTEM_CLOCK                 # paces async-poll waits; injectable

    # subclasses build the per-row request body
    def _row_body(self, row_vals: dict[str, Any], i: int) -> Any:
        raise NotImplementedError

    def _headers(self) -> dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.get("subscription_key"):
            h["Ocp-Apim-Subscription-Key"] = self.get("subscription_key")
        return h

    def _service_values(self, table: Table) -> dict[str, list[Any]]:
        vals = {}
        for name, p in self._params.items():
            if isinstance(p, ServiceParam):
                v = p.resolve(self, table)
                if v is not None:
                    vals[name] = v
        return vals

    def _row_request(self, row_vals: dict[str, Any], i: int) -> HTTPRequestData:
        """Default: POST the JSON body; GET-style stages override."""
        return HTTPRequestData.from_json(
            self.get("url"), self._row_body(row_vals, i), headers=self._headers()
        )

    def _guarded_handler(self, req: HTTPRequestData) -> HTTPResponseData:
        """The handler hook routed through the breaker, mirroring what
        http_send does for real traffic: open circuit answers a local 503
        (which flows to error_col), outcomes feed the rolling window."""
        from ..resilience.policy import is_retryable_status
        from .clients import _breaker_open_response

        if self.breaker is None:
            return self.handler(req)
        if not self.breaker.allow():
            return _breaker_open_response(self.breaker)
        try:
            r = self.handler(req)
        except Exception:
            self.breaker.record_failure()
            raise
        if isinstance(r, HTTPResponseData) and \
                is_retryable_status(r.status_code):
            self.breaker.record_failure()
        else:
            self.breaker.record_success()
        return r

    def _send_one(self, req: HTTPRequestData) -> HTTPResponseData:
        if self.handler is not None:
            return self._guarded_handler(req)
        from .clients import http_send

        return http_send(req, timeout=self.get("timeout"),
                         retries=self.get("retries"),
                         policy=self.retry_policy, breaker=self.breaker)

    def _exchange(self, reqs: list[HTTPRequestData]) -> list[HTTPResponseData]:
        if self.handler is not None:
            return [self._guarded_handler(r) for r in reqs]
        client = HTTPClient(concurrency=self.get("concurrency"),
                            timeout=self.get("timeout"),
                            retries=self.get("retries"),
                            policy=self.retry_policy, breaker=self.breaker)
        return client.send_all(reqs)

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        sv = self._service_values(table)
        reqs = []
        for i in range(n):
            row_vals = {k: v[i] for k, v in sv.items()}
            reqs.append(self._row_request(row_vals, i))
        resps = self._exchange(reqs)
        parsed, errors = [], []
        for r in resps:
            if isinstance(r, HTTPResponseData) and r.ok:
                parsed.append(self._parse(r))
                errors.append(None)
            else:
                parsed.append(None)
                errors.append({"status_code": getattr(r, "status_code", 0),
                               "reason": getattr(r, "reason", "")})
        if self.get("error_col"):
            table = table.with_column(self.get("error_col"), errors)
        elif any(e is not None for e in errors):
            first = next(e for e in errors if e is not None)
            raise IOError(f"cognitive service error: {first}")
        return table.with_column(self.get("output_col"), parsed)

    def _parse(self, resp: HTTPResponseData) -> Any:
        return resp.json()


class _TextAnalyticsBase(CognitiveServiceBase):
    """documents[] body shape (TextAnalytics.scala:31-120)."""

    text = ServiceParam(None, "text to analyze (scalar or column)")
    language = ServiceParam("en", "language hint")

    def _row_body(self, row_vals, i):
        return {"documents": [{
            "id": str(i),
            "language": row_vals.get("language", "en"),
            "text": row_vals.get("text", ""),
        }]}

    def _parse(self, resp):
        docs = (resp.json() or {}).get("documents", [])
        return docs[0] if docs else None


@register_stage
class TextSentiment(_TextAnalyticsBase):
    """Reference: TextSentiment (TextAnalytics.scala:214-258). Output: the
    document's sentiment payload (score field)."""


@register_stage
class LanguageDetector(_TextAnalyticsBase):
    """Reference: LanguageDetector (TextAnalytics.scala:122-160)."""

    def _row_body(self, row_vals, i):
        return {"documents": [{"id": str(i), "text": row_vals.get("text", "")}]}


@register_stage
class EntityDetector(_TextAnalyticsBase):
    """Reference: EntityDetector (TextAnalytics.scala:162-190)."""


@register_stage
class KeyPhraseExtractor(_TextAnalyticsBase):
    """Reference: KeyPhraseExtractor (TextAnalytics.scala:192-212)."""


class _VisionBase(CognitiveServiceBase):
    """image url-or-bytes body (ComputerVision.scala:157-220)."""

    image_url = ServiceParam(None, "image URL (scalar or column)")
    image_bytes = ServiceParam(None, "raw image bytes (column)")

    def _row_body(self, row_vals, i):
        if row_vals.get("image_url"):
            return {"url": row_vals["image_url"]}
        data = row_vals.get("image_bytes")
        if data is None:
            raise ValueError("need image_url or image_bytes")
        import base64

        return {"data": base64.b64encode(bytes(data)).decode()}


@register_stage
class OCR(_VisionBase):
    """Reference: OCR (ComputerVision.scala:157-190)."""

    detect_orientation = Param(True, "detect text orientation", ptype=bool)


@register_stage
class AnalyzeImage(_VisionBase):
    """Reference: AnalyzeImage (ComputerVision.scala:300-360)."""

    visual_features = Param(["Categories"], "feature list")

    def _row_body(self, row_vals, i):
        body = _VisionBase._row_body(self, row_vals, i)
        body["visualFeatures"] = list(self.get("visual_features"))
        return body


@register_stage
class DetectFace(_VisionBase):
    """Reference: DetectFace (Face.scala:19-80)."""

    return_face_landmarks = Param(False, "include landmarks", ptype=bool)
    return_face_attributes = Param([], "attribute list")

    def _row_body(self, row_vals, i):
        body = _VisionBase._row_body(self, row_vals, i)
        body["returnFaceLandmarks"] = bool(self.get("return_face_landmarks"))
        if self.get("return_face_attributes"):
            body["returnFaceAttributes"] = ",".join(self.get("return_face_attributes"))
        return body


@register_stage
class NER(_TextAnalyticsBase):
    """Named-entity recognition (reference: NER, TextAnalytics.scala:31-120).
    Output: the document payload with its `entities` list."""


class _AsyncPollBase(_VisionBase):
    """Async-poll pattern (reference RecognizeText's `FixedPollingHandler`,
    ComputerVision.scala:192-278): the initial POST answers 202 with an
    `Operation-Location` header; the result is GET-polled from there until
    status leaves "Running"/"NotStarted"."""

    # seconds-scale budget like the reference's polling handler — real async
    # recognition takes several seconds (~5 min total here before giving up)
    poll_interval_s = Param(1.0, "delay between result polls (s)", ptype=float)
    max_polls = Param(300, "poll attempts before giving up", ptype=int)

    def _poll_operation(self, resp: HTTPResponseData) -> HTTPResponseData:
        if not (isinstance(resp, HTTPResponseData) and resp.status_code == 202):
            return resp
        loc = resp.headers.get("Operation-Location") or resp.headers.get(
            "operation-location"
        )
        if not loc:
            return HTTPResponseData(502, "202 without Operation-Location")
        poll_req = HTTPRequestData(method="GET", url=loc, headers=self._headers())
        for _ in range(int(self.get("max_polls"))):
            r = self._send_one(poll_req)
            if not (isinstance(r, HTTPResponseData) and r.ok):
                return r
            status = (r.json() or {}).get("status", "")
            if status == "Failed":
                # terminal failure is an ERROR, not a parsed success — route
                # through the error_col/raise path with the payload attached
                return HTTPResponseData(502, "async operation Failed",
                                        dict(r.headers), r.entity)
            if status not in ("Running", "NotStarted", ""):
                return r
            self.clock.sleep(self.get("poll_interval_s"))
        return HTTPResponseData(504, "poll limit reached")

    def _exchange(self, reqs):
        from ..utils.async_utils import buffered_map

        initial = CognitiveServiceBase._exchange(self, reqs)
        # rows poll concurrently through the same window width as the
        # initial requests — sequential polling would sum every row's wait
        return list(buffered_map(self._poll_operation, initial,
                                 max(int(self.get("concurrency")), 1)))


@register_stage
class RecognizeText(_AsyncPollBase):
    """Async text recognition (ComputerVision.scala:192-278). Output: the
    final operation payload (`recognitionResult` with lines/words)."""

    mode = Param("Printed", "Printed | Handwritten", ptype=str)

    def _row_request(self, row_vals, i):
        url = f"{self.get('url')}?mode={self.get('mode')}"
        return HTTPRequestData.from_json(
            url, self._row_body(row_vals, i), headers=self._headers()
        )


@register_stage
class GenerateThumbnails(_VisionBase):
    """Thumbnail generation (ComputerVision.scala:222-260). Output: the raw
    thumbnail image bytes."""

    width = Param(64, "thumbnail width (px)", ptype=int)
    height = Param(64, "thumbnail height (px)", ptype=int)
    smart_cropping = Param(True, "center on the region of interest", ptype=bool)

    def _row_request(self, row_vals, i):
        url = (f"{self.get('url')}?width={self.get('width')}"
               f"&height={self.get('height')}"
               f"&smartCropping={str(self.get('smart_cropping')).lower()}")
        return HTTPRequestData.from_json(
            url, self._row_body(row_vals, i), headers=self._headers()
        )

    def _parse(self, resp):
        return resp.entity  # image bytes, not JSON


@register_stage
class RecognizeDomainSpecificContent(_VisionBase):
    """Domain-model analysis — celebrities/landmarks (reference: DSIR,
    RecognizeDomainSpecificContent, ComputerVision.scala:362-378). The
    domain model is a URL path segment; output: the `result` payload."""

    model = Param("celebrities", "domain model (celebrities | landmarks)",
                  ptype=str)

    def _row_request(self, row_vals, i):
        url = f"{self.get('url').rstrip('/')}/models/{self.get('model')}/analyze"
        return HTTPRequestData.from_json(
            url, self._row_body(row_vals, i), headers=self._headers()
        )

    def _parse(self, resp):
        return (resp.json() or {}).get("result")


@register_stage
class TagImage(_VisionBase):
    """Image tagging (ComputerVision.scala:380-420). Output: `tags` list."""

    def _parse(self, resp):
        return (resp.json() or {}).get("tags")


@register_stage
class DescribeImage(_VisionBase):
    """Image description (ComputerVision.scala:422-460). Output: the
    `description` payload (captions + tags)."""

    max_candidates = Param(1, "caption candidates to return", ptype=int)

    def _row_request(self, row_vals, i):
        url = f"{self.get('url')}?maxCandidates={self.get('max_candidates')}"
        return HTTPRequestData.from_json(
            url, self._row_body(row_vals, i), headers=self._headers()
        )

    def _parse(self, resp):
        return (resp.json() or {}).get("description")


# ---------------------------------------------------------------------------
# Face suite (reference: Face.scala:19-347)


@register_stage
class FindSimilarFace(CognitiveServiceBase):
    """Find faces similar to a query face (Face.scala:120-180)."""

    face_id = ServiceParam(None, "query face id (scalar or column)")
    face_ids = ServiceParam(None, "candidate face id list (scalar or column)")
    max_candidates = Param(20, "max matches returned", ptype=int)
    mode = Param("matchPerson", "matchPerson | matchFace", ptype=str)

    def _row_body(self, row_vals, i):
        return {
            "faceId": row_vals.get("face_id"),
            "faceIds": list(row_vals.get("face_ids") or []),
            "maxNumOfCandidatesReturned": self.get("max_candidates"),
            "mode": self.get("mode"),
        }


@register_stage
class GroupFaces(CognitiveServiceBase):
    """Partition faces into similarity groups (Face.scala:182-220)."""

    face_ids = ServiceParam(None, "face id list (scalar or column)")

    def _row_body(self, row_vals, i):
        return {"faceIds": list(row_vals.get("face_ids") or [])}


@register_stage
class IdentifyFaces(CognitiveServiceBase):
    """Identify faces against a person group (Face.scala:222-280)."""

    person_group_id = ServiceParam(None, "person group id (scalar or column)")
    face_ids = ServiceParam(None, "face id list (scalar or column)")
    max_candidates = Param(1, "candidates per face", ptype=int)
    confidence_threshold = Param(None, "identification confidence floor", ptype=float)

    def _row_body(self, row_vals, i):
        body = {
            "personGroupId": row_vals.get("person_group_id"),
            "faceIds": list(row_vals.get("face_ids") or []),
            "maxNumOfCandidatesReturned": self.get("max_candidates"),
        }
        if self.get("confidence_threshold") is not None:
            body["confidenceThreshold"] = self.get("confidence_threshold")
        return body


@register_stage
class VerifyFaces(CognitiveServiceBase):
    """Verify two faces belong to one person (Face.scala:282-347)."""

    face_id1 = ServiceParam(None, "first face id (scalar or column)")
    face_id2 = ServiceParam(None, "second face id (scalar or column)")

    def _row_body(self, row_vals, i):
        return {"faceId1": row_vals.get("face_id1"),
                "faceId2": row_vals.get("face_id2")}


@register_stage
class BingImageSearch(CognitiveServiceBase):
    """Bing image search (reference: ImageSearch.scala:23-296). Output: the
    `value` list of image results (contentUrl etc.)."""

    query = ServiceParam(None, "search query (scalar or column)")
    count = Param(10, "results per query", ptype=int)
    offset = Param(0, "result offset (paging)", ptype=int)
    market = Param(None, "market code, e.g. en-US", ptype=str)

    def _row_request(self, row_vals, i):
        from urllib.parse import urlencode

        params = {"q": row_vals.get("query", ""), "count": self.get("count"),
                  "offset": self.get("offset")}
        if self.get("market"):
            params["mkt"] = self.get("market")
        return HTTPRequestData(
            method="GET",
            url=f"{self.get('url')}?{urlencode(params)}",
            headers=self._headers(),
        )

    def _parse(self, resp):
        return (resp.json() or {}).get("value")

    @staticmethod
    def download_from_urls(urls, concurrency: int = 4, timeout: float = 30.0):
        """Fetch image bytes for result URLs (reference
        BingImageSearch.downloadFromUrls, ImageSearch.scala:240-296); failed
        fetches yield None."""
        client = HTTPClient(concurrency=concurrency, timeout=timeout)
        reqs = [HTTPRequestData(method="GET", url=u, headers={}) for u in urls]
        resps = client.send_all(reqs)
        return [r.entity if isinstance(r, HTTPResponseData) and r.ok else None
                for r in resps]
