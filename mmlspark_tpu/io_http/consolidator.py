"""PartitionConsolidator — funnel work through limited lanes.

Reference: `PartitionConsolidator` (src/io/http/src/main/scala/
PartitionConsolidator.scala:103+): funnels rows from all partitions to ONE
worker per host so rate-limited services see a bounded connection count.
Host equivalent: run a column function through a fixed-size worker pool with
a global rate limit — the same bounded-concurrency semantics without Spark's
partition machinery."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import Table
from ..core.serialize import register_stage
from ..utils.async_utils import buffered_map

__all__ = ["PartitionConsolidator"]


class _RateLimiter:
    def __init__(self, per_second: float | None):
        self.interval = 1.0 / per_second if per_second else 0.0
        self._lock = threading.Lock()
        self._next = 0.0

    def acquire(self) -> None:
        if not self.interval:
            return
        with self._lock:
            now = time.monotonic()
            wait = self._next - now
            self._next = max(self._next, now) + self.interval
        if wait > 0:
            time.sleep(wait)


@register_stage
class PartitionConsolidator(HasInputCol, HasOutputCol, Transformer):
    """Apply `fn` over a column through `num_lanes` workers at most
    `requests_per_second` calls/s (reference: one-consolidated-worker-per-
    host for rate-limited services)."""

    input_col = Param("input", "input column", ptype=str)
    output_col = Param("output", "output column", ptype=str)
    num_lanes = Param(1, "concurrent lanes (reference: 1 per host)", ptype=int)
    requests_per_second = Param(None, "global rate limit", ptype=float)

    fn: Callable[[Any], Any] | None = None

    def _transform(self, table: Table) -> Table:
        if self.fn is None:
            raise ValueError("PartitionConsolidator needs fn")
        limiter = _RateLimiter(self.get("requests_per_second"))

        def call(v):
            limiter.acquire()
            return self.fn(v)

        col = table[self.get("input_col")]
        vals = col.tolist() if hasattr(col, "tolist") else list(col)
        out = list(buffered_map(call, vals, max(self.get("num_lanes"), 1)))
        return table.with_column(self.get("output_col"), out)
