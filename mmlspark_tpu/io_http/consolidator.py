"""PartitionConsolidator — funnel work through limited lanes.

Reference: `PartitionConsolidator` (src/io/http/src/main/scala/
PartitionConsolidator.scala:103+): funnels rows from all partitions to ONE
worker per host so rate-limited services see a bounded connection count.
Two scopes here:

  * `PartitionConsolidator` (in-process): run a column function through a
    fixed-size worker pool with a global rate limit — the same
    bounded-concurrency semantics without Spark's partition machinery.
  * `ConsolidatorService` (fleet-wide): the SAME funnel as an HTTP
    micro-service on the driver. Every serving replica (a separate OS
    process — ServingFleet) proxies its upstream calls through it, so a
    rate-limited upstream sees ONE bounded client no matter how many
    replica processes the fleet runs — the cross-process completion of the
    reference's one-worker-per-host design.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Any, Callable

from ..observability.sanitizer import make_lock
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from .serving import SingleSegmentHandler
from ..core.schema import Table
from ..core.serialize import register_stage
from ..utils.async_utils import buffered_map

__all__ = ["PartitionConsolidator", "ConsolidatorService"]


class _RateLimiter:
    def __init__(self, per_second: float | None):
        self.interval = 1.0 / per_second if per_second else 0.0
        self._lock = make_lock("_RateLimiter._lock")
        self._next = 0.0

    def acquire(self) -> None:
        if not self.interval:
            return
        with self._lock:
            now = time.monotonic()
            wait = self._next - now
            self._next = max(self._next, now) + self.interval
        if wait > 0:
            time.sleep(wait)


@register_stage
class PartitionConsolidator(HasInputCol, HasOutputCol, Transformer):
    """Apply `fn` over a column through `num_lanes` workers at most
    `requests_per_second` calls/s (reference: one-consolidated-worker-per-
    host for rate-limited services)."""

    input_col = Param("input", "input column", ptype=str)
    output_col = Param("output", "output column", ptype=str)
    num_lanes = Param(1, "concurrent lanes (reference: 1 per host)", ptype=int)
    requests_per_second = Param(None, "global rate limit", ptype=float)

    fn: Callable[[Any], Any] | None = None

    def _transform(self, table: Table) -> Table:
        if self.fn is None:
            raise ValueError("PartitionConsolidator needs fn")
        limiter = _RateLimiter(self.get("requests_per_second"))

        def call(v):
            limiter.acquire()
            return self.fn(v)

        col = table[self.get("input_col")]
        vals = col.tolist() if hasattr(col, "tolist") else list(col)
        out = list(buffered_map(call, vals, max(self.get("num_lanes"), 1)))
        return table.with_column(self.get("output_col"), out)


class ConsolidatorService:
    """Fleet-wide rate-limit funnel as an HTTP micro-service.

    POST / with a raw body: the request passes the global rate limiter and
    the `num_lanes` concurrency gate, then `fn(body bytes) -> bytes` (the
    upstream call) runs; the result streams back. GET / reports stats
    {served, in_flight, max_in_flight}. Replica processes hit this URL
    instead of the rate-limited upstream directly, so the limit holds
    across the WHOLE fleet, not per process."""

    def __init__(self, fn: Callable[[bytes], bytes],
                 num_lanes: int = 1,
                 requests_per_second: float | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.fn = fn
        self.host, self.port = host, port
        self._limiter = _RateLimiter(requests_per_second)
        self._lanes = threading.Semaphore(max(num_lanes, 1))
        self._lock = make_lock("ConsolidatorService._lock")
        self.served = 0
        self.in_flight = 0
        self.max_in_flight = 0
        self._server: ThreadingHTTPServer | None = None

    def start(self) -> "ConsolidatorService":
        outer = self

        class Handler(SingleSegmentHandler):
            def do_POST(self):  # noqa: N802 — http.server API
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                with outer._lanes:
                    with outer._lock:
                        outer.in_flight += 1
                        outer.max_in_flight = max(outer.max_in_flight,
                                                  outer.in_flight)
                    try:
                        outer._limiter.acquire()
                        try:
                            out = outer.fn(body)
                            status = 200
                        except Exception as e:  # noqa: BLE001 — per-request
                            out = json.dumps({"error": str(e)}).encode()
                            status = 502
                    finally:
                        with outer._lock:
                            outer.in_flight -= 1
                            outer.served += 1
                self.send_response(status)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def do_GET(self):  # noqa: N802
                with outer._lock:
                    body = json.dumps({
                        "served": outer.served,
                        "in_flight": outer.in_flight,
                        "max_in_flight": outer.max_in_flight,
                    }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
