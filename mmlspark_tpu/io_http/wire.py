"""Length-prefixed zero-copy binary row protocol — the shared wire codec
for the serving hot path and the streaming fleet-worker shuffle.

A frame carries one table: a small JSON meta blob plus N dtype-tagged
column blocks whose payloads are raw little-endian array bytes, so the
receiving side decodes each column with a single ``np.frombuffer`` (no
per-value parse, no intermediate lists).  Layout (all integers
little-endian):

    offset  size       field
    0       4          magic  b"MSWR"
    4       1          version (currently 1)
    5       1          flags (reserved, 0)
    6       2          u16  column count
    8       4          u32  row count
    12      4          u32  meta length
    16      meta       UTF-8 JSON meta blob
    ...     per column:
              u16      name length
              name     UTF-8 column name
              u8       dtype tag (see _DTYPE_TAGS)
              u8       ndim
              ndim*u32 shape (dim 0 == row count)
              u32      payload byte length
              payload  raw C-order little-endian array bytes

Columns with non-numeric dtypes (object / str / lists) ride in
``meta["json_columns"]`` using the same ``{"dtype": ..., "values": ...}``
shape as the streaming JSON columnar encoding, so any table the JSON
path can carry, the binary path can too.

Version negotiation: a decoder rejects frames whose major version it
does not know (`WireError`); servers answer such requests 415 and the
client falls back to JSON.  The codec is deliberately self-contained
(numpy + stdlib only) so both ends of every wire can import it.
"""
from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

WIRE_CONTENT_TYPE = "application/x-mmlspark-rows"
WIRE_MAGIC = b"MSWR"
WIRE_VERSION = 1

_HEADER = struct.Struct("<4sBBHII")  # magic, version, flags, ncols, nrows, meta_len

# fixed-width dtypes that travel as raw bytes; everything else falls back
# to the JSON columnar encoding inside the meta blob
_DTYPE_TAGS: "dict[str, int]" = {
    "float64": 1, "float32": 2,
    "int64": 3, "int32": 4, "int16": 5, "int8": 6,
    "uint64": 7, "uint32": 8, "uint16": 9, "uint8": 10,
    "bool": 11,
}
_TAG_DTYPES = {v: np.dtype(k) for k, v in _DTYPE_TAGS.items()}


class WireError(ValueError):
    """Malformed or version-incompatible binary frame."""


def _binary_dtype(col: Any) -> "np.dtype | None":
    if not isinstance(col, np.ndarray):
        return None
    name = col.dtype.name
    if name not in _DTYPE_TAGS:
        return None
    return col.dtype


def encode_message(meta: "dict[str, Any]", cols: "dict[str, Any]",
                   n_rows: "int | None" = None) -> bytes:
    """One table -> one frame. Numeric ndarray columns become raw byte
    blocks; anything else (lists, object arrays) is folded into
    ``meta["json_columns"]`` with the JSON columnar shape."""
    meta = dict(meta)
    blocks: "list[bytes]" = []
    json_cols: "dict[str, Any]" = dict(meta.get("json_columns") or {})
    rows = n_rows
    for name, col in cols.items():
        dt = _binary_dtype(col)
        if dt is None:
            if isinstance(col, np.ndarray):
                json_cols[name] = {"dtype": str(col.dtype),
                                   "values": col.tolist()}
                n = col.shape[0] if col.ndim else 1
            else:
                json_cols[name] = {"dtype": "list", "values": list(col)}
                n = len(json_cols[name]["values"])
        else:
            arr = np.ascontiguousarray(col)
            if arr.dtype.byteorder == ">":  # big-endian host arrays
                arr = arr.astype(arr.dtype.newbyteorder("<"))
            payload = arr.tobytes()
            nm = name.encode("utf-8")
            head = struct.pack("<H", len(nm)) + nm
            head += struct.pack("<BB", _DTYPE_TAGS[dt.name], arr.ndim)
            head += struct.pack(f"<{arr.ndim}I", *arr.shape)
            head += struct.pack("<I", len(payload))
            blocks.append(head + payload)
            n = arr.shape[0] if arr.ndim else 1
        if rows is None:
            rows = n
    if json_cols:
        meta["json_columns"] = json_cols
    meta_b = json.dumps(meta).encode("utf-8")
    header = _HEADER.pack(WIRE_MAGIC, WIRE_VERSION, 0, len(blocks),
                          int(rows or 0), len(meta_b))
    return b"".join([header, meta_b, *blocks])


def decode_message(buf: "bytes | bytearray | memoryview"
                   ) -> "tuple[dict[str, Any], dict[str, np.ndarray]]":
    """One frame -> (meta, columns). Numeric columns are zero-copy
    ``np.frombuffer`` views over the frame buffer (read-only — copy
    before mutating); JSON-columnar entries in ``meta["json_columns"]``
    are materialized alongside them."""
    view = memoryview(buf)
    if len(view) < _HEADER.size:
        raise WireError(f"frame too short ({len(view)} bytes)")
    magic, version, _flags, ncols, nrows, meta_len = _HEADER.unpack_from(view)
    if magic != WIRE_MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if version != WIRE_VERSION:
        raise WireError(f"unsupported wire version {version} "
                        f"(this codec speaks {WIRE_VERSION})")
    off = _HEADER.size
    if off + meta_len > len(view):
        raise WireError("truncated meta blob")
    try:
        meta = json.loads(bytes(view[off:off + meta_len]).decode("utf-8"))
    except Exception as e:  # noqa: BLE001 — any parse failure is a bad frame
        raise WireError(f"bad meta blob: {e}") from e
    off += meta_len
    cols: "dict[str, np.ndarray]" = {}
    for _ in range(ncols):
        try:
            (name_len,) = struct.unpack_from("<H", view, off)
            off += 2
            name = bytes(view[off:off + name_len]).decode("utf-8")
            off += name_len
            tag, ndim = struct.unpack_from("<BB", view, off)
            off += 2
            shape = struct.unpack_from(f"<{ndim}I", view, off)
            off += 4 * ndim
            (nbytes,) = struct.unpack_from("<I", view, off)
            off += 4
        except struct.error as e:
            raise WireError(f"truncated column header: {e}") from e
        dt = _TAG_DTYPES.get(tag)
        if dt is None:
            raise WireError(f"unknown dtype tag {tag}")
        count = int(np.prod(shape, dtype=np.int64)) if ndim else 1
        if nbytes != count * dt.itemsize or off + nbytes > len(view):
            raise WireError(f"column {name!r}: payload size mismatch")
        arr = np.frombuffer(view, dtype=dt, count=count,
                            offset=off).reshape(shape)
        off += nbytes
        if ndim and shape[0] != nrows:
            raise WireError(
                f"column {name!r}: dim 0 is {shape[0]}, frame says {nrows}")
        cols[name] = arr
    for name, doc in (meta.get("json_columns") or {}).items():
        dtype, values = doc["dtype"], doc["values"]
        cols[name] = (list(values) if dtype == "list"
                      else np.array(values, dtype=dtype))
    return meta, cols


def is_wire_content_type(content_type: "str | None") -> bool:
    """True when an HTTP Content-Type / Accept value names the binary
    row protocol (parameters after ';' ignored)."""
    if not content_type:
        return False
    return content_type.split(";", 1)[0].strip().lower() == WIRE_CONTENT_TYPE


def accepts_wire(headers: "dict | None") -> bool:
    """True when a request's Accept header asks for binary replies."""
    if not headers:
        return False
    for k, v in headers.items():
        if k.lower() == "accept":
            return any(is_wire_content_type(part)
                       for part in str(v).split(","))
    return False


def content_type_of(headers: "dict | None") -> "str | None":
    if not headers:
        return None
    for k, v in headers.items():
        if k.lower() == "content-type":
            return v
    return None


def encode_features_request(values: "np.ndarray") -> bytes:
    """Client-side helper: one scoring request's feature row(s) as a
    frame with the single ``features`` column (f64, shape (n, F))."""
    feats = np.asarray(values, np.float64)
    if feats.ndim == 1:
        feats = feats[None, :]
    return encode_message({}, {"features": feats})


def decode_features_request(entity: bytes, n_features: int) -> np.ndarray:
    """Server-side inverse of encode_features_request: (n, F) f64 matrix.
    Raises WireError when the frame lacks a conforming features block."""
    _meta, cols = decode_message(entity)
    feats = cols.get("features")
    if not isinstance(feats, np.ndarray):
        raise WireError("frame has no 'features' column")
    if feats.ndim == 1:
        feats = feats[None, :]
    if feats.ndim != 2 or feats.shape[1] != n_features:
        raise WireError(f"features shape {feats.shape} != (n, {n_features})")
    return np.ascontiguousarray(feats, np.float64)


def encode_reply(value_col: str, value: Any) -> bytes:
    """One scoring reply as a frame: a single-row f64 column named after
    the output column (vector outputs ride as shape (1, K))."""
    arr = np.asarray(value, np.float64)
    arr = arr[None] if arr.ndim in (0, 1) else arr
    return encode_message({"value_col": value_col}, {value_col: arr})


def decode_reply(entity: bytes) -> "tuple[str, np.ndarray]":
    """(value_col, values) from a binary reply frame."""
    meta, cols = decode_message(entity)
    col = meta.get("value_col")
    if col is None or col not in cols:
        raise WireError("reply frame missing value column")
    return col, np.asarray(cols[col])
