"""Serving: deploy a pipeline as a web service.

Reference: Spark Serving (SURVEY.md §3.4) — batch mode `HTTPSource`/`HTTPSink`
(HTTPSource.scala:46-225), distributed mode's per-JVM `JVMSharedServer` with
request queues drained per micro-batch (DistributedHTTPSource.scala:89-343),
and continuous mode's per-partition servers replying through an in-process
routing table keyed by request id (HTTPSourceV2.scala:336-474, ~1 ms).

TPU redesign: one process = one host = one `ServingServer`. Requests land in
an in-memory queue; a batcher thread drains up to `max_batch_size` requests
or `max_latency_ms`, runs the scoring callable ONCE on the whole batch (the
jitted model step is persistent — compiled on the first batch, padded to a
fixed shape after that), and completes each request's event — the
continuous-mode direct-reply path without a streaming engine in the middle.
Multi-host serving = one ServingServer per host behind any TCP balancer
(the reference's per-executor servers + load balancer, SURVEY.md §3.4).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

import numpy as np

from ..core.schema import Table
from .schema import HTTPRequestData, HTTPResponseData, make_reply, parse_request

__all__ = ["ServingServer", "serve_model"]


@dataclass
class _Exchange:
    request: HTTPRequestData
    event: threading.Event = field(default_factory=threading.Event)
    response: HTTPResponseData | None = None


class ServingServer:
    """HTTP frontend + batched scoring loop.

    `handler(Table) -> Table` receives a table with a "request" column of
    HTTPRequestData and must return a table with a "reply" column of
    HTTPResponseData (use parse_request/make_reply, the reference's
    ServingImplicits pattern)."""

    def __init__(
        self,
        handler: Callable[[Table], Table],
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        reply_timeout_s: float = 30.0,
        api_path: str = "/",
    ):
        self.handler = handler
        self.host, self.port = host, port
        self.max_batch_size = max_batch_size
        self.max_latency_ms = max_latency_ms
        self.reply_timeout_s = reply_timeout_s
        self.api_path = api_path
        self._queue: queue.Queue[_Exchange] = queue.Queue()
        self._server: ThreadingHTTPServer | None = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # serving counters (reference requestsSeen/Accepted/Answered,
        # DistributedHTTPSource.scala:98-107); incremented from concurrent
        # ThreadingHTTPServer handler threads, so guarded by a lock
        self.requests_seen = 0
        self.requests_answered = 0
        self._counter_lock = threading.Lock()

    # ------------------------------------------------------------------ #

    def start(self) -> "ServingServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — http.server API
                with outer._counter_lock:
                    outer.requests_seen += 1
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                ex = _Exchange(HTTPRequestData(
                    method="POST", url=self.path,
                    headers=dict(self.headers), entity=body,
                ))
                outer._queue.put(ex)
                if not ex.event.wait(outer.reply_timeout_s):
                    self.send_response(504)
                    self.end_headers()
                    return
                resp = ex.response or HTTPResponseData(500, "no response")
                self.send_response(resp.status_code or 500)
                for k, v in resp.headers.items():
                    self.send_header(k, v)
                self.end_headers()
                if resp.entity:
                    self.wfile.write(resp.entity)
                with outer._counter_lock:
                    outer.requests_answered += 1

            def do_GET(self):  # noqa: N802 — health/info endpoint
                info = json.dumps({
                    "name": "mmlspark_tpu.serving",
                    "host": outer.host, "port": outer.port,
                    "seen": outer.requests_seen,
                    "answered": outer.requests_answered,
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(info)

            def log_message(self, *a):  # silence per-request stderr noise
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        st = threading.Thread(target=self._server.serve_forever, daemon=True)
        bt = threading.Thread(target=self._batch_loop, daemon=True)
        st.start()
        bt.start()
        self._threads = [st, bt]
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._server:
            self._server.shutdown()
            self._server.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    # ------------------------------------------------------------------ #

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            deadline = time.monotonic() + self.max_latency_ms / 1e3
            while len(batch) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            try:
                table = Table({"request": [ex.request for ex in batch]})
                out = self.handler(table)
                replies = out["reply"]
                if len(replies) != len(batch):
                    raise ValueError(
                        f"handler returned {len(replies)} replies for a "
                        f"batch of {len(batch)} requests — handlers must "
                        "preserve row count and order"
                    )
            except Exception as e:  # noqa: BLE001 — per-batch failure -> 500s
                err = HTTPResponseData(
                    500, "handler error",
                    headers={"Content-Type": "application/json"},
                    entity=json.dumps({"error": str(e)}).encode(),
                )
                replies = [err] * len(batch)
            for ex, resp in zip(batch, replies):
                ex.response = resp
                ex.event.set()


def serve_model(
    model,
    input_cols: list[str],
    output_col: str = "prediction",
    host: str = "127.0.0.1",
    port: int = 0,
    **server_kw,
) -> ServingServer:
    """Deploy a fitted Transformer: JSON body {col: value, ...} in,
    {output_col: value} out (the `SparkServing - Deploying a Classifier`
    notebook flow)."""

    def handler(table: Table) -> Table:
        t = parse_request(table)
        missing = [c for c in input_cols if c not in t]
        if missing:
            raise ValueError(f"request missing fields {missing}")
        if "features" not in t and all(
            isinstance(t[c], np.ndarray) for c in input_cols
        ):
            feats = np.stack([np.asarray(t[c], np.float64) for c in input_cols], 1)
            t = t.with_column("features", feats)
        scored = model.transform(t)
        return make_reply(scored, output_col)

    return ServingServer(handler, host=host, port=port, **server_kw).start()
