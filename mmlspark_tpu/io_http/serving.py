"""Serving: deploy a pipeline as a web service.

Reference: Spark Serving (SURVEY.md §3.4) — batch mode `HTTPSource`/`HTTPSink`
(HTTPSource.scala:46-225), distributed mode's per-JVM `JVMSharedServer` with
request queues drained per micro-batch (DistributedHTTPSource.scala:89-343),
and continuous mode's per-partition servers replying through an in-process
routing table keyed by request id (HTTPSourceV2.scala:336-474, ~1 ms).

TPU redesign: one process = one host = one `ServingServer`. Requests land in
an in-memory queue; a batcher thread greedily drains everything queued (up
to `max_batch_size`), runs the scoring callable ONCE on the whole batch (the
jitted model step is persistent — compiled on the first batch, padded to a
fixed shape after that), and completes each request's event — the
continuous-mode direct-reply path without a streaming engine in the middle.
Batching is backpressure-driven: requests arriving mid-score join the next
batch. `max_latency_ms` (default 0) is an opt-in collection window that
trades exactly that much p50 for bigger batches.
Multi-host serving = one ServingServer per host behind any TCP balancer
(the reference's per-executor servers + load balancer, SURVEY.md §3.4).
"""

from __future__ import annotations

import collections
import itertools
import json
import multiprocessing
import queue
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

import numpy as np

from ..core.dataplane import AsyncReadback, ShapeBucketer, cache_stats
from ..core.schema import Table
from ..observability.sanitizer import make_lock, make_rlock
from .schema import (HTTPRequestData, HTTPResponseData, RequestDecoder,
                     make_reply, parse_request)
from .wire import (WIRE_CONTENT_TYPE, accepts_wire, encode_reply,
                   is_wire_content_type)

__all__ = ["ServingServer", "ServingFleet", "MicroBatchQuery", "serve_model",
           "ServiceInfo", "FleetRendezvous"]

# unique `server=` label per ServingServer in this process: the registry is
# shared, the per-server counts must stay exact (tests assert them)
_SERVER_SEQ = itertools.count()


def _prof_ledger(kind: str, segment: str, span: Any = None, **meta: Any):
    """The process profiler's phase ledger for one scored batch — the
    shared no-op when disarmed (one attribute check on the hot path).
    Import is deferred so serving never pays observability's package
    init unless a batch is actually scored."""
    from ..observability.profiler import get_profiler

    return get_profiler().ledger(kind, segment, span=span, **meta)


def _negotiate_reply(resp: "HTTPResponseData",
                     request: "HTTPRequestData") -> "HTTPResponseData":
    """Honor a binary-Accept-ing client on routes that replied JSON (the
    handler fallback path): a 200 single-value ``{col: v}`` JSON reply is
    re-framed as the binary wire reply. Hot-path routes frame binary
    replies directly (`replies_for`'s binary_mask), so this is a no-op
    for them; error statuses and non-scalar bodies pass through as JSON
    — the negotiation rule is 'binary clients must also accept JSON',
    never the reverse."""
    if (resp.status_code != 200 or not resp.entity
            or not accepts_wire(request.headers)):
        return resp
    ct = resp.headers.get("Content-Type", "")
    if is_wire_content_type(ct) or not ct.startswith("application/json"):
        return resp
    try:
        body = json.loads(resp.entity)
        (col, v), = body.items()
        if v is None or isinstance(v, (bool, str, dict)):
            return resp
        return HTTPResponseData(
            status_code=200, reason="OK",
            headers={"Content-Type": WIRE_CONTENT_TYPE},
            entity=encode_reply(col, v))
    except Exception:  # noqa: BLE001 — negotiation never breaks a reply
        return resp


def _handler_error_response(e: Exception) -> "HTTPResponseData":
    """Uniform 500 payload for a failed scoring batch (continuous and
    micro-batch paths share the error contract)."""
    return HTTPResponseData(
        500, "handler error",
        headers={"Content-Type": "application/json"},
        entity=json.dumps({"error": str(e)}).encode(),
    )


@dataclass
class _Exchange:
    request: HTTPRequestData
    event: threading.Event = field(default_factory=threading.Event)
    response: HTTPResponseData | None = None
    enqueued_at: float = 0.0
    # absolute perf_counter deadline (request_deadline_s); None = no deadline
    deadline: float | None = None
    # the serving.request span (handler thread) — the batcher parents its
    # serving.score span on it so one trace covers park -> score -> reply
    span: Any = None
    # stamped by the batcher before scoring: which hot-path route and
    # bucket rung served this request (+ the readback window depth at
    # resident dispatch) — the handler thread attaches them to the
    # latency exemplar and the flight-recorder request record
    route: str | None = None
    bucket: int | None = None
    readback_lag: int | None = None


class SingleSegmentHandler(BaseHTTPRequestHandler):
    """Base for every HTTP handler in this package: buffered writes +
    TCP_NODELAY so each response leaves as ONE TCP segment.

    The stdlib defaults (wbufsize=0, Nagle on) write headers and body as
    separate small sends; on a keep-alive connection the second send
    stalls behind the peer's delayed ACK — ~40 ms added to every round
    trip, invisible to server-side latency counters (enqueue -> reply
    written) and devastating to the ~1 ms serving claim. Subclass this
    instead of BaseHTTPRequestHandler so no future endpoint reintroduces
    the stall."""

    wbufsize = -1
    disable_nagle_algorithm = True


class _DeepBacklogServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a serving-grade accept backlog.

    socketserver's default listen backlog is 5: a burst of concurrent
    clients connecting at once (exactly the load continuous batching is
    built to coalesce) overflows it and the overflow gets TCP RSTs
    before the server ever sees the requests. The batcher's admission
    control (max_pending -> 503 + Retry-After) is the intended overload
    answer — it can only run on connections that got accepted."""

    request_queue_size = 128


class _HotPath:
    """serve_model's device-resident fast lane.

    Holds the long-lived scoring session the batcher can route through
    instead of the per-request handler: a `core.fusion.ResidentExecutor`
    with the fused segment's params (and GBDT SoAs) pinned on device once
    at startup, a `RequestDecoder` that turns a request batch into ONE
    preallocated feature matrix, and — when the model exposes one — the
    native C++ tree-walk scorer, the small-batch champion. `route_for`
    picks the route per bucket rung from the crossover measured during
    warmup; a rung warmup never measured stays on the handler path —
    the fast routes are only ever taken where they were verified and
    their executables pre-compiled (no warmup_request = no fast lane).

    Every route must be byte-identical to the handler path. Warmup
    enforces that literally: each rung's resident (and native) reply
    BYTES are compared against the handler's replies for the same batch,
    and the first divergence disables the fast lane — correctness
    degrades to the handler path, never to different answers."""

    # timing repetitions per rung when measuring the crossover
    WARM_REPS = 3

    # the route label this hot path's resident lane reports under —
    # subclasses serving other workloads (recommendation.resident's
    # SARHotPath) override it so serving_path_total separates workloads
    resident_label = "resident"

    def __init__(self, executor, decoder: RequestDecoder, feature_col: str,
                 output_col: str, native_fn=None, readback_lag: int = 1):
        self.executor = executor
        self.decoder = decoder
        self.feature_col = feature_col
        self.output_col = output_col
        self.native_fn = native_fn
        self.readback_lag = max(int(readback_lag), 0)
        # bucket rung -> resident_label | "native", learned by warm_rung
        self.crossover: dict[int, str] = {}
        self.timings_ms: dict[int, dict[str, float]] = {}
        self.disabled: "str | None" = None
        # test hook: pin every batch to one route (resident_label/
        # "native"/"host") regardless of the crossover
        self.force_path: "str | None" = None
        self.path_requests = {self.resident_label: 0, "native": 0, "host": 0}
        self.resident_batches = 0
        # guards the routing tables and counters above: warm_rung runs on
        # the warmup thread while scorer threads call route_for/note
        self._lock = make_rlock("_HotPath._lock")

    def _disable(self, reason: str) -> None:
        with self._lock:
            self.disabled = reason

    def route_for(self, bucket: int) -> str:
        with self._lock:
            if self.disabled is not None:
                return "host"
            if self.force_path is not None:
                return self.force_path
            # only rungs warmup measured (and byte-verified) route fast:
            # an unknown rung on the resident path would pay a LIVE
            # compile and score through a route whose replies were never
            # checked
            return self.crossover.get(bucket, "host")

    def replies_for(self, vals: np.ndarray,
                    binary_mask: "list[bool] | None" = None
                    ) -> "list[HTTPResponseData]":
        """Score column -> replies, byte-for-byte what the handler path's
        `make_reply` produces (tolist() -> Python float -> json.dumps).
        `binary_mask[i]` True swaps row i's reply for the binary wire
        frame (the request Accept-ed it) — raw f64 bytes, no json.dumps
        on the hot path."""
        col = self.output_col
        vlist = np.asarray(vals).tolist()
        if binary_mask is None:
            binary_mask = [False] * len(vlist)
        return [
            HTTPResponseData(
                status_code=200, reason="OK",
                headers={"Content-Type": WIRE_CONTENT_TYPE},
                entity=encode_reply(col, v),
            ) if binary else HTTPResponseData(
                status_code=200, reason="OK",
                headers={"Content-Type": "application/json"},
                entity=json.dumps({col: v}).encode(),
            )
            for v, binary in zip(vlist, binary_mask)]

    def native_values(self, feats: np.ndarray) -> np.ndarray:
        return np.asarray(self.native_fn(feats), np.float64)

    def value_check(self, feats: np.ndarray) -> str:
        """Per-batch resident precondition — the VALUE-dependent subset of
        `executor.check_ready`.  Schema validation (dense ndarray, column
        contract) ran exactly once at warmup (`warm_rung`'s full
        check_ready); live batches pay only each kernel's vectorized
        `ready_values` hook — for GBDT, nothing at all on float32 payloads.
        '' routes resident; a reason string declines the batch (the native
        walk is exact for any float64 payload, so nothing is lost)."""
        try:
            return self.executor.check_ready_values(
                {self.feature_col: feats})
        except Exception as e:  # noqa: BLE001 — decline, never crash the loop
            return f"value check failed: {e}"

    def fetch_values(self, outs, n_valid: int, ledger=None):
        """Block on one in-flight batch's device results and return
        whatever `replies_for` consumes — subclasses with a different
        reply schema override both as a pair. An armed `ledger` splits
        the wait into compute (device) and d2h (host copy) phases."""
        return self.executor.fetch(outs, n_valid, ledger=ledger)[
            self.output_col]

    def resident_values(self, feats: np.ndarray, n_valid: int):
        outs = self.executor.dispatch({self.feature_col: feats})
        return self.fetch_values(outs, n_valid)

    def warm_rung(self, handler, request: HTTPRequestData, rung: int,
                  expect_entities: list) -> None:
        """Compile, verify, and time one ladder rung. The handler's
        replies for the same batch are the oracle: the resident and
        native routes must reproduce their entity bytes exactly. The
        faster measured route wins the rung in `crossover`."""
        if self.disabled is not None:
            return
        feats = self.decoder.decode([request] * rung)
        if feats is None:
            self._disable("warmup request outside the fast-path schema")
            return
        expect = list(expect_entities)
        reason = self.executor.check_ready(Table({self.feature_col: feats}))
        if reason:
            # commonly: the warmup payload's floats are not f32-
            # representable, so the resident route would decline the batch
            # (live routing guards this per batch too). Warm and time the
            # ladder on the nearest representable request instead, with
            # the handler re-scored on it as the byte oracle.
            vals = feats[0].astype(np.float32).astype(np.float64)
            req32 = HTTPRequestData.from_json(
                request.url or "/",
                dict(zip(self.decoder.cols, vals.tolist())))
            feats = self.decoder.decode([req32] * rung)
            reason = (self.executor.check_ready(
                Table({self.feature_col: feats}))
                if feats is not None else "warmup schema")
            if feats is None or reason:
                self._disable(f"resident precondition: {reason}")
                return
            expect = [r.entity
                      for r in handler(Table({"request": [req32] * rung}))
                      ["reply"]]
        try:
            vals = self.resident_values(feats, rung)  # first call compiles
        except Exception as e:  # noqa: BLE001 — degrade, don't break serving
            self._disable(f"resident dispatch failed: {e}")
            return
        if [r.entity for r in self.replies_for(vals)] != expect:
            self._disable(f"resident replies diverge at rung {rung}")
            return
        t = {self.resident_label: self._time(
            lambda: self.resident_values(feats, rung))}
        if self.native_fn is not None:
            try:
                nvals = self.native_values(feats)
            except Exception:  # noqa: BLE001 — native scorer unusable
                with self._lock:
                    self.native_fn = None
            else:
                if [r.entity for r in self.replies_for(nvals)] != expect:
                    # wrong answers never route; resident is already proven
                    with self._lock:
                        self.native_fn = None
                else:
                    t["native"] = self._time(
                        lambda: self.native_values(feats))
        with self._lock:
            self.timings_ms[rung] = {k: v * 1e3 for k, v in t.items()}
            self.crossover[rung] = min(t, key=t.get)

    @staticmethod
    def _time(fn) -> float:
        best = float("inf")
        for _ in range(_HotPath.WARM_REPS):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    def note(self, path: str, n: int) -> None:
        with self._lock:
            self.path_requests[path] = self.path_requests.get(path, 0) + n

    def note_resident_batch(self) -> None:
        with self._lock:
            self.resident_batches += 1

    def snapshot(self) -> dict:
        """The info() `hot_path` block: routing table, measured per-rung
        timings, and round-trip accounting — the ROADMAP's ≤1-host-round-
        trip-per-request bar is `round_trips_per_resident_request` (each
        resident BATCH costs exactly one upload+readback pair, shared by
        every request coalesced into it)."""
        ex_stats: dict = {}
        try:
            ex_stats = self.executor.stats()
        except Exception:  # noqa: BLE001 — stats are strictly optional
            pass
        with self._lock:
            res_req = self.path_requests.get(self.resident_label, 0)
            return {
                "enabled": self.disabled is None,
                "disabled_reason": self.disabled,
                "resident_label": self.resident_label,
                "crossover": {str(b): p
                              for b, p in sorted(self.crossover.items())},
                "timings_ms": {str(b): {k: round(v, 4)
                                        for k, v in t.items()}
                               for b, t in sorted(self.timings_ms.items())},
                "readback_lag": self.readback_lag,
                "donate_buffers": bool(ex_stats.get("donate_buffers", False)),
                "dispatch_overlap_fraction": round(float(
                    ex_stats.get("dispatch_overlap_fraction", 0.0)), 4),
                "paths": dict(self.path_requests),
                "resident_batches": self.resident_batches,
                "round_trips": self.executor.round_trips,
                "round_trips_per_resident_request": (
                    self.resident_batches / res_req if res_req else 0.0),
                "decoder": {"hits": self.decoder.hits,
                            "fallbacks": self.decoder.fallbacks,
                            "binary_hits": getattr(
                                self.decoder, "binary_hits", 0)},
            }


class ServingServer:
    """HTTP frontend + batched scoring loop.

    `handler(Table) -> Table` receives a table with a "request" column of
    HTTPRequestData and must return a table with a "reply" column of
    HTTPResponseData (use parse_request/make_reply, the reference's
    ServingImplicits pattern)."""

    def __init__(
        self,
        handler: Callable[[Table], Table] | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch_size: int = 64,
        max_latency_ms: float = 0.0,
        reply_timeout_s: float = 30.0,
        api_path: str = "/",
        mode: str = "continuous",
        checkpoint_dir: str | None = None,
        max_pending: int = 0,
        request_deadline_s: float | None = None,
        drain_timeout_s: float = 5.0,
        bucket_batches: bool = False,
        bucket_multiple_of: int = 1,
        metrics: Any = None,
        warmup_request: "HTTPRequestData | None" = None,
        tracer: Any = None,
        hot_path: "_HotPath | None" = None,
        exemplars: bool = True,
        flight_recorder_dir: "str | None" = None,
        recorder: Any = None,
    ):
        if mode not in ("continuous", "batch"):
            raise ValueError(f"mode must be 'continuous' or 'batch', got {mode!r}")
        if mode == "continuous" and handler is None:
            raise ValueError("continuous mode needs a handler(Table) -> Table")
        if checkpoint_dir is not None and mode != "batch":
            raise ValueError(
                "checkpoint_dir journals the micro-batch source; it "
                "requires mode='batch' (the reference's checkpointLocation "
                "applies to the streaming query, "
                "docs/mmlspark-serving.md:50-52)"
            )
        self.handler = handler
        self.host, self.port = host, port
        self.max_batch_size = max_batch_size
        self.max_latency_ms = max_latency_ms
        self.reply_timeout_s = reply_timeout_s
        # load shedding: an overloaded server must answer 503 + Retry-After
        # immediately instead of queueing without bound (and timing every
        # caller out at once later). max_pending=0 keeps the historical
        # unbounded-queue behavior.
        self.max_pending = max_pending
        # per-request deadline: past it the request answers 504 WITHOUT
        # being scored — an expired exchange must not occupy a batch slot
        self.request_deadline_s = request_deadline_s
        self.drain_timeout_s = drain_timeout_s
        # Pad each scored batch up to a power-of-two bucket (repeating the
        # last request; padded replies are sliced off before completion).
        # A greedy batcher hands the handler every row count from 1 to
        # max_batch_size — one fresh XLA compile per NEW count, i.e. p99
        # recompile spikes deep into a deployment. The ladder bounds the
        # handler's input sizes to a small closed set, so the jitted model
        # is fully warm after one pass over the ladder. OPT-IN: padding
        # re-presents the last request to the handler, which is only safe
        # for pure scoring handlers (serve_model enables it) — a handler
        # with side effects per row (e.g. forwarding upstream) would see
        # duplicates.
        # Under a mesh the resident executor row-shards each dispatch over
        # the data axis, so every ladder rung must divide by its size —
        # serve_model passes bucket_multiple_of from the fused model's mesh
        # (mirroring _FusedSegment.run's mini-batch ladder).
        m = max(1, int(bucket_multiple_of))
        bmax = -(-max_batch_size // m) * m
        # skew-aware ladder (`shards=m`): each rung splits into m equal
        # per-shard slices, not just an m-divisible total
        self.bucketer = (ShapeBucketer(bmax, shards=m)
                         if bucket_batches and max_batch_size > 1 else None)
        self.api_path = api_path
        # "continuous": batcher thread drains the queue and replies directly
        # (HTTPSourceV2.scala:336-474). "batch": the micro-batch engine is the
        # CALLER — get_batch() drains pending requests as a Table, reply()
        # completes them (HTTPSource.getBatch/HTTPSink, HTTPSource.scala:46-225).
        self.mode = mode
        self._queue: queue.Queue[_Exchange] = queue.Queue()
        self._pending: dict[str, _Exchange] = {}   # batch mode: id -> exchange
        self._id_counter = itertools.count()
        self._server: ThreadingHTTPServer | None = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # durable accept/reply journal (reference checkpointLocation,
        # DistributedHTTPSource.scala:308-343): accepted-but-unanswered
        # requests survive a restart and are replayed by the next query
        self.journal = None
        if checkpoint_dir is not None:
            from .journal import ServingJournal

            self.journal = ServingJournal(checkpoint_dir)
            # never reuse a journaled id after restart
            self._id_counter = itertools.count(self.journal.max_id() + 1)
            # recovery: re-park the replay set; no live socket waits on
            # these exchanges — their replies land in the journal only
            for ex_id, req in self.journal.unanswered().items():
                self._pending[ex_id] = _Exchange(req)
        # serving counters (reference requestsSeen/Accepted/Answered,
        # DistributedHTTPSource.scala:98-107), registry-backed so one
        # GET /metrics scrape covers every server in the process; each
        # server owns uniquely-labeled children and the requests_*
        # properties read them back, keeping per-server accounting exact.
        # Imports are deferred: observability's package init pulls in
        # core.pipeline, and resilience must stay import-order free.
        from ..core.dataplane import ensure_cache_metrics
        from ..observability.metrics import get_registry
        from ..resilience.breaker import ensure_metrics as _breaker_metrics

        self.metrics = metrics if metrics is not None else get_registry()
        self.server_label = f"srv{next(_SERVER_SEQ)}"

        def _own(name: str, doc: str):
            return self.metrics.counter(name, doc, labels=("server",)) \
                .labels(server=self.server_label)

        self._c_seen = _own("mmlspark_tpu_serving_requests_seen_total",
                            "requests received, any outcome")
        self._c_accepted = _own("mmlspark_tpu_serving_requests_accepted_total",
                                "requests admitted past load shedding")
        self._c_answered = _own("mmlspark_tpu_serving_requests_answered_total",
                                "requests answered with a scored reply")
        self._c_shed = _own("mmlspark_tpu_serving_requests_shed_total",
                            "requests refused 503 (overload / draining)")
        self._c_expired = _own("mmlspark_tpu_serving_requests_expired_total",
                               "requests answered 504 past their deadline")
        self._c_failed = _own("mmlspark_tpu_serving_requests_failed_total",
                              "requests answered 500 from a failed "
                              "scoring batch")
        self._g_queue = self.metrics.gauge(
            "mmlspark_tpu_serving_queue_depth",
            "requests parked awaiting scoring",
            labels=("server",)).labels(server=self.server_label)
        # exemplars link each latency bucket to the exact trace that last
        # filled it (OpenMetrics suffix on the _bucket lines) — the fleet
        # aggregator merges them so a fleet p99 resolves to one trace_id
        self.exemplars = bool(exemplars)
        self._h_latency = self.metrics.histogram(
            "mmlspark_tpu_serving_latency_seconds",
            "service latency, enqueue to reply written",
            labels=("server",),
            exemplars=self.exemplars).labels(server=self.server_label)
        # the black box: None stays a one-attribute-check no-op on the hot
        # path; a flight_recorder_dir arms a per-server recorder whose
        # triggered dumps `tools/diagnose.py --postmortem` reassembles
        if recorder is None and flight_recorder_dir:
            from ..observability.recorder import FlightRecorder

            recorder = FlightRecorder(dump_dir=flight_recorder_dir,
                                      process=f"serving-{self.server_label}")
        self.recorder = recorder
        self._c_bucket = self.metrics.counter(
            "mmlspark_tpu_serving_bucket_batches_total",
            "scored batches per bucket-ladder rung",
            labels=("server", "bucket"))
        # hot-path accounting (serve_model's resident fast lane): which
        # route scored each request, how many host<->device round-trips
        # were spent, and how many dispatched batches await readback
        self.hot_path = hot_path
        self._c_path = self.metrics.counter(
            "mmlspark_tpu_serving_path_total",
            "requests scored per hot-path route (resident/native/host)",
            labels=("server", "path"))
        # wire-protocol mix: which framing each accepted request arrived
        # in (json vs the zero-copy binary protocol, io_http/wire.py)
        self._c_proto = self.metrics.counter(
            "mmlspark_tpu_serving_protocol_requests_total",
            "requests received per wire protocol (json/binary)",
            labels=("server", "proto"))
        self._proto_counts = {"json": 0, "binary": 0}
        self._c_round_trips = _own(
            "mmlspark_tpu_serving_host_round_trips_total",
            "host<->device round-trips spent scoring (one per resident "
            "batch; the native route adds none)")
        self._g_readback = self.metrics.gauge(
            "mmlspark_tpu_serving_readback_inflight_depth",
            "resident batches dispatched, reply fetch still pending",
            labels=("server",)).labels(server=self.server_label)
        # declare the process-wide executable-cache and breaker families on
        # this registry so a scrape shows them even before they move
        ensure_cache_metrics(self.metrics)
        _breaker_metrics(self.metrics)
        self._draining = False
        self._counter_lock = make_lock("ServingServer._counter_lock")
        # rolling service latencies (seconds, enqueue -> reply written)
        self._latencies: collections.deque[float] = collections.deque(maxlen=8192)
        # distributed tracing: None resolves the process-default tracer
        # PER REQUEST so tests can swap it after the server started
        self._tracer = tracer
        # readiness (the /readyz contract): with a warmup request the
        # server reports ready only after warmup() has scored every
        # bucket-ladder rung — the executable cache holds every shape the
        # batcher can produce, so steady state is zero-recompile. Extra
        # liveness probes (e.g. the reverse tunnel) hook in via
        # health_probes and surface under /healthz.
        self.warmup_request = warmup_request
        self._warm_rungs: set[int] = set()
        self._warmed = threading.Event()
        self.health_probes: dict[str, Callable[[], Any]] = {}

    # read-only views over the registry children — the historical int
    # attributes, same exact per-server values
    @property
    def requests_seen(self) -> int:
        return int(self._c_seen.value)

    @property
    def requests_accepted(self) -> int:
        return int(self._c_accepted.value)

    @property
    def requests_answered(self) -> int:
        return int(self._c_answered.value)

    @property
    def requests_shed(self) -> int:
        return int(self._c_shed.value)

    @property
    def requests_expired(self) -> int:
        return int(self._c_expired.value)

    @property
    def requests_failed(self) -> int:
        return int(self._c_failed.value)

    def protocol_counts(self) -> dict:
        """Accepted requests per wire protocol (the info() `protocols`
        block diagnose --serving prints as the protocol mix)."""
        with self._counter_lock:
            return dict(self._proto_counts)

    # -- health / readiness --------------------------------------------- #

    def tracer(self):
        if self._tracer is not None:
            return self._tracer
        from ..observability.tracing import get_tracer

        return get_tracer()

    @property
    def ready(self) -> bool:
        """Liveness is /healthz; THIS is /readyz: started, not draining,
        and (when a warmup request is configured) every bucket-ladder rung
        scored once so the executable cache is fully populated."""
        if self._server is None or self._draining:
            return False
        if self.warmup_request is None:
            return True
        if self.bucketer is not None:
            with self._counter_lock:
                return set(self.bucketer.ladder) <= self._warm_rungs
        return self._warmed.is_set()

    def warmup(self, request: "HTTPRequestData | None" = None) -> int:
        """Score `request` once per bucket-ladder rung (one batch without
        a ladder), populating the executable cache so live traffic never
        pays a compile. Runs in a background thread at start() when
        `warmup_request` is set; callable directly for explicit warmup
        (e.g. before a rolling cutover). Returns rungs warmed."""
        req = request if request is not None else self.warmup_request
        if req is None:
            raise ValueError("no warmup request configured or given")
        if self.handler is None:
            raise RuntimeError("warmup scores through the continuous-mode "
                               "handler; batch mode warms via its query")
        rungs = (list(self.bucketer.ladder) if self.bucketer is not None
                 else [1])
        for rung in rungs:
            out = self.handler(Table({"request": [req] * rung}))
            if len(out["reply"]) != rung:
                raise ValueError(
                    f"warmup handler returned {len(out['reply'])} replies "
                    f"for a batch of {rung}")
            if self.hot_path is not None:
                # compile the resident executable for this rung, verify
                # its reply bytes against the handler's, and measure the
                # native-vs-resident crossover that routes live traffic
                self.hot_path.warm_rung(
                    self.handler, req, rung,
                    [r.entity for r in out["reply"]])
            with self._counter_lock:
                self._warm_rungs.add(rung)
        self._warmed.set()
        return len(rungs)

    def _warmup_async(self) -> None:
        try:
            self.warmup()
        except Exception:  # noqa: BLE001 — a failed warmup keeps /readyz 503
            pass

    def health(self) -> dict:
        """The /healthz payload: process-alive facts + extra probe
        results (a failing probe reports its error, never raises)."""
        probes = {}
        for name, fn in list(self.health_probes.items()):
            try:
                probes[name] = fn()
            except Exception as e:  # noqa: BLE001 — probe failure is data
                probes[name] = {"error": str(e)}
        with self._counter_lock:
            warm = sorted(self._warm_rungs)
        return {"status": "ok", "draining": self._draining,
                "ready": self.ready, "pending": self._load(),
                "warm_rungs": warm,
                "probes": probes}

    # ------------------------------------------------------------------ #

    def start(self) -> "ServingServer":
        outer = self

        class Handler(SingleSegmentHandler):
            # HTTP/1.1 keep-alive: one connection (and one server thread)
            # serves a client's whole request stream instead of paying TCP
            # setup + thread spawn per request — the tail-latency source on
            # the continuous path. Requires exact Content-Length on every
            # response (sent below).
            protocol_version = "HTTP/1.1"
            # idle keep-alive connections time out so stop() quiesces:
            # handle_one_request treats a socket timeout as end-of-stream
            # and the per-connection thread exits. This short window applies
            # only BETWEEN requests — do_POST widens it while a request body
            # is in flight, so a slow sender isn't dropped mid-upload.
            timeout = 5.0
            body_timeout = 60.0

            def do_POST(self):  # noqa: N802 — http.server API
                # the idle timeout covered the wait for the request line;
                # reading the body gets the slow-sender grace window, and
                # the finally below restores the idle window for keep-alive
                self.connection.settimeout(self.body_timeout)
                try:
                    path, _, query = self.path.partition("?")
                    if path == "/flightrecorder/dump":
                        self._dump_recorder(query)
                        return
                    self._handle_post()
                finally:
                    self.connection.settimeout(self.timeout)

            def _dump_recorder(self, query: str) -> None:
                # the fleet-wide dump broadcast (ServingFleet.dump_all):
                # a driver-side trigger makes EVERY replica flush its
                # black box while the evidence is still in the ring
                import urllib.parse

                length = int(self.headers.get("Content-Length", 0))
                if length:
                    self.rfile.read(length)
                trigger = urllib.parse.parse_qs(query).get(
                    "trigger", ["remote"])[0]
                rec = outer.recorder
                path = (rec.trigger_dump(trigger, force=True)
                        if rec is not None else None)
                self._reply_json(200, {"dumped": path is not None,
                                       "path": path})

            def _handle_post(self):
                # bind this request into the caller's trace: a client-
                # injected W3C traceparent becomes the parent of the
                # serving.request span, so the merged fleet trace shows
                # client -> gateway -> replica as one tree
                tracer = outer.tracer()
                remote = tracer.extract(self.headers.get("traceparent"))
                with tracer.start_span("serving.request", parent=remote,
                                       path=self.path,
                                       server=outer.server_label) as span:
                    self._serve_post(span)

            def _serve_post(self, span):
                outer._c_seen.inc()
                if self.headers.get("Transfer-Encoding"):
                    # chunked bodies aren't framed by Content-Length; reading
                    # them wrong would desync the keep-alive stream — refuse
                    # and drop the connection (411 Length Required)
                    self.send_response(411)
                    self.send_header("Content-Length", "0")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.close_connection = True
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                # admission control BEFORE parking: draining servers and
                # full queues shed with 503 + Retry-After (the bounded-
                # queue contract) instead of queueing without bound and
                # timing everyone out later. The body was already read so
                # the keep-alive stream stays framed.
                if outer._draining or (
                        outer.max_pending and
                        outer._load() >= outer.max_pending):
                    outer._c_shed.inc()
                    if outer.recorder is not None:
                        outer.recorder.note_shed()
                    span.set(status=503)
                    self.send_response(503)
                    self.send_header("Retry-After", "1")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                outer._c_accepted.inc()
                proto = ("binary" if is_wire_content_type(
                    self.headers.get("Content-Type")) else "json")
                outer._c_proto.labels(server=outer.server_label,
                                      proto=proto).inc()
                with outer._counter_lock:
                    outer._proto_counts[proto] += 1
                now = time.perf_counter()
                ex = _Exchange(HTTPRequestData(
                    method="POST", url=self.path,
                    headers=dict(self.headers), entity=body,
                ), enqueued_at=now,
                    deadline=(now + outer.request_deadline_s
                              if outer.request_deadline_s is not None
                              else None),
                    span=span)
                ex_id = None
                if outer.mode == "batch":
                    ex_id = str(next(outer._id_counter))
                    # journal BEFORE parking: a journaled reply always has
                    # its accept record on disk first
                    if outer.journal is not None:
                        outer.journal.record_accept(ex_id, ex.request)
                    with outer._counter_lock:
                        outer._pending[ex_id] = ex
                else:
                    outer._queue.put(ex)
                    outer._g_queue.set(outer._load())
                wait_s = outer.reply_timeout_s
                if outer.request_deadline_s is not None:
                    wait_s = min(wait_s, outer.request_deadline_s)
                if not ex.event.wait(wait_s):
                    if ex_id is not None and outer.journal is None:
                        # dead client: stop re-serving it via get_batch().
                        # With a journal the request is DATA in the stream
                        # (accepted = must be processed): it stays parked,
                        # its reply lands in the journal even though this
                        # connection gets a 504.
                        with outer._counter_lock:
                            outer._pending.pop(ex_id, None)
                    outer._c_expired.inc()
                    if outer.recorder is not None:
                        outer.recorder.note_expired()
                    span.set(status=504)
                    self.send_response(504)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                resp = ex.response or HTTPResponseData(500, "no response")
                resp = _negotiate_reply(resp, ex.request)
                span.set(status=resp.status_code or 500)
                self.send_response(resp.status_code or 500)
                entity = resp.entity or b""
                for k, v in resp.headers.items():
                    # forwarded upstream responses can carry stale framing /
                    # hop-by-hop headers (clients.py de-chunks entities but
                    # keeps the original header dict); only the ACTUAL
                    # entity length keeps the keep-alive stream framed
                    if k.lower() not in ("content-length", "transfer-encoding",
                                         "connection", "keep-alive"):
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(entity)))
                self.end_headers()
                if entity:
                    self.wfile.write(entity)
                elapsed = time.perf_counter() - ex.enqueued_at
                outer._c_answered.inc()
                outer._h_latency.observe(elapsed,
                                         exemplar=outer._exemplar_for(ex, span))
                rec = outer.recorder
                if rec is not None:
                    rec.record_request(
                        trace_id=format(getattr(span, "trace_id", 0), "032x"),
                        route=ex.route or "", bucket=ex.bucket,
                        queue_depth=outer._load(), latency_s=elapsed,
                        status=resp.status_code or 500,
                        readback_lag=ex.readback_lag)
                    rec.maybe_tick(outer.metrics)
                with outer._counter_lock:
                    outer._latencies.append(elapsed)

            def _reply_json(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — health/info + /metrics
                # Prometheus scrape surface; every other path keeps the
                # info JSON (FleetRendezvous polls GET / per replica)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = outer.metrics.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/healthz":
                    # liveness: answering at all IS the signal — 200 even
                    # while draining (restarting a draining server would
                    # drop the very requests the drain protects)
                    self._reply_json(200, outer.health())
                    return
                if path == "/readyz":
                    # readiness: load balancers route only to 200
                    ready = outer.ready
                    with outer._counter_lock:
                        warm = sorted(outer._warm_rungs)
                    self._reply_json(200 if ready else 503, {
                        "ready": ready, "draining": outer._draining,
                        "warm_rungs": warm,
                        "ladder": (list(outer.bucketer.ladder)
                                   if outer.bucketer is not None else None),
                    })
                    return
                # process-wide executable-cache counters: steady-state
                # recompiles staying flat is the bucket ladder working
                exe = cache_stats()
                info = json.dumps({
                    "name": "mmlspark_tpu.serving",
                    "host": outer.host, "port": outer.port,
                    "mode": outer.mode,
                    "seen": outer.requests_seen,
                    "answered": outer.requests_answered,
                    "shed": outer.requests_shed,
                    "expired": outer.requests_expired,
                    "failed": outer.requests_failed,
                    "ready": outer.ready,
                    "executable_cache_hits": exe["hits"],
                    "executable_cache_misses": exe["misses"],
                    "executable_cache_recompiles": exe["recompiles"],
                    # wall-clock seconds spent inside builders, process-
                    # wide + the slowest (family, shape) entries of the
                    # hot path's own cache — where startup time went
                    "compile_seconds_total": round(
                        exe.get("compile_seconds", 0.0), 6),
                    "compile_ledger": (
                        outer.hot_path.executor.segment
                        ._exec_cache.compile_ledger(top=8)
                        if outer.hot_path is not None else None),
                    "bucket_ladder": (list(outer.bucketer.ladder)
                                      if outer.bucketer is not None
                                      else [outer.max_batch_size]),
                    "latency": outer.latency_stats(),
                    "protocols": outer.protocol_counts(),
                    "hot_path": (outer.hot_path.snapshot()
                                 if outer.hot_path is not None else None),
                    "profiler": outer._profiler_info(),
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(info)))
                self.end_headers()
                self.wfile.write(info)

            def log_message(self, *a):  # silence per-request stderr noise
                pass

        self._server = _DeepBacklogServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        st = threading.Thread(target=self._server.serve_forever, daemon=True)
        st.start()
        self._threads = [st]
        if self.mode == "continuous":
            bt = threading.Thread(target=self._batch_loop, daemon=True)
            bt.start()
            self._threads.append(bt)
            if self.warmup_request is not None:
                wt = threading.Thread(target=self._warmup_async, daemon=True)
                wt.start()
                self._threads.append(wt)
        return self

    def _load(self) -> int:
        """Requests parked and not yet answered — the shed/drain signal."""
        if self.mode == "batch":
            with self._counter_lock:
                return len(self._pending)
        return self._queue.qsize()

    def stop(self, drain: "bool | None" = None) -> None:
        """Graceful by default on the continuous path: new requests shed
        with 503 while the batcher finishes what was already admitted
        (up to drain_timeout_s), THEN the loops stop — in-flight clients
        get answers instead of resets. drain=False skips the wait."""
        self._draining = True
        if drain is None:
            drain = self.mode == "continuous"
        if drain and self.mode == "continuous" and self._server is not None:
            deadline = time.monotonic() + self.drain_timeout_s
            while self._load() > 0 and time.monotonic() < deadline:
                time.sleep(0.005)
        self._stop.set()
        if self._server:
            self._server.shutdown()
            self._server.server_close()
        if self.journal is not None:
            self.journal.close()
        if self.recorder is not None:
            try:
                self.recorder.trigger_dump("drain", force=True)
            except Exception:
                pass

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def _exemplar_for(self, ex: "_Exchange", span) -> "dict | None":
        """The OpenMetrics exemplar for one answered request: trace_id is
        the join key (postmortem + fleet merge resolve it to the exact
        trace), route/bucket/readback_lag say WHICH lane served it."""
        if not self.exemplars:
            return None
        trace_id = getattr(span, "trace_id", 0)
        if not trace_id and ex.route is None:
            return None
        labels: dict[str, str] = {}
        if trace_id:
            labels["trace_id"] = format(trace_id, "032x")
        if ex.route:
            labels["route"] = ex.route
        if ex.bucket is not None:
            labels["bucket"] = str(ex.bucket)
        if ex.readback_lag is not None:
            labels["readback_lag"] = str(ex.readback_lag)
        return labels or None

    def latency_stats(self) -> dict[str, float]:
        """p50/p99 service latency (ms) over the rolling window — the measured
        version of the reference's ~1 ms continuous-mode claim
        (docs/mmlspark-serving.md:10-11)."""
        with self._counter_lock:
            lat = list(self._latencies)
        if not lat:
            return {"n": 0, "p50_ms": float("nan"), "p99_ms": float("nan")}
        arr = np.asarray(lat) * 1e3
        return {
            "n": len(arr),
            "p50_ms": float(np.percentile(arr, 50)),
            "p99_ms": float(np.percentile(arr, 99)),
        }

    def _profiler_info(self) -> dict:
        """The info() `profiler` block: the process profiler's phase
        attribution (diagnose --perf renders it for a live server).
        Fail-soft so a broken profiler can never break GET /."""
        try:
            from ..observability.profiler import get_profiler

            return get_profiler().snapshot()
        except Exception:  # noqa: BLE001 — info must always answer
            return {"enabled": False, "ledgers": 0, "attribution": []}

    def reset_latency_stats(self) -> None:
        """Clear the rolling latency window (e.g. after warm-up requests)."""
        with self._counter_lock:
            self._latencies.clear()

    # -- batch ("micro-batch source") mode ------------------------------- #

    def get_batch(self, max_rows: int | None = None) -> Table:
        """Drain pending requests into a Table with `id` + `request` columns
        (reference `HTTPSource.getBatch`, HTTPSource.scala:46-225). The
        caller scores the table and completes the requests with `reply`."""
        if self.mode != "batch":
            raise RuntimeError("get_batch() is only available in batch mode")
        with self._counter_lock:
            # journaled requests are stream DATA (accepted = must be
            # processed) and never expire; without a journal an expired
            # exchange answers 504 and leaves the replay set
            if self.request_deadline_s is not None and self.journal is None:
                now = time.perf_counter()
                for ex_id in [i for i, ex in self._pending.items()
                              if ex.deadline is not None
                              and now > ex.deadline]:
                    ex = self._pending.pop(ex_id)
                    ex.response = HTTPResponseData(
                        504, "deadline exceeded before scoring")
                    ex.event.set()
                    self._c_expired.inc()
            ids = list(self._pending)
            if max_rows is not None:
                ids = ids[:max_rows]
            requests = [self._pending[i].request for i in ids]
        return Table({"id": ids, "request": requests})

    def reply(self, ids: list[str], responses: list[HTTPResponseData],
              record: bool = True) -> None:
        """Complete batch-mode requests by id (reference `HTTPSink` keyed by
        (name, partitionId, requestId), HTTPSourceV2.scala:421-476).

        record=False answers live clients WITHOUT journaling the reply as
        the request's final answer — the transient-failure path: a 500 for
        a failed batch must leave the request in the durable replay set
        (the reference's failed micro-batch reruns after restart)."""
        if self.mode != "batch":
            raise RuntimeError("reply() is only available in batch mode")
        if len(ids) != len(responses):
            raise ValueError(
                f"{len(responses)} responses for {len(ids)} request ids — "
                "repliers must answer every drained request"
            )
        for ex_id, resp in zip(ids, responses):
            ex_id = str(ex_id)
            if self.journal is not None:
                if self.journal.replied(ex_id):
                    # already answered durably (e.g. a batch raced a
                    # restart's replay): exactly-once drops the duplicate
                    with self._counter_lock:
                        self._pending.pop(ex_id, None)
                    continue
                if record:
                    self.journal.record_reply(ex_id, resp)
            with self._counter_lock:
                ex = self._pending.pop(ex_id, None)
            if ex is not None:
                ex.response = resp
                ex.event.set()

    def reply_table(self, table: Table) -> None:
        """reply() over a Table holding `id` + `reply` columns (the shape
        `make_reply` produces when the `id` column is carried through)."""
        self.reply(list(table["id"]), list(table["reply"]))

    # ------------------------------------------------------------------ #

    def _batch_loop(self) -> None:
        hp = self.hot_path
        # lag-1 overlapped readback: a resident batch's reply fetch is
        # deferred until the NEXT batch has been dispatched (or the queue
        # goes idle), so reply serialization of batch N runs while the
        # device computes batch N+1 — dispatch never blocks on readback
        readback = (AsyncReadback(self._complete_resident,
                                  lag=hp.readback_lag)
                    if hp is not None else None)
        while not self._stop.is_set():
            if (readback is not None and readback.pending
                    and self._queue.empty()):
                # nothing queued: force pending replies out NOW instead of
                # holding them for a next batch that may never come — the
                # overlap window is only ever other requests' compute
                readback.drain()
                self._g_readback.set(0)
                continue
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            # Everything already queued joins the batch at zero latency
            # cost; batching happens naturally through backpressure —
            # requests arriving while the handler scores batch N drain
            # into batch N+1. max_latency_ms (default 0) is an OPT-IN
            # collection window for device-efficiency tuning: it adds its
            # full length to p50 at low concurrency (measured 1.00 ->
            # 0.59 ms server p50 when the old 0.2 ms window was removed).
            deadline = time.monotonic() + self.max_latency_ms / 1e3
            while len(batch) < self.max_batch_size:
                try:
                    # timeout=0 == non-blocking get, so past the deadline
                    # (always, when the window is 0) this drains whatever
                    # is queued and stops at the first Empty
                    batch.append(self._queue.get(
                        timeout=max(deadline - time.monotonic(), 0)))
                except queue.Empty:
                    break
            # expired exchanges answer 504 HERE and never occupy a batch
            # slot — scoring them would waste device time on a reply the
            # client already gave up on (its wait is capped by the same
            # deadline)
            now = time.perf_counter()
            expired = [ex for ex in batch
                       if ex.deadline is not None and now > ex.deadline]
            if expired:
                self._c_expired.inc(len(expired))
                if self.recorder is not None:
                    for _ in expired:
                        self.recorder.note_expired()
                for ex in expired:
                    ex.response = HTTPResponseData(
                        504, "deadline exceeded before scoring")
                    ex.event.set()
                batch = [ex for ex in batch
                         if ex.deadline is None or now <= ex.deadline]
                if not batch:
                    continue
            self._g_queue.set(self._load())
            # stamped BEFORE scoring (and re-stamped on each fallback) so
            # the handler thread — which may complete the exchange the
            # moment scoring sets its event — always reads the final
            # route/bucket into the latency exemplar
            target = (self.bucketer.bucket_for(len(batch))
                      if self.bucketer is not None else len(batch))
            route = "host"
            if hp is not None:
                route = hp.route_for(target)
                self._stamp_route(batch, route, target)
                if route == hp.resident_label and not self._score_resident(
                        batch, target, readback):
                    # batch outside the cached schema or the device
                    # precondition — the native walk is exact for ANY
                    # float64 payload, so it catches what resident can't
                    route = "native" if hp.native_fn is not None else "host"
                    self._stamp_route(batch, route, target)
                if route == "native" and not self._score_native(batch):
                    route = "host"
                    self._stamp_route(batch, route, target)
            else:
                self._stamp_route(batch, route, target)
            if route == "host":
                self._score_batch(batch)
            if hp is not None:
                hp.note(route, len(batch))
                self._c_path.labels(server=self.server_label,
                                    path=route).inc(len(batch))
        if readback is not None:
            readback.drain()

    @staticmethod
    def _stamp_route(batch: "list[_Exchange]", route: str,
                     bucket: int) -> None:
        for ex in batch:
            ex.route, ex.bucket = route, bucket

    def _score_resident(self, batch: "list[_Exchange]", target: int,
                        readback: AsyncReadback) -> bool:
        """Decode + upload + launch one batch on the resident executor;
        replies complete through the readback window (see _batch_loop).
        False = the batch fell outside the cached schema and the caller
        must re-route it to the handler path."""
        hp = self.hot_path
        t_score = time.perf_counter()
        feats = hp.decoder.decode([ex.request for ex in batch], target)
        if feats is None:
            return False
        if hp.value_check(feats):
            # non-empty reason (e.g. floats not f32-representable): this
            # batch cannot run resident byte-identically.  Schema checks
            # were hoisted to warmup — only value-dependent hooks run here
            return False
        self._c_bucket.labels(server=self.server_label,
                              bucket=str(target)).inc()
        # the ledger opens only after the batch is committed to this
        # route (a declined batch would leave an uncommitted ledger);
        # the decode above IS the prepare phase, timed retroactively
        ledger = _prof_ledger(
            "request", hp.resident_label,
            span=batch[0].span if len(batch) == 1 else None,
            server=self.server_label, bucket=target)
        if ledger.armed:
            ledger.add("queue", max(t_score - batch[0].enqueued_at, 0.0))
            ledger.add("prepare", time.perf_counter() - t_score)
            ledger.note_pad(len(batch), target)
        try:
            outs = hp.executor.dispatch({hp.feature_col: feats},
                                        ledger=ledger)
        except Exception as e:  # noqa: BLE001 — batch failure -> 500s
            self._c_failed.inc(len(batch))
            for ex in batch:
                ex.response = _handler_error_response(e)
                ex.event.set()
            return True
        hp.note_resident_batch()
        self._c_round_trips.inc()
        readback.push((outs, batch, ledger, time.perf_counter()))
        depth = readback.pending
        for ex in batch:
            ex.readback_lag = depth
        self._g_readback.set(depth)
        with self._counter_lock:
            self._warm_rungs.add(target)
        return True

    def _complete_resident(self, item) -> None:
        """AsyncReadback's fetch callback: block on one in-flight batch's
        device results and write every exchange's reply. The dispatch ->
        drain gap is the lag-N readback hold — attributed to `queue`
        alongside the input wait, so the attribution table shows the
        latency the overlap window costs each request."""
        outs, batch, ledger, t_dispatched = item
        hp = self.hot_path
        if ledger.armed:
            ledger.add("queue",
                       max(time.perf_counter() - t_dispatched, 0.0))
        try:
            vals = hp.fetch_values(outs, len(batch), ledger=ledger)
            # reply materialization is host readback work too — without
            # it the phase sum can't explain the measured RTT
            with ledger.phase("d2h"):
                replies = hp.replies_for(
                    vals, binary_mask=[accepts_wire(ex.request.headers)
                                       for ex in batch])
        except Exception as e:  # noqa: BLE001 — batch failure -> 500s
            self._c_failed.inc(len(batch))
            replies = [_handler_error_response(e)] * len(batch)
        for ex, resp in zip(batch, replies):
            ex.response = resp
            ex.event.set()
        if ledger.armed:
            # server-side RTT for the batch's oldest request: enqueue ->
            # replies written (the 15% phase-coverage bar in diagnose)
            ledger.done(
                rtt_s=time.perf_counter() - batch[0].enqueued_at)

    def _score_native(self, batch: "list[_Exchange]") -> bool:
        """Score synchronously on the native C++ tree walk — zero
        host<->device round-trips, no padding (nothing compiles, so
        ragged sizes cost nothing); the small-batch side of the
        crossover. False = re-route to the handler path."""
        hp = self.hot_path
        t_score = time.perf_counter()
        feats = hp.decoder.decode([ex.request for ex in batch])
        if feats is None:
            return False
        ledger = _prof_ledger("request", "native",
                              server=self.server_label)
        if ledger.armed:
            ledger.add("queue", max(t_score - batch[0].enqueued_at, 0.0))
            ledger.add("prepare", time.perf_counter() - t_score)
        try:
            with ledger.phase("compute"):
                replies = hp.replies_for(
                    hp.native_values(feats),
                    binary_mask=[accepts_wire(ex.request.headers)
                                 for ex in batch])
        except Exception as e:  # noqa: BLE001 — batch failure -> 500s
            self._c_failed.inc(len(batch))
            replies = [_handler_error_response(e)] * len(batch)
        for ex, resp in zip(batch, replies):
            ex.response = resp
            ex.event.set()
        if ledger.armed:
            ledger.done(
                rtt_s=time.perf_counter() - batch[0].enqueued_at)
        return True

    def _score_batch(self, batch: "list[_Exchange]") -> None:
        """The handler path: pad to the bucket rung, score through
        `self.handler`, reply — serve_model's pre-hot-path behavior and
        the fallback every other route degrades to."""
        # a single-exchange batch scores INSIDE that request's span,
        # so a proxying handler's outbound http_send propagates the
        # same trace downstream (client -> gateway -> replica); multi-
        # request batches fan in, so serving.score stands alone
        tracer = self.tracer()
        parent = batch[0].span if len(batch) == 1 else None
        if parent is not None and not getattr(parent, "span_id", 0):
            parent = None
        t_score = time.perf_counter()
        with tracer.start_span("serving.score", parent=parent,
                               batch_rows=len(batch)) as sspan:
            ledger = _prof_ledger("request", "host", span=sspan,
                                  server=self.server_label)
            if ledger.armed:
                ledger.add("queue",
                           max(t_score - batch[0].enqueued_at, 0.0))
            target = None
            try:
                requests = [ex.request for ex in batch]
                if self.bucketer is not None:
                    target = self.bucketer.bucket_for(len(requests))
                    self._c_bucket.labels(
                        server=self.server_label,
                        bucket=str(target)).inc()
                    with ledger.phase("pad"):
                        requests = requests + \
                            [requests[-1]] * (target - len(requests))
                    ledger.note_pad(len(batch), target)
                table = Table({"request": requests})
                # the handler path scores host-side (or through its own
                # fused transform): the whole call is its compute phase
                with ledger.phase("compute"):
                    out = self.handler(table)
                replies = out["reply"]
                if len(replies) != len(requests):
                    raise ValueError(
                        f"handler returned {len(replies)} replies for a "
                        f"batch of {len(requests)} requests — handlers "
                        "must preserve row count and order"
                    )
                replies = list(replies)[:len(batch)]
                if target is not None:
                    # this rung's executable is compiled now — the
                    # readiness signal warmup() drives deliberately
                    with self._counter_lock:
                        self._warm_rungs.add(target)
            except Exception as e:  # noqa: BLE001 — batch failure -> 500s
                self._c_failed.inc(len(batch))
                sspan.set(error=str(e))
                replies = [_handler_error_response(e)] * len(batch)
        for ex, resp in zip(batch, replies):
            ex.response = resp
            ex.event.set()
        if ledger.armed:
            ledger.done(rtt_s=time.perf_counter() - batch[0].enqueued_at)


class MicroBatchQuery:
    """Streaming micro-batch engine for a batch-mode ServingServer — the
    role of Spark's streaming query over `readStream.server()` (the
    reference's HTTPSource getOffset/getBatch/commit tick loop,
    HTTPSource.scala:46-225; query lifecycle = start/stop/awaitTermination).

    Each tick drains pending requests (`get_batch`), runs `handler`
    (Table{id, request} -> Table{id, reply}), and completes the exchanges
    (`reply_table`). Handler errors 500 the affected batch instead of
    killing the query; `exception` records the last one.
    """

    def __init__(self, server: "ServingServer",
                 handler: Callable[[Table], Table],
                 trigger_interval_s: float = 0.05,
                 max_rows_per_batch: int | None = None,
                 compact_every_batches: int = 64):
        if server.mode != "batch":
            raise ValueError("MicroBatchQuery drives a mode='batch' server")
        self.server = server
        self.handler = handler
        self.trigger_interval_s = trigger_interval_s
        self.max_rows_per_batch = max_rows_per_batch
        # journal commit-trimming cadence (reference commit(),
        # DistributedHTTPSource.scala:308-343); 0 disables
        self.compact_every_batches = compact_every_batches
        self.batches_processed = 0
        self.rows_processed = 0
        self.exception: Exception | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "MicroBatchQuery":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self.server.get_batch(self.max_rows_per_batch)
            if len(batch) == 0:
                self._stop.wait(self.trigger_interval_s)
                continue
            ids = list(batch["id"])
            try:
                out = self.handler(batch)
                out_ids = [str(i) for i in out["id"]]
                if sorted(out_ids) != sorted(str(i) for i in ids):
                    # a partial/mismatched answer would leave requests
                    # parked and re-served every tick (same contract as the
                    # continuous loop's replies-per-batch guard)
                    raise ValueError(
                        f"handler answered {len(out_ids)} of {len(ids)} "
                        "drained requests — it must reply to every id"
                    )
                self.server.reply(out_ids, list(out["reply"]))
            except Exception as e:  # noqa: BLE001 — batch fails, query lives
                self.exception = e
                self.server._c_failed.inc(len(ids))
                # record=False: live clients get the 500, but the journal
                # keeps these requests UNANSWERED so a restart replays them
                # (transient failures must not commit as final answers)
                self.server.reply(
                    ids, [_handler_error_response(e)] * len(ids), record=False
                )
                if self.server.journal is not None:
                    # re-park the failed batch so THIS query retries it on a
                    # later tick (the clients already got their 500s; the
                    # retried replies land in the journal only) — without
                    # this, accepted-but-failed requests would wait for a
                    # full process restart even though the query recovered
                    reqs = list(batch["request"])
                    with self.server._counter_lock:
                        for ex_id, req in zip(ids, reqs):
                            ex_id = str(ex_id)
                            if not self.server.journal.replied(ex_id):
                                self.server._pending.setdefault(
                                    ex_id, _Exchange(req)
                                )
                    # breathe between retries of a failing handler instead
                    # of spinning the tick loop hot
                    self._stop.wait(self.trigger_interval_s)
            self.batches_processed += 1
            self.rows_processed += len(ids)
            if (self.server.journal is not None
                    and self.compact_every_batches
                    and self.batches_processed % self.compact_every_batches == 0):
                self.server.journal.compact()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def await_termination(self, timeout_s: float | None = None) -> bool:
        """Block until stop() (or timeout). Mirrors the reference query's
        awaitTermination; returns True if the query terminated."""
        if self._thread is None:
            return True
        self._thread.join(timeout_s)
        return not self._thread.is_alive()


def _build_hot_path(model, decoder: RequestDecoder,
                    output_col: str) -> "_HotPath | None":
    """serve_model's resident fast lane over `model`, or None when the
    model cannot host one (multi-segment plan, host-only stages, feature
    column mismatch) — the handler path then serves everything,
    unchanged."""
    try:
        rex = model.resident_executor()
    except Exception:  # noqa: BLE001 — the fast lane is strictly optional
        return None
    if isinstance(rex, str):
        return None
    if rex.upload_cols != ("features",) or output_col not in rex.download_cols:
        return None
    # the native tree walk can substitute for the WHOLE segment only when
    # the segment is exactly one stage exposing a host scorer
    native_fn = None
    stages = list(model.get("stages") or [])
    if len(stages) == 1:
        get_fn = getattr(stages[0], "native_score_fn", None)
        fn = get_fn() if callable(get_fn) else None
        if callable(fn):
            native_fn = fn
    # the hot path inherits the model's dispatch-pipeline window when one
    # is set (pipeline_depth generalizes readback_lag: same lag-K fetch,
    # framed as the bounded in-flight dispatch count)
    lag = model.get("pipeline_depth")
    if lag is None:
        lag = model.get("readback_lag")
    return _HotPath(rex, decoder, "features", output_col,
                    native_fn=native_fn, readback_lag=lag)


def serve_model(
    model,
    input_cols: "list[str] | None" = None,
    output_col: str = "prediction",
    host: str = "127.0.0.1",
    port: int = 0,
    fuse_pipeline: bool = True,
    mesh=None,
    hot_path: bool = True,
    **server_kw,
) -> ServingServer:
    """Deploy a fitted Transformer: JSON body {col: value, ...} in,
    {output_col: value} out (the `SparkServing - Deploying a Classifier`
    notebook flow).

    PipelineModel handlers score through the whole-pipeline fusion path
    (core/fusion.py) automatically: adjacent device-capable stages compile
    into one XLA program per request batch. `fuse_pipeline=False` keeps
    the stage-by-stage path. With `mesh` (a parallel.mesh mesh) the fused
    segments compile sharded over it — request batches score data-parallel
    across chips, byte-identical to the single-chip path.

    `hot_path=True` (default) additionally pins a fully-fused model's
    params on device ONCE and routes live batches between the resident
    executor and the native tree walk per the bucket crossover measured
    at warmup — byte-identical replies with no per-request re-staging.
    It silently stays on the handler path whenever the model cannot host
    a resident session.

    A fitted `SARModel` delegates to `recommendation.resident
    .serve_recommender` — same warmup/byte-identity/readback contract,
    top-k reply schema (`input_cols`/`output_col` are implied by the
    model and ignored)."""
    from ..core.fusion import FusedPipelineModel
    from ..core.pipeline import PipelineModel
    from ..recommendation.sar import SARModel

    if isinstance(model, SARModel):
        from ..recommendation.resident import serve_recommender

        return serve_recommender(model, host=host, port=port, mesh=mesh,
                                 hot_path=hot_path, **server_kw)

    if input_cols is None:
        raise TypeError("serve_model requires input_cols for this model")

    if (fuse_pipeline and isinstance(model, PipelineModel)
            and not isinstance(model, FusedPipelineModel)):
        from ..core.fusion import fuse

        model = fuse(model, mesh=mesh)
    elif mesh is not None and isinstance(model, FusedPipelineModel):
        model.set_mesh(mesh)

    # one decoder serves the handler fast path AND the hot-path routes,
    # so the cached schema and its hit/fallback counts stay unified
    decoder = RequestDecoder(input_cols)
    hp = None
    if hot_path and fuse_pipeline:
        hp_model = model
        if (not isinstance(model, PipelineModel)
                and hasattr(model, "device_kernel")):
            # a bare device-capable transformer (e.g. a fitted GBDT model)
            # hosts a resident session through a single-stage fused wrap;
            # the handler keeps scoring through the original model —
            # warmup verifies the two produce the same reply bytes
            from ..core.fusion import fuse

            try:
                hp_model = fuse(PipelineModel([model]), mesh=mesh)
            except Exception:  # noqa: BLE001 — fast lane is optional
                hp_model = None
        if isinstance(hp_model, FusedPipelineModel):
            hp = _build_hot_path(hp_model, decoder, output_col)

    def handler(table: Table) -> Table:
        reqs = list(table["request"])
        # the fast assembly is safe exactly when a resident session could
        # be built: that proves the model consumes the single "features"
        # column (a model reading per-field columns needs parse_request)
        feats = decoder.decode(reqs) if hp is not None else None
        if feats is not None:
            # fast assembly: one preallocated matrix straight from the
            # request bytes — parse_request's per-request dtype
            # re-inference and the per-column stack re-copy are both gone
            scored = model.transform(
                Table({"request": reqs, "features": feats}))
            return make_reply(scored, output_col)
        t = parse_request(table)
        missing = [c for c in input_cols if c not in t]
        if missing:
            raise ValueError(f"request missing fields {missing}")
        if "features" not in t and all(
            isinstance(t[c], np.ndarray) for c in input_cols
        ):
            feats = np.stack([np.asarray(t[c], np.float64) for c in input_cols], 1)
            t = t.with_column("features", feats)
        scored = model.transform(t)
        return make_reply(scored, output_col)

    # scoring is pure per-row, so batch-size bucketing is safe here and
    # keeps the jitted model's compiled-shape set closed
    server_kw.setdefault("bucket_batches", True)
    if hp is not None:
        # sharded resident dispatch needs every ladder rung divisible by
        # the mesh data axis; single-device this is 1 (no-op)
        server_kw.setdefault("bucket_multiple_of", hp.executor.data_axis_size)
    return ServingServer(handler, host=host, port=port, hot_path=hp,
                         **server_kw).start()


@dataclass
class ServiceInfo:
    """One serving replica's coordinates — the reference's
    `ServiceInfo{name, host, port, partitionId, localIp, publicIp}`
    collected by the driver rendezvous service (HTTPSourceV2.scala:118-165).

    `public_host`/`public_port` are the NAT-traversing coordinates when a
    reverse tunnel is attached (io_http.forwarding — the reference's
    PortForwarding path); clients outside the boundary route there, the
    rendezvous keeps polling the direct host:port."""

    name: str
    host: str
    port: int
    partition_id: int
    pid: int
    local_ip: str | None = None
    public_host: str | None = None
    public_port: int | None = None

    def to_dict(self) -> dict:
        return {"name": self.name, "host": self.host, "port": self.port,
                "partition_id": self.partition_id, "pid": self.pid,
                "local_ip": self.local_ip, "public_host": self.public_host,
                "public_port": self.public_port}

    @staticmethod
    def from_dict(d: dict) -> "ServiceInfo":
        pub_port = d.get("public_port")
        return ServiceInfo(name=d["name"], host=d["host"], port=int(d["port"]),
                           partition_id=int(d["partition_id"]),
                           pid=int(d.get("pid", 0)),
                           local_ip=d.get("local_ip"),
                           public_host=d.get("public_host"),
                           public_port=(int(pub_port)
                                        if pub_port is not None else None))


# the serving counter families the rendezvous reads out of scrapes
_SEEN = "mmlspark_tpu_serving_requests_seen_total"
_ANSWERED = "mmlspark_tpu_serving_requests_answered_total"
_LATENCY = "mmlspark_tpu_serving_latency_seconds"


class FleetRendezvous:
    """Driver-side rendezvous + fleet-state aggregator.

    Reference: continuous mode runs an HTTP service ON THE DRIVER that
    collects each partition reader's ServiceInfo and exposes the routing
    table (HTTPSourceV2.scala:118-165). Here:

      POST /register      — a replica announces its ServiceInfo at startup
      POST /metrics/push  — a draining replica flushes its final counters
      GET  /services      — the raw registry
      GET  /info          — LIVE aggregate: scrapes every replica's
                            /metrics through the MetricsAggregator and
                            reads counters/latency out of it (replicas
                            that fail to answer are reported unreachable,
                            not dropped silently)
      GET  /metrics       — the fleet-wide exposition: per-replica samples
                            under a `replica` label + merged samples under
                            replica="fleet" (+ SLO series when an engine
                            is attached via attach_slo)
      GET  /healthz       — fleet health: per-replica alive/ready

    `info()` and `/metrics` read the SAME aggregator state, so the JSON
    totals and the exposition's fleet-merged counters cannot disagree.
    """

    def __init__(self, name: str = "fleet", host: str = "127.0.0.1",
                 port: int = 0, clock: Any = None,
                 stale_after_s: float = 10.0):
        from ..observability.fleet import MetricsAggregator

        self.name = name
        self.host, self.port = host, port
        self._services: dict[int, ServiceInfo] = {}
        self._lock = make_lock("FleetRendezvous._lock")
        self._server: ThreadingHTTPServer | None = None
        self.aggregator = MetricsAggregator(
            urls=self._metric_urls, clock=clock,
            stale_after_s=stale_after_s)
        self.slo_engine = None

    def _metric_urls(self) -> dict[str, str]:
        return {str(s.partition_id): f"http://{s.host}:{s.port}/metrics"
                for s in self.services()}

    def attach_slo(self, engine) -> None:
        """Serve an SLOEngine's series from `/metrics` (it is evaluated on
        every scrape). Point the engine's `source` at `self.aggregator` so
        SLO math reads the same merged series the exposition shows."""
        self.slo_engine = engine

    # -- aggregate ------------------------------------------------------ #

    def services(self) -> list[ServiceInfo]:
        with self._lock:
            return [self._services[k] for k in sorted(self._services)]

    def register(self, info: ServiceInfo) -> None:
        with self._lock:
            self._services[info.partition_id] = info

    def _replica_latency(self, rid: str) -> dict:
        """p50/p99 (ms) estimated from the replica's scraped latency
        histogram — shaped like ServingServer.latency_stats()."""
        from ..observability.slo import SeriesReader

        reader = SeriesReader(self.aggregator.replica_snapshot(rid))
        h = reader.histogram(_LATENCY)
        n = int(h["count"])
        if n == 0:
            return {"n": 0, "p50_ms": float("nan"), "p99_ms": float("nan")}
        return {"n": n,
                "p50_ms": reader.histogram_quantile(_LATENCY, 0.5) * 1e3,
                "p99_ms": reader.histogram_quantile(_LATENCY, 0.99) * 1e3}

    def info(self) -> dict:
        """Scrape every replica's /metrics and merge fleet state. Totals
        come from the aggregator's retained counter families, so a
        gracefully-stopped replica's final flush stays counted."""
        ok = self.aggregator.scrape()
        replicas = []
        for svc in self.services():
            rid = str(svc.partition_id)
            entry: dict[str, Any] = svc.to_dict()
            if ok.get(rid):
                entry.update(
                    seen=int(self.aggregator.total(_SEEN, replica=rid)),
                    answered=int(self.aggregator.total(_ANSWERED,
                                                       replica=rid)),
                    latency=self._replica_latency(rid),
                    reachable=True)
            else:
                entry.update(reachable=False)
            replicas.append(entry)
        totals = {"seen": int(self.aggregator.total(_SEEN)),
                  "answered": int(self.aggregator.total(_ANSWERED))}
        return {"name": self.name, "replicas": replicas, "totals": totals,
                "n_replicas": len(replicas)}

    def fleet_health(self) -> dict:
        """Per-replica liveness/readiness polled from /healthz + /readyz."""
        import http.client

        replicas = {}
        for svc in self.services():
            rid = str(svc.partition_id)
            entry = {"alive": False, "ready": False}
            for path, key in (("/healthz", "alive"), ("/readyz", "ready")):
                conn = None
                try:
                    conn = http.client.HTTPConnection(svc.host, svc.port,
                                                      timeout=2)
                    conn.request("GET", path)
                    r = conn.getresponse()
                    r.read()
                    entry[key] = r.status == 200
                except (OSError, http.client.HTTPException):
                    pass
                finally:
                    if conn is not None:
                        conn.close()
            replicas[rid] = entry
        n_ready = sum(e["ready"] for e in replicas.values())
        return {"replicas": replicas, "n_replicas": len(replicas),
                "alive": sum(e["alive"] for e in replicas.values()),
                "ready": n_ready,
                "all_ready": bool(replicas) and n_ready == len(replicas)}

    def render_metrics(self) -> str:
        """The fleet exposition (+ SLO series when an engine is attached)."""
        self.aggregator.scrape()
        text = self.aggregator.render()
        if self.slo_engine is not None:
            try:
                self.slo_engine.evaluate()
                text += self.slo_engine.render()
            except Exception:  # noqa: BLE001 — SLO math must not kill scrape
                pass
        return text

    # -- HTTP surface --------------------------------------------------- #

    def start(self) -> "FleetRendezvous":
        outer = self

        class Handler(SingleSegmentHandler):
            def _reply(self, status: int, payload: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if path == "/register":
                    try:
                        info = ServiceInfo.from_dict(json.loads(body))
                    except (ValueError, KeyError):
                        self._reply(400, b'{"error": "bad ServiceInfo"}')
                        return
                    outer.register(info)
                    self._reply(200, b'{"registered": true}')
                    return
                if path == "/metrics/push":
                    # a draining replica's final flush: its counters stay
                    # in the fleet totals after the process exits
                    import urllib.parse

                    params = urllib.parse.parse_qs(query)
                    rid = params.get("replica", ["?"])[0]
                    try:
                        outer.aggregator.push(rid, body.decode("utf-8"),
                                              final=True)
                    except Exception:  # noqa: BLE001 — bad push, not a crash
                        self._reply(400, b'{"error": "bad exposition"}')
                        return
                    self._reply(200, b'{"pushed": true}')
                    return
                self._reply(404, b"{}")

            def do_GET(self):  # noqa: N802
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    payload = outer.render_metrics().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if path == "/services":
                    body = json.dumps(
                        [s.to_dict() for s in outer.services()]
                    ).encode()
                elif self.path == "/info":
                    body = json.dumps(outer.info()).encode()
                elif path == "/healthz":
                    health = outer.fleet_health()
                    payload = json.dumps(health).encode()
                    self._reply(200 if health["all_ready"] else 503, payload)
                    return
                else:
                    self._reply(404, b"{}")
                    return
                self._reply(200, body)

            def log_message(self, *a):
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def _register_with_rendezvous(rendezvous_url: str, info: ServiceInfo) -> None:
    import http.client
    import urllib.parse

    u = urllib.parse.urlparse(rendezvous_url)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
    conn.request("POST", "/register", body=json.dumps(info.to_dict()).encode(),
                 headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    r.read()
    conn.close()
    if r.status != 200:
        raise IOError(f"rendezvous register failed: {r.status}")


def _push_final_metrics(rendezvous_url: str, partition_id: int,
                        text: str) -> None:
    import http.client
    import urllib.parse

    u = urllib.parse.urlparse(rendezvous_url)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
    conn.request("POST", f"/metrics/push?replica={partition_id}",
                 body=text.encode(), headers={"Content-Type": "text/plain"})
    r = conn.getresponse()
    r.read()
    conn.close()
    if r.status != 200:
        raise IOError(f"metrics push failed: {r.status}")


def _fleet_worker(handler_factory, conn, server_kw, partition_id=0,
                  rendezvous_url=None, forwarding=None,
                  trace_dir=None, flight_recorder_dir=None) -> None:
    """Child-process entry: build the handler locally (models must not cross
    the process boundary — the reference re-creates per-JVM servers the same
    way, DistributedHTTPSource.scala:244-291), optionally open a reverse
    tunnel to the public gateway (the HTTPSourceV2 `forwarding.*` path,
    HTTPSourceV2.scala:363-372), announce ServiceInfo to the driver
    rendezvous, and serve until terminated."""
    import os
    import signal

    from .forwarding import establish_forward, get_local_ip

    rec = None
    if flight_recorder_dir:
        from ..observability.recorder import (FlightRecorder,
                                              set_default_recorder)

        rec = FlightRecorder(dump_dir=flight_recorder_dir,
                             process=f"replica-{partition_id}")
        # the process default, so gateway/autoscaler/supervisor code
        # running in this replica records into the same ring
        set_default_recorder(rec)
        server_kw = dict(server_kw, recorder=rec)
    srv = ServingServer(handler_factory(), **server_kw).start()
    # SIGTERM (ServingFleet.stop) begins the GRACEFUL sequence below:
    # shed new work, drain what was already admitted (srv.stop's default
    # continuous-mode drain), flush final counters to the rendezvous, and
    # export the replica's trace — so stopping the fleet loses neither
    # in-flight requests nor their telemetry. The fleet's hard kill()
    # stays as the timeout fallback for a worker stuck draining.
    shutdown = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: shutdown.set())
    fwd = None
    if forwarding is not None:
        fwd = establish_forward(srv.port, forwarding, local_host=srv.host)
        # a dead tunnel must surface in /healthz, not blackhole traffic
        srv.health_probes["forwarding"] = fwd.status
    if rendezvous_url:
        _register_with_rendezvous(rendezvous_url, ServiceInfo(
            name="mmlspark_tpu.serving", host=srv.host, port=srv.port,
            partition_id=partition_id, pid=os.getpid(),
            local_ip=get_local_ip(),
            public_host=fwd.remote_host if fwd else None,
            public_port=fwd.remote_port if fwd else None,
        ))
    conn.send((srv.host, srv.port))
    try:
        shutdown.wait()
        if rec is not None:
            rec.record_transition("replica", "sigterm",
                                  partition_id=partition_id)
        srv.stop()  # graceful: drains in-flight requests first (and the
        # recorder, when armed, dumps with trigger "drain")
        if rendezvous_url:
            try:
                _push_final_metrics(rendezvous_url, partition_id,
                                    srv.metrics.render_prometheus())
            except Exception:  # noqa: BLE001 — rendezvous may be gone
                pass
        if trace_dir:
            try:
                from ..observability.tracing import get_tracer

                get_tracer().export_jsonl(os.path.join(
                    trace_dir, f"replica-{partition_id}.jsonl"))
            except Exception:  # noqa: BLE001 — tracing is best-effort
                pass
    finally:
        if fwd is not None:
            fwd.close()


class ServingFleet:
    """Distributed serving: one ServingServer PROCESS per "host".

    Reference: DistributedHTTPSource's per-executor-JVM `JVMSharedServer`
    (DistributedHTTPSource.scala:89-343) — here each host is a real OS
    process with its own handler instance (clients spread requests across
    `urls`, the role of the reference's load balancer).

    `handler_factory` must be a picklable zero-arg callable returning the
    `handler(Table) -> Table` for that host.

    A `FleetRendezvous` runs on the driver: every replica registers its
    ServiceInfo at startup (HTTPSourceV2.scala:118-165), and `info()` /
    the rendezvous `GET /info` endpoint aggregates live per-replica
    counters into fleet totals.

    Membership is dynamic: `kill()` prunes the dead replica from `urls`,
    `respawn(index)` refills a slot through the same startup handshake,
    `scale_to(n)` grows/shrinks the fleet (shrink = graceful drain), and
    `rolling_swap(new_handler_factory)` replaces every replica's handler
    with zero downtime. `watch(callback)` observes membership changes —
    io_http.gateway.ServingGateway attaches itself this way so its
    routing table tracks the live set."""

    def __init__(self, handler_factory: Callable[[], Callable[[Table], Table]],
                 n_hosts: int = 2, start_timeout_s: float = 60.0,
                 rendezvous: bool = True, forwarding=None,
                 trace_dir: "str | None" = None,
                 flight_recorder_dir: "str | None" = None,
                 timeline_dir: "str | None" = None,
                 timeline_interval_s: float = 5.0,
                 timeline_keep: int = 8,
                 stop_timeout_s: float = 15.0, clock: Any = None,
                 stale_after_s: float = 10.0, **server_kw):
        self.handler_factory = handler_factory
        self.n_hosts = n_hosts
        self.start_timeout_s = start_timeout_s
        self.server_kw = server_kw
        # io_http.forwarding.ForwardingOptions: every replica opens its own
        # reverse tunnel to the gateway and registers the public coords
        # (HTTPSourceV2's forwarding.enabled path)
        self.forwarding = forwarding
        # when set, each gracefully-stopped replica exports its spans to
        # trace_dir/replica-N.jsonl (merge with Tracer.merge_jsonl)
        self.trace_dir = trace_dir
        # when set, every replica arms a FlightRecorder dumping into this
        # directory (tools/diagnose.py --postmortem merges the dumps)
        self.flight_recorder_dir = flight_recorder_dir
        # when set, a TimelineRecorder runs on the DRIVER beside the
        # rendezvous aggregator, persisting the merged fleet scrape as
        # segment files (tools/diagnose.py --history replays them);
        # requires rendezvous=True — there is no fleet view without it
        self.timeline_dir = timeline_dir
        self.timeline_interval_s = float(timeline_interval_s)
        self.timeline_keep = int(timeline_keep)
        if timeline_dir is not None and not rendezvous:
            raise ValueError("timeline_dir needs rendezvous=True "
                             "(the recorder samples the aggregator)")
        self.timeline: "Any | None" = None
        # how long stop() waits for the graceful drain-and-flush before
        # falling back to a hard kill
        self.stop_timeout_s = stop_timeout_s
        # slot-indexed bookkeeping: _procs[slot] may hold a dead process
        # (killed / retired); _url_of maps LIVE slots to their URLs and
        # `urls` is rebuilt from it, so a crashed replica never lingers
        # in the routing view
        self._procs: list[multiprocessing.Process] = []
        self._url_of: dict[int, str] = {}
        self.urls: list[str] = []
        # fresh partition id per spawned process, NEVER reused: the
        # aggregator retains a dead replica's counters for monotone fleet
        # totals, so a respawn restarting the same id at zero would walk
        # the totals backwards
        self._next_part = 0
        # slots drained ON PURPOSE (retire/scale-down) — dead_slots()
        # excludes them so self-healing never resurrects a scale-down
        self._retired: set[int] = set()
        self._watchers: list[Callable[[str, str], None]] = []
        self._fleet_lock = make_rlock("ServingFleet._fleet_lock")
        # the injectable clock drives the startup wait loop and the
        # rendezvous aggregator's staleness logic — chaos tests pass a
        # FakeClock so dead-replica detection needs zero real waiting
        if clock is None:
            from ..resilience.policy import SYSTEM_CLOCK

            clock = SYSTEM_CLOCK
        self.clock = clock
        self.rendezvous: FleetRendezvous | None = (
            FleetRendezvous(name="mmlspark_tpu.fleet", clock=clock,
                            stale_after_s=stale_after_s)
            if rendezvous else None
        )

    # -- membership bookkeeping ----------------------------------------- #

    @staticmethod
    def _record_transition(action: str, **detail) -> None:
        """Driver-side fleet transitions land in the driver's black box
        (the process-default recorder, armed once anything configures a
        flight_recorder_dir on it)."""
        try:
            from ..observability.recorder import get_recorder

            get_recorder().record_transition("fleet", action, **detail)
        except Exception:  # noqa: BLE001 — telemetry stays optional
            pass

    def watch(self, callback: Callable[[str, str], None]) -> None:
        """Register `callback(event, url)` for membership changes; event
        is "added" (replica live and warm) or "removed" (about to drain
        or already dead). The gateway admits/ejects through this."""
        self._watchers.append(callback)

    def _notify(self, event: str, url: str) -> None:
        for cb in list(self._watchers):
            try:
                cb(event, url)
            except Exception:  # noqa: BLE001 — watchers must not kill ops
                pass

    def _set_url(self, slot: int, url: str) -> None:
        with self._fleet_lock:
            self._url_of[slot] = url
            self.urls = [self._url_of[s] for s in sorted(self._url_of)]
        self._notify("added", url)

    def _drop_url(self, slot: int) -> None:
        with self._fleet_lock:
            url = self._url_of.pop(slot, None)
            self.urls = [self._url_of[s] for s in sorted(self._url_of)]
        if url is not None:
            self._notify("removed", url)

    def live_slots(self) -> list[int]:
        with self._fleet_lock:
            return sorted(self._url_of)

    def dead_slots(self) -> list[int]:
        """Slots whose process died WITHOUT being retired on purpose —
        the self-healing respawn set (FleetAutoscaler polls this)."""
        with self._fleet_lock:
            return [i for i, p in enumerate(self._procs)
                    if i not in self._retired and not p.is_alive()]

    @property
    def n_live(self) -> int:
        return len(self._url_of)

    # -- spawning ------------------------------------------------------- #

    def _launch(self, partition_id: int):
        """Start one worker process; returns (process, parent_conn) for
        the startup handshake."""
        ctx = multiprocessing.get_context("spawn")
        parent, child = ctx.Pipe()
        p = ctx.Process(
            target=_fleet_worker,
            args=(self.handler_factory, child, self.server_kw, partition_id,
                  self.rendezvous.url if self.rendezvous else None,
                  self.forwarding, self.trace_dir,
                  self.flight_recorder_dir),
            daemon=True,
        )
        p.start()
        return p, parent

    def _await_url(self, slot: int, p, parent) -> str:
        """The startup handshake wait: fail FAST on a dead child (e.g.
        establish_forward raised on bad credentials/exhausted ports) —
        waiting out the full timeout would mask the real error with a
        generic one. The deadline runs on the injectable clock."""
        deadline = self.clock.monotonic() + self.start_timeout_s
        while not parent.poll(0.5):
            if not p.is_alive():
                raise RuntimeError(
                    f"serving host {slot} died during startup (exitcode "
                    f"{p.exitcode}) — see the child's "
                    "stderr; with forwarding enabled this is usually "
                    "the reverse tunnel failing to establish"
                )
            if self.clock.monotonic() > deadline:
                raise TimeoutError("serving host failed to start")
        host, port = parent.recv()
        return f"http://{host}:{port}/"

    def _wait_ready(self, url: str, timeout_s: "float | None" = None,
                    proc=None) -> None:
        """Poll the replica's /readyz until 200 — with a warmup request
        configured, readiness means the fused executable is warm over the
        FULL bucket ladder, so admitting the replica cannot cost a live
        request a compile. Real-time deadline: this waits on a real
        subprocess, not on simulated time."""
        import http.client
        import urllib.parse

        u = urllib.parse.urlsplit(url)
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.start_timeout_s)
        while True:
            try:
                conn = http.client.HTTPConnection(u.hostname, u.port,
                                                  timeout=2)
                try:
                    conn.request("GET", "/readyz")
                    if conn.getresponse().status == 200:
                        return
                finally:
                    conn.close()
            except (OSError, http.client.HTTPException):
                pass
            if proc is not None and not proc.is_alive():
                raise RuntimeError(
                    f"replica {url} died while warming up (exitcode "
                    f"{proc.exitcode})")
            if time.monotonic() > deadline:
                raise TimeoutError(f"replica {url} never became ready")
            time.sleep(0.02)

    def _spawn(self, slot: int) -> str:
        """Fill `slot` with a fresh worker: handshake, wait until warm
        (/readyz), then publish it to `urls`/watchers — a spawned replica
        is never routable before it is ready."""
        part = self._next_part
        self._next_part += 1
        p, parent = self._launch(part)
        with self._fleet_lock:
            while len(self._procs) <= slot:
                self._procs.append(p)
            self._procs[slot] = p
        url = self._await_url(slot, p, parent)
        self._wait_ready(url, proc=p)
        self._set_url(slot, url)
        return url

    def start(self) -> "ServingFleet":
        if self.rendezvous is not None:
            self.rendezvous.start()
        if self.timeline_dir is not None and self.timeline is None:
            from ..observability.recorder import get_recorder
            from ..observability.timeline import TimelineRecorder

            self.timeline = TimelineRecorder(
                self.timeline_dir, self.rendezvous.aggregator,
                clock=self.clock, interval_s=self.timeline_interval_s,
                keep=self.timeline_keep, recorder=get_recorder())
            self.timeline.start()
        # spawn all workers in parallel, then run each handshake
        started = []
        for slot in range(self.n_hosts):
            part = self._next_part
            self._next_part += 1
            p, parent = self._launch(part)
            with self._fleet_lock:
                self._procs.append(p)
            started.append((slot, p, parent))
        try:
            for slot, p, parent in started:
                url = self._await_url(slot, p, parent)
                self._wait_ready(url, proc=p)
                self._set_url(slot, url)
        except Exception:
            self.stop()
            raise
        return self

    def info(self) -> dict:
        """Aggregated fleet state (requires rendezvous=True)."""
        if self.rendezvous is None:
            raise ValueError("fleet started with rendezvous=False")
        return self.rendezvous.info()

    def kill(self, index: int) -> None:
        """Hard-kill one replica — the chaos path: no drain, no final
        flush, its ServiceInfo left registered (the rendezvous reports it
        unreachable/down, which is exactly what the fleet view must show
        for a crashed process). The dead replica's URL is pruned from
        `urls` so routing layers stop offering it."""
        p = self._procs[index]
        if p.is_alive():
            p.kill()
        p.join(timeout=10)
        self._drop_url(index)
        self._record_transition("kill", slot=index)

    def dump_all(self, trigger: str = "fleet") -> int:
        """Broadcast a flight-recorder dump to every LIVE replica (POST
        /flightrecorder/dump) — the fleet-wide snapshot a driver-side
        trigger (SLO burn, chaos kill about to land) fans out so each
        process writes its ring BEFORE anything dies. Fail-soft per
        replica; returns how many acknowledged."""
        import http.client
        import urllib.parse

        dumped = 0
        with self._fleet_lock:
            urls = list(self.urls)
        for url in urls:
            u = urllib.parse.urlsplit(url)
            try:
                conn = http.client.HTTPConnection(u.hostname, u.port,
                                                  timeout=5)
                try:
                    conn.request(
                        "POST", f"/flightrecorder/dump?trigger={trigger}",
                        body=b"")
                    if conn.getresponse().status == 200:
                        dumped += 1
                finally:
                    conn.close()
            except (OSError, http.client.HTTPException):
                pass
        return dumped

    def respawn(self, index: int) -> str:
        """Self-healing: refill a dead slot through the same startup
        handshake `start()` uses. The new process gets a FRESH partition
        id (the crashed one's counters stay retained in the fleet totals)
        and is published only after /readyz. Returns the new URL."""
        p = self._procs[index]
        if p.is_alive():
            raise RuntimeError(
                f"slot {index} is still alive — kill() or retire() it "
                "before respawning")
        self._drop_url(index)  # no-op when kill() already pruned it
        with self._fleet_lock:
            self._retired.discard(index)
        url = self._spawn(index)
        self._record_transition("respawn", slot=index, url=url)
        return url

    def retire(self, index: int) -> None:
        """Gracefully drain one replica out of the fleet: unpublish its
        URL first (routing layers stop sending new work), then SIGTERM —
        the worker sheds, drains in-flight requests, flushes its final
        counters, and exits. Hard kill only past stop_timeout_s."""
        with self._fleet_lock:
            self._retired.add(index)
        self._drop_url(index)
        self._record_transition("retire", slot=index)
        p = self._procs[index]
        if p.is_alive():
            p.terminate()
            p.join(timeout=self.stop_timeout_s)
            if p.is_alive():
                p.kill()
                p.join(timeout=10)

    def scale_to(self, n: int) -> list[str]:
        """Grow or shrink the live replica set to `n`. Growth spawns into
        fresh slots and publishes each replica once warm; shrink retires
        the highest live slots via graceful drain. Returns `urls`."""
        if n < 0:
            raise ValueError(f"cannot scale to {n} replicas")
        with self._fleet_lock:
            live = sorted(self._url_of)
        while len(live) < n:
            slot = len(self._procs)
            self._spawn(slot)
            live.append(slot)
        for slot in reversed(live[n:]):
            self.retire(slot)
        return list(self.urls)

    def rolling_swap(self, new_handler_factory) -> int:
        """Zero-downtime model swap: for each live replica, start a NEW
        replica with `new_handler_factory`, warm it over the full bucket
        ladder (the warmup/readyz gate in _spawn), publish it, and only
        then drain and retire one old replica — the live set never drops
        below its pre-swap size and every routable replica is warm, so
        clients see no downtime and no compile stalls. Returns the number
        of replicas swapped."""
        self.handler_factory = new_handler_factory
        old_slots = self.live_slots()
        self._record_transition("swap_begin", n=len(old_slots))
        for slot in old_slots:
            self._spawn(len(self._procs))
            self.retire(slot)
        self._record_transition("swap_done", n=len(old_slots))
        return len(old_slots)

    def stop(self) -> None:
        """Graceful first: SIGTERM puts every worker through its drain-
        and-flush sequence (in-flight requests answered, final counters
        pushed to the rendezvous, traces exported); workers that miss
        `stop_timeout_s` get the historical hard kill. The rendezvous
        stops LAST so the final flushes have somewhere to land."""
        if self.timeline is not None:
            try:
                self.timeline.sample()       # the shutdown-edge sample
            except Exception:  # noqa: BLE001 — telemetry stays optional
                pass
            self.timeline.stop()
            self.timeline = None
        with self._fleet_lock:
            procs = list(self._procs)
        for p in procs:
            if p.is_alive():
                p.terminate()
        deadline = time.monotonic() + self.stop_timeout_s
        for p in procs:
            p.join(timeout=max(deadline - time.monotonic(), 0.1))
        for p in procs:
            if p.is_alive():
                p.kill()
                p.join(timeout=10)
        with self._fleet_lock:
            self._procs = []
            self._url_of = {}
            self._retired = set()
            self.urls = []
        if self.rendezvous is not None:
            self.rendezvous.stop()
