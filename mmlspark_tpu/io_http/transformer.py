"""HTTPTransformer + SimpleHTTPTransformer + parsers.

Reference: `HTTPTransformer` (src/io/http/src/main/scala/HTTPTransformer.
scala:78-128: request column -> response column with per-partition async
client), `SimpleHTTPTransformer` (SimpleHTTPTransformer.scala:61+: input
parser → HTTP → output parser mini-pipeline with optional error column),
parsers (Parsers.scala:21-227: JSONInput/CustomInput/JSONOutput/StringOutput/
CustomOutput).
"""

from __future__ import annotations

import json
from typing import Any, Callable

import numpy as np

from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import Table
from ..core.serialize import register_stage
from ..utils.async_utils import buffered_map
from .clients import HTTPClient, TargetPool
from .schema import HTTPRequestData, HTTPResponseData

__all__ = [
    "HTTPTransformer",
    "DistributedHTTPTransformer",
    "SimpleHTTPTransformer",
    "JSONInputParser",
    "JSONOutputParser",
    "StringOutputParser",
    "CustomInputParser",
    "CustomOutputParser",
]


@register_stage
class HTTPTransformer(HasInputCol, HasOutputCol, Transformer):
    """Request column -> response column (HTTPTransformer.scala:78-128)."""

    input_col = Param("request", "HTTPRequestData column", ptype=str)
    output_col = Param("response", "HTTPResponseData column", ptype=str)
    concurrency = Param(1, "in-flight requests per call", ptype=int)
    timeout = Param(60.0, "per-request timeout (s)", ptype=float)
    retries = Param(3, "retry attempts (429/5xx/conn)", ptype=int)

    handler: Callable | None = None  # test hook: req -> HTTPResponseData
    # optional resilience overrides (runtime wiring, not serialized):
    # a RetryPolicy replaces the retries ladder, a CircuitBreaker guards
    # the endpoint (open circuit -> synthetic 503 responses)
    retry_policy = None
    breaker = None

    def _transform(self, table: Table) -> Table:
        reqs = table[self.get("input_col")]
        if self.handler is not None:
            resps = [self.handler(r) for r in reqs]
        else:
            client = HTTPClient(
                concurrency=self.get("concurrency"),
                timeout=self.get("timeout"),
                retries=self.get("retries"),
                policy=self.retry_policy,
                breaker=self.breaker,
            )
            resps = client.send_all(list(reqs))
        return table.with_column(self.get("output_col"), resps)


@register_stage
class DistributedHTTPTransformer(HasInputCol, HasOutputCol, Transformer):
    """Request column -> response column spread over a REPLICA SET — the
    client-side load-balancer role of the reference's distributed serving
    mode (per-executor servers behind a balancer, SURVEY.md §3.4).

    Routing goes through io_http.clients.TargetPool — the same primitive
    ServingGateway uses — so every row gets per-replica circuit breakers
    and one automatic failover to a different replica on a connection
    failure. `routing_key_col` switches to consistent-hash routing on
    that column's values (session affinity for stateful handlers)."""

    input_col = Param("request", "HTTPRequestData column", ptype=str)
    output_col = Param("response", "HTTPResponseData column", ptype=str)
    urls = Param(None, "replica base URLs to spread over",
                 ptype=(list, tuple), required=True)
    strategy = Param("round_robin",
                     "'round_robin' or 'least_loaded' replica pick",
                     ptype=str)
    routing_key_col = Param(None, "column whose values consistent-hash "
                            "each row to a replica", ptype=str)
    concurrency = Param(1, "in-flight requests per call", ptype=int)
    timeout = Param(60.0, "per-request timeout (s)", ptype=float)

    handler: Callable | None = None  # test hook: req -> HTTPResponseData
    retry_policy = None              # runtime wiring, not serialized
    _pool: "TargetPool | None" = None

    @property
    def pool(self) -> TargetPool:
        """Pool (and its breakers) persists across transform calls, so
        replica health learned in one batch guards the next."""
        if self._pool is None:
            self._pool = TargetPool(list(self.get("urls")))
        return self._pool

    def _transform(self, table: Table) -> Table:
        reqs = list(table[self.get("input_col")])
        if self.handler is not None:
            resps = [self.handler(r) for r in reqs]
            return table.with_column(self.get("output_col"), resps)
        key_col = self.get("routing_key_col")
        keys = ([str(k) for k in table[key_col]] if key_col
                else [None] * len(reqs))
        pool = self.pool

        def send(pair):
            req, key = pair
            return pool.send(
                req, timeout=self.get("timeout"), policy=self.retry_policy,
                strategy=("hash" if key is not None
                          else self.get("strategy")), key=key)

        pairs = list(zip(reqs, keys))
        if self.get("concurrency") <= 1:
            resps = [send(p) for p in pairs]
        else:
            resps = list(buffered_map(send, pairs, self.get("concurrency")))
        return table.with_column(self.get("output_col"), resps)


@register_stage
class JSONInputParser(HasInputCol, HasOutputCol, Transformer):
    """Column value -> JSON POST request (Parsers.scala:60-89)."""

    input_col = Param("input", "column with JSON-able payloads", ptype=str)
    output_col = Param("request", "HTTPRequestData output column", ptype=str)
    url = Param(None, "target URL", ptype=str, required=True)
    method = Param("POST", "HTTP method", ptype=str)
    headers = Param({}, "extra headers")

    def _transform(self, table: Table) -> Table:
        col = table[self.get("input_col")]
        vals = col.tolist() if isinstance(col, np.ndarray) else col
        reqs = [
            HTTPRequestData.from_json(
                self.get("url"), v, self.get("method"), dict(self.get("headers"))
            )
            for v in vals
        ]
        return table.with_column(self.get("output_col"), reqs)


@register_stage
class CustomInputParser(HasInputCol, HasOutputCol, Transformer):
    """udf column -> request (Parsers.scala:91-108)."""

    input_col = Param("input", "input column", ptype=str)
    output_col = Param("request", "request output column", ptype=str)

    udf: Callable[[Any], HTTPRequestData] | None = None

    def _transform(self, table: Table) -> Table:
        if self.udf is None:
            raise ValueError("CustomInputParser needs a udf")
        col = table[self.get("input_col")]
        vals = col.tolist() if isinstance(col, np.ndarray) else col
        return table.with_column(self.get("output_col"), [self.udf(v) for v in vals])


@register_stage
class JSONOutputParser(HasInputCol, HasOutputCol, Transformer):
    """Response -> parsed JSON body (Parsers.scala:110-162)."""

    input_col = Param("response", "HTTPResponseData column", ptype=str)
    output_col = Param("output", "parsed output column", ptype=str)
    field_path = Param(None, "dotted path into the JSON body", ptype=str)

    def _transform(self, table: Table) -> Table:
        out = []
        for r in table[self.get("input_col")]:
            body = r.json() if isinstance(r, HTTPResponseData) and r.ok else None
            if body is not None and self.get("field_path"):
                for part in self.get("field_path").split("."):
                    if body is None:
                        break
                    body = body.get(part) if isinstance(body, dict) else None
            out.append(body)
        return table.with_column(self.get("output_col"), out)


@register_stage
class StringOutputParser(HasInputCol, HasOutputCol, Transformer):
    """Response -> body text (Parsers.scala:164-180)."""

    input_col = Param("response", "HTTPResponseData column", ptype=str)
    output_col = Param("output", "text output column", ptype=str)

    def _transform(self, table: Table) -> Table:
        out = [
            r.text() if isinstance(r, HTTPResponseData) else str(r)
            for r in table[self.get("input_col")]
        ]
        return table.with_column(self.get("output_col"), out)


@register_stage
class CustomOutputParser(HasInputCol, HasOutputCol, Transformer):
    """udf response -> value (Parsers.scala:182-199)."""

    input_col = Param("response", "HTTPResponseData column", ptype=str)
    output_col = Param("output", "output column", ptype=str)

    udf: Callable[[HTTPResponseData], Any] | None = None

    def _transform(self, table: Table) -> Table:
        if self.udf is None:
            raise ValueError("CustomOutputParser needs a udf")
        return table.with_column(
            self.get("output_col"),
            [self.udf(r) for r in table[self.get("input_col")]],
        )


@register_stage
class SimpleHTTPTransformer(HasInputCol, HasOutputCol, Transformer):
    """input parser → HTTP → output parser, with optional error column
    (SimpleHTTPTransformer.scala:61+, error col :18-26)."""

    input_col = Param("input", "payload column", ptype=str)
    output_col = Param("output", "parsed output column", ptype=str)
    url = Param(None, "target URL (JSON input parser)", ptype=str)
    concurrency = Param(1, "in-flight requests", ptype=int)
    timeout = Param(60.0, "request timeout (s)", ptype=float)
    retries = Param(3, "retry attempts (429/5xx/conn)", ptype=int)
    error_col = Param(None, "error-info column (None = raise on HTTP error)", ptype=str)
    flatten_output_field = Param(None, "dotted path into response JSON", ptype=str)

    input_parser: Transformer | None = None
    output_parser: Transformer | None = None
    handler: Callable | None = None  # test hook passed to HTTPTransformer
    retry_policy = None              # forwarded to HTTPTransformer
    breaker = None

    def _transform(self, table: Table) -> Table:
        inp = self.input_parser or JSONInputParser(
            input_col=self.get("input_col"), output_col="__http_request",
            url=self.get("url"),
        )
        if self.input_parser is not None:
            inp = inp.copy({"input_col": self.get("input_col"),
                            "output_col": "__http_request"})
        http = HTTPTransformer(
            input_col="__http_request", output_col="__http_response",
            concurrency=self.get("concurrency"), timeout=self.get("timeout"),
            retries=self.get("retries"),
        )
        http.handler = self.handler
        http.retry_policy = self.retry_policy
        http.breaker = self.breaker
        outp = self.output_parser or JSONOutputParser(
            input_col="__http_response", output_col=self.get("output_col"),
            field_path=self.get("flatten_output_field"),
        )
        if self.output_parser is not None:
            outp = outp.copy({"input_col": "__http_response",
                              "output_col": self.get("output_col")})

        t = outp.transform(http.transform(inp.transform(table)))
        resps = t["__http_response"]
        err_col = self.get("error_col")
        if err_col:
            errors = [
                None if (isinstance(r, HTTPResponseData) and r.ok)
                else {"status_code": getattr(r, "status_code", 0),
                      "reason": getattr(r, "reason", "")}
                for r in resps
            ]
            t = t.with_column(err_col, errors)
        else:
            bad = [r for r in resps if not (isinstance(r, HTTPResponseData) and r.ok)]
            if bad:
                raise IOError(
                    f"{len(bad)} HTTP failures (first: {bad[0].status_code} "
                    f"{bad[0].reason}); set error_col to capture instead"
                )
        return t.drop("__http_request", "__http_response")
