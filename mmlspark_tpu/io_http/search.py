"""AzureSearch-style indexed sink.

Reference: src/io/http/src/main/scala/cognitive/AzureSearch.scala:23-249
(`AzureSearchWriter`: checks/creates the index, then streams document
batches through `AddDocuments`) and `AzureSearchAPI.scala:19-211` (index
CRUD + per-item error checking).

The wire format follows the Azure Search REST API (api-key header,
api-version query param, `{"value": [{"@search.action": ..., ...doc}]}`
upload bodies), so the stage points at a live service or a local fake
equally.
"""

from __future__ import annotations

from typing import Any, Callable

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.schema import Table, as_scalar
from ..core.serialize import register_stage
from .schema import HTTPRequestData, HTTPResponseData

__all__ = ["AzureSearchWriter"]

API_VERSION = "2017-11-11"  # the version the reference pins (AzureSearch.scala)


@register_stage
class AzureSearchWriter(Transformer):
    """Write table rows as documents into a search index (sink stage: the
    output table is the input, unchanged).

    `index_definition` is the service's index-schema JSON (name + fields);
    if the index does not exist it is created first
    (AzureSearchAPI.scala:60-120 createIndexIfNotExists).
    """

    service_url = Param(None, "search service base url", ptype=str, required=True)
    index_definition = Param(None, "index schema dict: {name, fields:[...]}",
                             ptype=dict, required=True)
    api_key = Param(None, "admin api key (api-key header)", ptype=str)
    action = Param("upload", "upload | merge | mergeOrUpload | delete", ptype=str)
    action_col = Param(None, "column overriding the action per row", ptype=str)
    batch_size = Param(100, "documents per upload batch", ptype=int)
    columns = Param(None, "columns to index (default: all non-action columns)",
                    ptype=(list, tuple))

    handler: Callable | None = None  # test hook: request -> HTTPResponseData

    def _headers(self) -> dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.get("api_key"):
            h["api-key"] = self.get("api_key")
        return h

    def _send(self, req: HTTPRequestData) -> HTTPResponseData:
        if self.handler is not None:
            return self.handler(req)
        from .clients import http_send

        return http_send(req)

    def _index_name(self) -> str:
        name = (self.get("index_definition") or {}).get("name")
        if not name:
            raise ValueError("index_definition must carry a 'name'")
        return name

    def _ensure_index(self) -> None:
        base = self.get("service_url").rstrip("/")
        name = self._index_name()
        probe = HTTPRequestData(
            method="GET",
            url=f"{base}/indexes/{name}?api-version={API_VERSION}",
            headers=self._headers(),
        )
        resp = self._send(probe)
        if isinstance(resp, HTTPResponseData) and resp.ok:
            return
        if getattr(resp, "status_code", 0) != 404:
            raise IOError(f"index probe failed: {getattr(resp, 'status_code', 0)}")
        create = HTTPRequestData.from_json(
            f"{base}/indexes?api-version={API_VERSION}",
            self.get("index_definition"),
            headers=self._headers(),
        )
        resp = self._send(create)
        if not (isinstance(resp, HTTPResponseData) and resp.ok):
            raise IOError(
                f"index creation failed: {getattr(resp, 'status_code', 0)} "
                f"{getattr(resp, 'reason', '')}"
            )

    def _transform(self, table: Table) -> Table:
        self._ensure_index()
        base = self.get("service_url").rstrip("/")
        name = self._index_name()
        url = f"{base}/indexes/{name}/docs/index?api-version={API_VERSION}"
        cols = list(self.get("columns") or table.columns)
        action_col = self.get("action_col")
        if action_col and action_col in cols:
            cols.remove(action_col)
        n = table.num_rows
        bs = max(int(self.get("batch_size")), 1)
        for start in range(0, n, bs):
            stop = min(start + bs, n)
            docs = []
            for i in range(start, stop):
                doc: dict[str, Any] = {
                    "@search.action": (
                        as_scalar(table[action_col][i]) if action_col
                        else self.get("action")
                    )
                }
                for c in cols:
                    doc[c] = as_scalar(table[c][i])
                docs.append(doc)
            resp = self._send(HTTPRequestData.from_json(
                url, {"value": docs}, headers=self._headers()
            ))
            if not (isinstance(resp, HTTPResponseData) and resp.ok):
                raise IOError(
                    f"document upload failed: {getattr(resp, 'status_code', 0)}"
                )
            # per-item status check (AzureSearchAPI.scala:150-211)
            items = (resp.json() or {}).get("value", [])
            bad = [it for it in items if not it.get("status", True)]
            if bad:
                raise IOError(f"{len(bad)} documents rejected: {bad[:3]}")
        return table
