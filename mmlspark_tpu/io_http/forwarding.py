"""NAT/tunnel path for continuous serving across network boundaries.

Reference: `PortForwarding.forwardPortToRemote` (src/io/http/src/main/
scala/PortForwarding.scala:16-66) — each partition's HTTP server opens a
REVERSE ssh tunnel to a public gateway, scanning `remotePortStart +
attempt` until a free listen port is found, so clients outside the
cluster's NAT reach the per-partition servers; `HTTPSourceV2` wires it
under the `forwarding.*` options (HTTPSourceV2.scala:363-372).

TPU redesign: no jsch — the system `ssh` client (universally present
where a gateway is reachable) runs `-N -R` under a supervised
subprocess. `ExitOnForwardFailure=yes` turns "listen port busy" into a
fast nonzero exit, which drives the same port-scan loop as the
reference. The subprocess launcher is injectable so the scan/liveness
logic is testable without a real gateway (this build environment has
zero egress).
"""

from __future__ import annotations

import socket
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Sequence

from ..resilience.policy import Clock, SYSTEM_CLOCK

__all__ = ["ForwardingOptions", "PortForward", "build_ssh_command",
           "establish_forward", "get_local_ip"]


def get_local_ip() -> str:
    """This host's outbound-facing IP (reference getLocalIp,
    HTTPSourceV2.scala:325-327). A connectionless UDP socket picks the
    routing-table answer without sending any packet. The probe target is a
    PUBLIC address (the reference uses one too): probing 10/8 would return
    127.0.0.1 on any host without an RFC-1918 route even though it has a
    perfectly good default route."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


@dataclass
class ForwardingOptions:
    """The reference's `forwarding.*` option set (PortForwarding.scala:68-81),
    flattened to a typed record. `remote_port_start` defaults to the local
    port, exactly like the reference's orElse chain."""

    username: str
    ssh_host: str
    ssh_port: int = 22
    bind_address: str = "*"
    remote_port_start: int | None = None
    key_file: str | None = None
    max_retries: int = 50
    connect_timeout_s: float = 20.0
    extra_ssh_args: tuple[str, ...] = ()
    # the client binary; picklable across the fleet's spawn boundary
    # (unlike an injected launcher), and swappable for a stub in tests
    ssh_command: str = "ssh"
    # auth/handshake margin on top of ConnectTimeout in the settle window
    # (establish_forward): tunable here because the fleet path has no
    # other way to bound per-attempt wait when the gateway is fast
    settle_margin_s: float = 5.0


def build_ssh_command(opts: ForwardingOptions, remote_port: int,
                      local_host: str, local_port: int) -> list[str]:
    """argv for one reverse-forward attempt. Pure so the exact contract —
    flags, bind syntax, failure mode — is unit-testable."""
    cmd = [
        opts.ssh_command, "-N",
        # listen-port-busy must FAIL the process (the scan signal), not
        # degrade to a warning while ssh stays connected
        "-o", "ExitOnForwardFailure=yes",
        # no interactive auth: a gateway that falls back to a password
        # prompt must exit nonzero immediately, not sit at the prompt for
        # the whole settle window and register as an established tunnel
        "-o", "BatchMode=yes",
        "-o", "StrictHostKeyChecking=no",
        "-o", f"ConnectTimeout={max(int(opts.connect_timeout_s), 1)}",
        # a half-dead gateway must not leave a zombie forward behind NAT:
        # miss 3 keepalives (~45 s) and the tunnel tears down
        "-o", "ServerAliveInterval=15",
        "-o", "ServerAliveCountMax=3",
        "-p", str(opts.ssh_port),
    ]
    if opts.key_file:
        cmd += ["-i", opts.key_file]
    cmd += list(opts.extra_ssh_args)
    # an -R spec with NO bind address listens on the gateway's LOOPBACK
    # only — useless for NAT traversal. The default "*" must be emitted
    # explicitly ("*:port:...") to bind all interfaces (the gateway's sshd
    # needs GatewayPorts yes|clientspecified, same as the reference's jsch
    # setPortForwardingR("*", ...) deployment); "" opts into loopback.
    bind = "" if opts.bind_address == "" else f"{opts.bind_address}:"
    cmd += ["-R", f"{bind}{remote_port}:{local_host}:{local_port}"]
    cmd += [f"{opts.username}@{opts.ssh_host}"]
    return cmd


@dataclass
class PortForward:
    """A live reverse tunnel: `ssh_host:remote_port` -> local server."""

    remote_host: str
    remote_port: int
    local_port: int
    _proc: object = field(default=None, repr=False)

    @property
    def public_address(self) -> tuple[str, int]:
        return self.remote_host, self.remote_port

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def status(self) -> dict:
        """Health-probe payload: tunnel liveness + coordinates (surfaced
        under the serving `/healthz` extras so a dead ssh shows up in the
        fleet health view instead of silently blackholing traffic)."""
        return {"alive": self.alive(), "remote_host": self.remote_host,
                "remote_port": self.remote_port,
                "local_port": self.local_port}

    def close(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except Exception:  # noqa: BLE001 — last resort on a hung ssh
                self._proc.kill()


def _default_launcher(cmd: Sequence[str]):
    # stdin=DEVNULL: with no tty, anything in ssh that still tries to read
    # (a stray prompt BatchMode missed, host-key confirmation on an odd
    # sshd) gets EOF and dies instead of blocking on the parent's stdin
    return subprocess.Popen(
        list(cmd), stdin=subprocess.DEVNULL,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def establish_forward(
    local_port: int,
    opts: ForwardingOptions,
    local_host: str = "127.0.0.1",
    launcher: Callable[[Sequence[str]], object] = _default_launcher,
    settle_s: float | None = None,
    clock: "Clock | None" = None,
) -> PortForward:
    """Scan remote listen ports from `remote_port_start` (default: the
    local port), launching one reverse-forward attempt per candidate,
    until one SURVIVES the settle window — the reference's
    `setPortForwardingR` retry loop (PortForwarding.scala:46-62).

    With ExitOnForwardFailure, a busy listen port (or auth/connect
    failure) exits nonzero; a process still alive after the settle
    window holds an established tunnel. The window must therefore OUTLAST
    the slowest legitimate path to failure — TCP connect (bounded by
    ConnectTimeout) plus auth — or a still-connecting ssh would be
    reported as an established tunnel and registered in the rendezvous;
    hence the default of connect_timeout_s + settle_margin_s. Pass an
    explicit settle_s (or tune the margin in ForwardingOptions) only when
    the gateway's connect+auth latency is known."""
    if clock is None:
        clock = SYSTEM_CLOCK
    if settle_s is None:
        settle_s = opts.connect_timeout_s + opts.settle_margin_s
    start = (opts.remote_port_start
             if opts.remote_port_start is not None else local_port)
    for attempt in range(opts.max_retries + 1):
        remote_port = start + attempt
        proc = launcher(build_ssh_command(
            opts, remote_port, local_host, local_port))
        deadline = clock.monotonic() + settle_s
        failed = False
        while clock.monotonic() < deadline:
            if proc.poll() is not None:
                failed = True
                break
            clock.sleep(0.05)
        if not failed:
            return PortForward(
                remote_host=opts.ssh_host, remote_port=remote_port,
                local_port=local_port, _proc=proc)
    raise RuntimeError(
        f"could not establish a reverse forward on any port in "
        f"[{start}, {start + opts.max_retries}] via "
        f"{opts.username}@{opts.ssh_host} — every ssh attempt exited "
        "during the settle window (busy listen ports, auth failure, or "
        "an unreachable gateway)"
    )
