"""HTTP request/response records on Tables.

Reference: `HTTPSchema` (src/io/http/src/main/scala/HTTPSchema.scala:35-188)
defines full request/response StructTypes via SparkBindings; `parse_request`
/`make_reply` from ServingImplicits.scala:58-88. Here requests/responses are
plain dataclasses stored in object columns — the Table equivalent of the
reference's struct columns.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ..core.schema import Table

__all__ = ["HTTPRequestData", "HTTPResponseData", "parse_request",
           "make_reply", "RequestDecoder"]


@dataclass
class HTTPRequestData:
    """Reference: HTTPSchema request StructType (HTTPSchema.scala:121-160)."""

    method: str = "POST"
    url: str = ""
    headers: dict[str, str] = field(default_factory=dict)
    entity: bytes | None = None

    def json(self) -> Any:
        return json.loads(self.entity.decode()) if self.entity else None

    @staticmethod
    def from_json(url: str, payload: Any, method: str = "POST",
                  headers: dict[str, str] | None = None) -> "HTTPRequestData":
        h = {"Content-Type": "application/json", **(headers or {})}
        return HTTPRequestData(
            method=method, url=url, headers=h,
            entity=json.dumps(payload).encode(),
        )


@dataclass
class HTTPResponseData:
    """Reference: HTTPSchema response StructType (HTTPSchema.scala:60-119)."""

    status_code: int = 0
    reason: str = ""
    headers: dict[str, str] = field(default_factory=dict)
    entity: bytes | None = None

    @property
    def ok(self) -> bool:
        return 200 <= self.status_code < 300

    def json(self) -> Any:
        return json.loads(self.entity.decode()) if self.entity else None

    def text(self) -> str:
        return self.entity.decode() if self.entity else ""


def parse_request(table: Table, request_col: str = "request",
                  output_col: str | None = None, flatten_json: bool = True) -> Table:
    """Serving-side: request column -> parsed body column
    (ServingImplicits.parseRequest, ServingImplicits.scala:58-70)."""
    reqs = table[request_col]
    bodies = [r.json() if isinstance(r, HTTPRequestData) else r for r in reqs]
    if flatten_json and bodies and all(isinstance(b, dict) for b in bodies):
        keys: list[str] = []
        for b in bodies:
            for k in b:
                if k not in keys:
                    keys.append(k)
        new_cols: dict[str, Any] = {}
        for k in keys:
            vals = [b.get(k) for b in bodies]
            if all(isinstance(v, (int, float, bool, type(None))) for v in vals):
                new_cols[k] = np.asarray(
                    [np.nan if v is None else v for v in vals], np.float64)
            elif all(isinstance(v, list) for v in vals):
                try:
                    new_cols[k] = np.asarray(vals, np.float64)
                except (ValueError, TypeError):
                    new_cols[k] = vals
            else:
                new_cols[k] = vals
        # one functional update: a per-key with_column chain re-copies the
        # table once per JSON field on every request
        return table.with_columns(new_cols)
    col = output_col or "body"
    return table.with_column(col, bodies)


class RequestDecoder:
    """Schema-cached fast-path decoder: request batch -> one preallocated
    feature matrix, no Table in between.

    `parse_request` re-infers every column's dtype on EVERY request (an
    isinstance scan per value per field) and materializes one object list
    plus one ndarray per field before the handler stacks them again into a
    feature matrix — two full copies of the batch per request.  A serving
    server scores the SAME schema for its whole life, so this decoder
    locks the schema once — the input column list at construction, float64
    scalars confirmed by the first successfully decoded request — and from
    then on decodes each JSON body straight into its row of a preallocated
    `(target, n_cols)` float64 array (padding rows repeat the last real
    row, the batcher's bucket-ladder convention).

    Anything outside the locked schema — a missing field, a non-scalar
    value, a non-JSON body — returns None instead of guessing: the caller
    falls back to the full `parse_request` handler path, which either
    scores the request the slow way or raises the same errors it always
    did.  `null` decodes to NaN, booleans to 0/1, exactly as
    `parse_request`'s float64 conversion would.

    Binary-wire requests (Content-Type `application/x-mmlspark-rows`,
    io_http/wire.py) skip JSON entirely: the frame's `features` block is
    `np.frombuffer`-decoded straight into the same preallocated matrix.
    JSON and binary requests mix freely within one batch."""

    def __init__(self, input_cols: "list[str] | tuple[str, ...]"):
        self.cols = tuple(input_cols)
        self.schema_locked = False
        self.hits = 0
        self.fallbacks = 0
        self.binary_hits = 0

    def decode(self, requests: list, n_target: "int | None" = None
               ) -> "np.ndarray | None":
        """(n_target, n_cols) float64 features, or None when any request
        falls outside the cached schema."""
        from .wire import (content_type_of, decode_features_request,
                           is_wire_content_type)

        n = len(requests)
        if n == 0:
            return None
        target = n if n_target is None else int(n_target)
        out = np.empty((target, len(self.cols)), np.float64)
        cols = self.cols
        binary = 0
        try:
            for i, r in enumerate(requests):
                entity = r.entity if isinstance(r, HTTPRequestData) else None
                if entity and is_wire_content_type(
                        content_type_of(r.headers)):
                    # zero-copy lane: raw f64 bytes -> this row, no parse
                    out[i] = decode_features_request(entity, len(cols))[0]
                    binary += 1
                    continue
                body = json.loads(entity) if entity else None
                row = out[i]
                for j, c in enumerate(cols):
                    v = body[c]
                    if v is None:
                        row[j] = np.nan
                    elif isinstance(v, (int, float)):  # bool is an int
                        row[j] = v
                    else:
                        raise TypeError(f"non-scalar field {c!r}")
        except (TypeError, KeyError, ValueError, AttributeError):
            self.fallbacks += 1
            return None
        self.binary_hits += binary
        if target > n:
            out[n:] = out[n - 1]
        self.schema_locked = True
        self.hits += 1
        return out


def make_reply(table: Table, value_col: str, reply_col: str = "reply") -> Table:
    """Serving-side: column -> JSON reply column
    (ServingImplicits.makeReply, ServingImplicits.scala:73-88)."""
    vals = table[value_col]
    replies = []
    for v in (vals.tolist() if isinstance(vals, np.ndarray) else vals):
        replies.append(HTTPResponseData(
            status_code=200, reason="OK",
            headers={"Content-Type": "application/json"},
            entity=json.dumps({value_col: v}).encode(),
        ))
    return table.with_column(reply_col, replies)
