"""Streaming joins: stream-stream interval join and stream-table
(broadcast) join with watermark semantics.

Reference: Spark's stream-stream inner join — both sides buffer rows
per key, each arriving row probes the opposite buffer, and the
watermark bounds how long a buffered row can wait for a match before it
is evicted (`join_window_s` is the interval condition
`|t_left - t_right| <= window`). Stream-table joins are Spark's
broadcast join of a stream against a static DataFrame.

These are the first operators that REQUIRE the keyed shuffle: per-key
two-sided buffers only stay correct when every row of a key lands on
the same partition (`StreamStreamJoin.partition_key_col`). Determinism
under partitioning follows the same discipline as the aggregators —
state docs are key-sorted, watermarks advance on driver time hints, and
the per-batch output is canonically ordered (sorted by key, left time,
right time) so a P-way merge reconstructs the P=1 output byte-for-byte.

A joined pair is emitted in the batch that completes it (eager inner
join): whichever side arrives second finds the first in the buffer.
Rows older than the batch-start watermark are dropped as late; buffered
rows older than `watermark - join_window_s` can no longer match any
admissible future row and are evicted.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.schema import Table
from ..core.serialize import register_stage
from .state import StatefulOperator

__all__ = ["StreamStreamJoin", "StreamTableJoin"]


@register_stage
class StreamStreamJoin(StatefulOperator):
    """Inner interval join of two event streams multiplexed in one table.

    Input rows carry a key, an event time, a side tag (`side_col` equal
    to `left_tag` or `right_tag`) and a value. Output rows are matched
    pairs: `key_col`, `left_time`, `right_time`, `left_<value_col>`,
    `right_<value_col>`, sorted by (key, left_time, right_time).
    """

    key_col = Param("key", "join key; rows sharing a value can match",
                    ptype=str)
    time_col = Param("time", "event-time column, in seconds", ptype=str)
    side_col = Param("side", "column tagging each row's stream",
                     ptype=str)
    left_tag = Param("left", "side_col value marking left-stream rows",
                     ptype=str)
    right_tag = Param("right", "side_col value marking right-stream rows",
                      ptype=str)
    value_col = Param("value", "numeric payload column carried through "
                      "the join", ptype=str)
    join_window_s = Param(60.0, "max |left_time - right_time| for a "
                          "match", ptype=float, validator=lambda v: v >= 0)
    watermark_delay_s = Param(0.0, "how long to admit out-of-order rows "
                              "past the max event time seen", ptype=float,
                              validator=lambda v: v >= 0)

    # class-level default: reconstruction via load_stage skips __init__
    # and only load_state_doc runs, which never carries a pending hint
    _time_hint: "float | None" = None

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        # {key: [[time, value], ...]} in arrival order, per side
        self._left: dict[str, list] = {}
        self._right: dict[str, list] = {}
        self._max_t: "float | None" = None
        self._time_hint: "float | None" = None
        self.late_rows_dropped = 0

    # -- state ------------------------------------------------------------- #

    def state_doc(self) -> dict:
        return {
            "left": {k: [list(r) for r in self._left[k]]
                     for k in sorted(self._left)},
            "right": {k: [list(r) for r in self._right[k]]
                      for k in sorted(self._right)},
            "max_t": self._max_t,
            "late": self.late_rows_dropped,
        }

    def load_state_doc(self, doc: dict) -> None:
        self._left = {str(k): [list(r) for r in v]
                      for k, v in (doc.get("left") or {}).items()}
        self._right = {str(k): [list(r) for r in v]
                       for k, v in (doc.get("right") or {}).items()}
        self._max_t = doc.get("max_t")
        self.late_rows_dropped = int(doc.get("late") or 0)

    def reset_state(self) -> None:
        self._left, self._right = {}, {}
        self._max_t = None
        self.late_rows_dropped = 0

    def watermark(self) -> "float | None":
        if self._max_t is None:
            return None
        return self._max_t - self.get("watermark_delay_s")

    def set_time_hint(self, t: "float | None") -> None:
        self._time_hint = t

    def merge_sort_cols(self) -> "list[str] | None":
        return [self.get("key_col"), "left_time", "right_time"]

    def partition_key_col(self) -> "str | None":
        return self.get("key_col")

    @property
    def buffered_rows(self) -> int:
        return (sum(len(v) for v in self._left.values())
                + sum(len(v) for v in self._right.values()))

    # -- one batch ---------------------------------------------------------- #

    def _evict(self, low: "float | None") -> None:
        """Drop buffered rows that can no longer match: any future row
        has t >= watermark, so a buffered row older than
        `watermark - join_window_s` is out of every admissible interval."""
        if low is None:
            return
        horizon = low - self.get("join_window_s")
        for buf in (self._left, self._right):
            for k in list(buf):
                kept = [r for r in buf[k] if r[0] >= horizon]
                if kept:
                    buf[k] = kept
                else:
                    del buf[k]

    def _transform(self, table: Table) -> Table:
        win = self.get("join_window_s")
        low = self.watermark()          # watermark BEFORE this batch
        self._evict(low)
        left_tag = self.get("left_tag")
        out: list[tuple] = []           # (key, lt, rt, lv, rv)
        if table.num_rows:
            times = np.asarray(table[self.get("time_col")],
                               dtype=np.float64)
            keys = [str(k) for k in table[self.get("key_col")]]
            sides = [str(s) for s in table[self.get("side_col")]]
            values = np.asarray(table[self.get("value_col")],
                                dtype=np.float64)
            for t, k, side, v in zip(times, keys, sides, values):
                t, v = float(t), float(v)
                if low is not None and t < low:
                    self.late_rows_dropped += 1
                    continue
                is_left = side == left_tag
                own = self._left if is_left else self._right
                other = self._right if is_left else self._left
                for t2, v2 in other.get(k, ()):
                    if abs(t - t2) <= win:
                        out.append((k, t, t2, v, v2) if is_left
                                   else (k, t2, t, v2, v))
                own.setdefault(k, []).append([t, v])
                if self._max_t is None or t > self._max_t:
                    self._max_t = t
        hint, self._time_hint = self._time_hint, None
        if hint is not None and (self._max_t is None or hint > self._max_t):
            self._max_t = hint
        # canonical order: a P-way merge stable-sorts by the same triple,
        # and ties (same key+times) keep per-key emission order, which is
        # arrival order and thus partition-invariant
        out.sort(key=lambda e: (e[0], e[1], e[2]))
        vc = self.get("value_col")
        return Table({
            self.get("key_col"): [e[0] for e in out],
            "left_time": np.array([e[1] for e in out], dtype=np.float64),
            "right_time": np.array([e[2] for e in out], dtype=np.float64),
            f"left_{vc}": np.array([e[3] for e in out], dtype=np.float64),
            f"right_{vc}": np.array([e[4] for e in out], dtype=np.float64),
        })


@register_stage
class StreamTableJoin(Transformer):
    """Broadcast join of a stream against a static table on disk.

    The static side (csv or parquet, keyed uniquely by `key_col`) loads
    lazily once and every batch row looks up its match: `how="left"`
    keeps all batch rows (unmatched static columns become NaN / ""),
    `how="inner"` drops unmatched rows. Stateless, so it runs anywhere
    in a partition chain — or before the shuffle on the driver."""

    key_col = Param("key", "join key present in both sides", ptype=str)
    table_path = Param(None, "csv or parquet file holding the static "
                       "side", ptype=str)
    how = Param("left", "'left' keeps unmatched stream rows, 'inner' "
                "drops them", ptype=str,
                validator=lambda v: v in ("left", "inner"))

    # class-level defaults so a blob-reconstructed instance (no __init__)
    # lazy-loads the static side exactly like a fresh one
    _static: "Table | None" = None
    _index: "dict[str, int] | None" = None

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._static: "Table | None" = None
        self._index: "dict[str, int] | None" = None

    def _load_static(self) -> Table:
        if self._static is None:
            path = self.get("table_path")
            if not path:
                raise ValueError("StreamTableJoin requires table_path")
            if path.endswith(".parquet"):
                from ..core.table_io import read_parquet

                self._static = read_parquet(path)
            else:
                from ..core.table_io import read_csv

                self._static = read_csv(path)
            key = self.get("key_col")
            index: dict[str, int] = {}
            for i, k in enumerate(self._static[key]):
                k = str(k)
                if k in index:
                    raise ValueError(
                        f"static table {path!r} has duplicate key {k!r}")
                index[k] = i
            self._index = index
        return self._static

    def _transform(self, table: Table) -> Table:
        static = self._load_static()
        key = self.get("key_col")
        hits = [self._index.get(str(k), -1) for k in table[key]]
        if self.get("how") == "inner":
            keep = np.array([h >= 0 for h in hits], dtype=bool)
            table = table.gather(keep)
            hits = [h for h in hits if h >= 0]
        out = table
        for name in static.columns:
            if name == key:
                continue
            col = static[name]
            numeric = isinstance(col, np.ndarray) and \
                np.issubdtype(col.dtype, np.number)
            if numeric:
                vals = np.array(
                    [float(col[h]) if h >= 0 else np.nan for h in hits],
                    dtype=np.float64)
            else:
                vals = [str(col[h]) if h >= 0 else "" for h in hits]
            dest = name if name not in out.columns else f"right_{name}"
            out = out.with_column(dest, vals)
        return out
