"""Structured streaming: micro-batch queries over TPU pipelines.

Reference: Spark Structured Streaming as used by the reference's serving
and PowerBI stories — "deploy any streaming query as a web service"
(docs/mmlspark-serving.md), `PowerBIWriter.stream`, and the
DistributedHTTPSource getOffset/getBatch/commit contract. The reference
leans on Spark's ~9.9k-LoC streaming/DataSource plumbing (VERDICT.md's
LoC diagnostic); this package is the TPU-native counterpart.

Design: a `StreamingQuery` drives source -> transform -> sink micro-batch
ticks. Any `core.pipeline` Transformer/PipelineModel is a streaming
transform — its jitted inner step compiles on the first batch and is
reused for the life of the query (compiled-once / stream-forever).
Exactly-once comes from three pieces working together:

- deterministic, replayable sources (`DirectorySource`, `ServingSource`);
- a write-ahead commit log (`CommitLog`) that records each batch's offset
  range BEFORE the batch runs and its commit after the sink write, plus
  per-batch snapshots of stateful-operator state;
- idempotent batch-id-named sink writes (`ParquetSink`'s atomic
  `part-<batch_id>` files, `MemorySink`'s keyed buffer, the serving
  journal's duplicate-reply suppression behind `ReplySink`).

A killed query restarts from the last committed batch, replays the
in-flight batch against the exact planned offsets, and the sink skips
anything it already wrote — output is identical to a one-shot batch
`Pipeline.transform` over the same input.

Distributed execution (shuffle.py / partition.py): a `KeyedShuffle`
stage splits the pipeline, `ParallelStreamingQuery` runs the stateful
chain over P key-partitions — on driver threads or across a fleet of
worker processes — with per-partition incremental checkpoints, and the
kill-restart byte-identity guarantee holds at any P. `StreamStreamJoin`
and `StreamTableJoin` are the first operators requiring the shuffle.
"""

from .checkpoint import CommitLog
from .joins import StreamStreamJoin, StreamTableJoin
from .partition import (
    ParallelStreamingQuery,
    PartitionWorkerFactory,
    ThreadPartitionWorker,
    split_pipeline_at_shuffle,
)
from .query import StreamingQuery
from .shuffle import (
    KeyedShuffle,
    partition_of,
    split_by_partition,
    stable_hash,
)
from .sinks import (
    ForeachBatchSink,
    MemorySink,
    ParquetSink,
    PowerBISink,
    ReplySink,
    Sink,
)
from .sources import (
    DirectorySource,
    MemorySource,
    ServingSource,
    SocketSource,
    Source,
)
from .state import (
    GroupedAggregator,
    MemoryStateBackend,
    SpillingStateBackend,
    StateBackend,
    StatefulOperator,
    WindowedAggregator,
)

__all__ = [
    "CommitLog",
    "StreamingQuery",
    "ParallelStreamingQuery",
    "KeyedShuffle",
    "stable_hash",
    "partition_of",
    "split_by_partition",
    "split_pipeline_at_shuffle",
    "ThreadPartitionWorker",
    "PartitionWorkerFactory",
    "StreamStreamJoin",
    "StreamTableJoin",
    "StateBackend",
    "MemoryStateBackend",
    "SpillingStateBackend",
    "Source",
    "DirectorySource",
    "MemorySource",
    "SocketSource",
    "ServingSource",
    "Sink",
    "MemorySink",
    "ParquetSink",
    "ForeachBatchSink",
    "PowerBISink",
    "ReplySink",
    "StatefulOperator",
    "GroupedAggregator",
    "WindowedAggregator",
]
