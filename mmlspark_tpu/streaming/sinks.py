"""Streaming sinks: idempotent addBatch(batchId, table).

Reference: Spark's `Sink` trait — `addBatch(batchId, data)` with the
documented contract that a sink asked to write a batchId it has already
written must SKIP it, because the engine replays the in-flight batch
after recovery. The reference's `HTTPSink` keys replies by
(name, partitionId, requestId) for the same reason
(HTTPSourceV2.scala:421-476) and `PowerBIWriter` is its fire-and-forget
HTTP sink (PowerBIWriter.scala:98-107).

Exactly-once lands here: the commit log guarantees a replayed batch
carries the same id and (via planned offsets + deterministic sources)
the same rows, so batch-id-named idempotent writes make the replay a
no-op. `ParquetSink` gets this from atomic `part-<batchId>` files,
`MemorySink` from a keyed buffer, `ReplySink` from the serving journal's
duplicate-reply suppression. `ForeachBatchSink` and `PowerBISink` are
at-least-once unless the user's callback/dataset dedupes on batch_id —
same caveat Spark documents for foreachBatch and its HTTP sinks.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

from ..core.schema import Table
from ..observability.sanitizer import make_lock
from ..core.table_io import write_parquet

__all__ = ["Sink", "MemorySink", "ParquetSink", "ForeachBatchSink",
           "PowerBISink", "ReplySink"]


class Sink:
    """Base streaming sink."""

    def add_batch(self, batch_id: int, table: Table) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemorySink(Sink):
    """Keyed in-memory buffer (Spark's memory sink): `table()` concatenates
    committed batches in batch-id order. Idempotent — a replayed batch_id
    is dropped."""

    def __init__(self) -> None:
        self._lock = make_lock("MemorySink._lock")
        self._batches: dict[int, Table] = {}

    def add_batch(self, batch_id: int, table: Table) -> None:
        with self._lock:
            if batch_id in self._batches:
                return
            self._batches[batch_id] = table

    def table(self) -> Table:
        with self._lock:
            items = sorted(self._batches.items())
        out: "Table | None" = None
        for _bid, t in items:
            if t.num_rows == 0:
                continue
            out = t if out is None else out.concat(t)
        return out if out is not None else Table({})

    def batch_ids(self) -> list[int]:
        with self._lock:
            return sorted(self._batches)


class ParquetSink(Sink):
    """One `part-<batchId>.parquet` per batch, written to a dot-prefixed
    temp name and os.replace'd into place — the visible file is always
    complete, and an existing part file means a pre-crash attempt already
    wrote this batch (identical bytes, by the replay contract), so the
    write is skipped. Empty batches produce no file."""

    _PART_FMT = "part-{:09d}.parquet"

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _part(self, batch_id: int) -> str:
        return os.path.join(self.path, self._PART_FMT.format(batch_id))

    def add_batch(self, batch_id: int, table: Table) -> None:
        if table.num_rows == 0:
            return
        final = self._part(batch_id)
        if os.path.exists(final):
            return
        tmp = os.path.join(
            self.path, f".tmp-{self._PART_FMT.format(batch_id)}")
        write_parquet(table, tmp)
        os.replace(tmp, final)

    def table(self) -> Table:
        """All committed parts concatenated in batch order (test/validation
        convenience, mirroring MemorySink.table)."""
        from ..core.table_io import read_parquet

        out: "Table | None" = None
        for name in sorted(os.listdir(self.path)):
            if name.startswith("part-") and name.endswith(".parquet"):
                t = read_parquet(os.path.join(self.path, name))
                out = t if out is None else out.concat(t)
        return out if out is not None else Table({})


class ForeachBatchSink(Sink):
    """User callback per batch (Spark's foreachBatch): fn(table, batch_id).
    At-least-once — after a crash between the callback and the commit
    record, the replayed batch calls fn again with the SAME batch_id, so
    callbacks that need exactly-once must dedupe on it."""

    def __init__(self, fn: Callable[[Table, int], Any]) -> None:
        self.fn = fn

    def add_batch(self, batch_id: int, table: Table) -> None:
        self.fn(table, batch_id)


class PowerBISink(Sink):
    """Each batch POSTs to a Power BI push dataset via PowerBIWriter — the
    reference's `writeStream.format("console")`-free production demo
    (PowerBIWriter.scala `stream`). At-least-once: the REST API has no
    batch-id dedupe, so a crash inside the commit window can repost a
    batch (true of the reference's sink too)."""

    def __init__(self, url: str, batch_size: int = 100,
                 concurrency: int = 1, client: Any = None) -> None:
        self.url = url
        self.batch_size = batch_size
        self.concurrency = concurrency
        self.client = client
        self.requests_sent = 0

    def add_batch(self, batch_id: int, table: Table) -> None:
        if table.num_rows == 0:
            return
        from ..io_http.powerbi import PowerBIWriter

        self.requests_sent += PowerBIWriter.write(
            table, self.url, batch_size=self.batch_size,
            concurrency=self.concurrency, client=self.client)


class ReplySink(Sink):
    """Completes ServingSource batches: expects `id` + `reply` columns (the
    shape `make_reply` produces with the id carried through) and answers
    the parked HTTP exchanges. Exactly-once rides on the serving journal:
    a replayed batch's already-answered ids are suppressed as duplicates
    inside ServingServer.reply."""

    def __init__(self, server: Any) -> None:
        self.server = server

    def add_batch(self, batch_id: int, table: Table) -> None:
        if table.num_rows == 0:
            return
        self.server.reply_table(table)
