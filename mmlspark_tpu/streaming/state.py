"""Stateful streaming operators: running and windowed aggregation with
watermarks.

Reference: Spark's `groupBy().agg()` on a stream (complete output mode)
and `groupBy(window(...)).agg()` with `withWatermark` — the engine keeps
per-group running state across micro-batches, drops rows older than the
watermark, and finalizes a window only once the watermark passes its
end, at which point its state is evicted. The reference's serving and
anomaly pipelines run exactly these shapes over HTTP sources.

TPU redesign: the operators are ordinary registered Transformer stages —
`transform(batch)` folds the batch into held state and returns that
batch's output — so a StreamingQuery can put them inside any
PipelineModel and the registry machinery (fuzzing, R wrappers, api docs)
picks them up like any other stage. State is a JSON-able doc exposed via
`state_doc`/`load_state_doc`: the StreamingQuery snapshots it through
the commit log before every sink write (and restores the pre-batch doc
if the batch fails), which is what makes replay after kill-and-restart
produce identical output. The same doc flows through `_save_state`, so
`save/load` round-trips mid-stream state too.

Aggregates are kept as (count, sum, min, max) tuples — every supported
agg ("count", "sum", "mean", "min", "max") is derivable, and merging a
batch is O(rows) python regardless of which agg is requested.

Distributed additions (streaming/partition.py): state docs are key-order
DETERMINISTIC (sorted), so two runs that folded the same rows in a
different arrival order still checkpoint byte-identical docs — the
per-partition incremental-checkpoint diff depends on it. Accumulator
storage is pluggable through `StateBackend` (in-memory dict, or a
bounded hot set spilling cold keys to parquet), and operators accept a
driver-supplied `set_time_hint` so watermarks in a P-way run advance on
the GLOBAL batch rather than each partition's slice of it.
"""

from __future__ import annotations

import os
import uuid
from typing import Any

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.schema import Table
from ..core.serialize import register_stage

__all__ = ["StatefulOperator", "GroupedAggregator", "WindowedAggregator",
           "StateBackend", "MemoryStateBackend", "SpillingStateBackend"]

_AGGS = ("count", "sum", "mean", "min", "max")


def _new_acc() -> list:
    return [0, 0.0, None, None]          # [count, sum, min, max]


def _fold(acc: list, v: float) -> None:
    acc[0] += 1
    acc[1] += v
    acc[2] = v if acc[2] is None else min(acc[2], v)
    acc[3] = v if acc[3] is None else max(acc[3], v)


def _emit(acc: list, agg: str) -> float:
    if agg == "count":
        return float(acc[0])
    if agg == "sum":
        return float(acc[1])
    if agg == "mean":
        return float(acc[1]) / acc[0] if acc[0] else float("nan")
    if agg == "min":
        return float(acc[2]) if acc[2] is not None else float("nan")
    return float(acc[3]) if acc[3] is not None else float("nan")


class StateBackend:
    """Storage contract for per-key accumulator state.

    A stateful operator folds into mutable per-key accumulator lists via
    `acc(key)` and reads everything back — sorted by key — for emission
    and checkpointing. Backends trade memory for IO: `MemoryStateBackend`
    is a plain dict; `SpillingStateBackend` keeps a bounded hot set and
    spills cold keys to parquet, faulting them back on access.
    `end_batch()` is the operator's signal that a batch's folds are done;
    the spill backend enforces its hot-key bound there so mid-batch folds
    never thrash the spill file.
    """

    spilled_bytes = 0

    def acc(self, key: str) -> list:
        """Get-or-create the accumulator for `key` (mutated in place)."""
        raise NotImplementedError

    def items(self) -> "list[tuple[str, list]]":
        """Every (key, accumulator), sorted by key."""
        raise NotImplementedError

    def doc(self) -> dict:
        """Sorted-key JSON-able materialization of the full state."""
        return {k: list(v) for k, v in self.items()}

    def load(self, doc: dict) -> None:
        raise NotImplementedError

    def end_batch(self) -> None:
        """Called once per batch after the fold loop."""

    def __len__(self) -> int:
        raise NotImplementedError


class MemoryStateBackend(StateBackend):
    """All accumulators in one dict — the default, zero-IO backend."""

    def __init__(self) -> None:
        self._state: dict[str, list] = {}

    def acc(self, key: str) -> list:
        return self._state.setdefault(key, _new_acc())

    def items(self) -> "list[tuple[str, list]]":
        return sorted(self._state.items())

    def load(self, doc: dict) -> None:
        self._state = {str(k): list(v) for k, v in (doc or {}).items()}

    def __len__(self) -> int:
        return len(self._state)


class SpillingStateBackend(StateBackend):
    """Bounded-memory backend: at most `hot_keys` accumulators stay
    resident; the rest live in one parquet spill file under `spill_dir`
    and fault back on access. Faults are read-only (the cold index, not
    the file, is authoritative — stale rows are dropped at the next
    spill rewrite), so a fault costs one file read. `items()`/`doc()`
    read the file once WITHOUT promoting cold keys, so complete-mode
    emission and checkpointing leave the hot set untouched.
    """

    def __init__(self, spill_dir: str, hot_keys: int = 1024):
        os.makedirs(spill_dir, exist_ok=True)
        self.dir = spill_dir
        self.hot_keys = int(hot_keys)
        self.path = os.path.join(
            spill_dir, f"spill-{uuid.uuid4().hex}.parquet")
        self._hot: dict[str, list] = {}
        self._cold: set[str] = set()
        self.spilled_bytes = 0
        self.faults = 0

    def _read_cold(self) -> dict[str, list]:
        if not self._cold:
            return {}
        from ..core.table_io import read_parquet

        t = read_parquet(self.path)
        keys, cnt = t["key"], t["count"]
        sm, mn, mx = t["sum"], t["min"], t["max"]
        return {
            str(k): [int(cnt[i]), float(sm[i]),
                     None if np.isnan(mn[i]) else float(mn[i]),
                     None if np.isnan(mx[i]) else float(mx[i])]
            for i, k in enumerate(keys) if str(k) in self._cold}

    def _write_cold(self, cold: dict[str, list]) -> None:
        self._cold = set(cold)
        if not cold:
            if os.path.exists(self.path):
                os.unlink(self.path)
            self.spilled_bytes = 0
            return
        from ..core.table_io import write_parquet

        keys = sorted(cold)
        write_parquet(Table({
            "key": [str(k) for k in keys],
            "count": np.array([cold[k][0] for k in keys], dtype=np.float64),
            "sum": np.array([cold[k][1] for k in keys], dtype=np.float64),
            "min": np.array(
                [np.nan if cold[k][2] is None else cold[k][2]
                 for k in keys], dtype=np.float64),
            "max": np.array(
                [np.nan if cold[k][3] is None else cold[k][3]
                 for k in keys], dtype=np.float64),
        }), self.path)
        self.spilled_bytes = os.path.getsize(self.path)

    def acc(self, key: str) -> list:
        a = self._hot.get(key)
        if a is not None:
            # refresh recency: end_batch evicts least-recently-touched
            del self._hot[key]
        elif key in self._cold:
            a = self._read_cold()[key]
            self._cold.discard(key)
            self.faults += 1
        else:
            a = _new_acc()
        self._hot[key] = a
        return a

    def end_batch(self) -> None:
        over = len(self._hot) - self.hot_keys
        if over <= 0:
            return
        cold = self._read_cold()
        for k in list(self._hot)[:over]:
            cold[k] = self._hot.pop(k)
        self._write_cold(cold)

    def items(self) -> "list[tuple[str, list]]":
        merged = self._read_cold()
        merged.update(self._hot)
        return sorted(merged.items())

    def load(self, doc: dict) -> None:
        self._hot = {str(k): list(v) for k, v in (doc or {}).items()}
        self._write_cold({})
        self.end_batch()

    def __len__(self) -> int:
        return len(self._hot) + len(self._cold)


class StatefulOperator(Transformer):
    """Marker + contract for operators whose output depends on state folded
    across batches. StreamingQuery walks its transform for instances and
    checkpoints `state_doc()` per batch."""

    def state_doc(self) -> dict:
        """JSON-able snapshot of the held state. MUST be key-order
        deterministic (sorted) so identical state serializes to identical
        bytes regardless of arrival order."""
        raise NotImplementedError

    def load_state_doc(self, doc: dict) -> None:
        raise NotImplementedError

    def reset_state(self) -> None:
        self.load_state_doc({})

    # -- distributed-run contract (streaming/partition.py) ----------------- #

    def set_time_hint(self, t: "float | None") -> None:
        """Driver-supplied max event time of the GLOBAL batch about to
        transform. A partition folding only its slice would otherwise
        advance its watermark on the slice's max — time hints keep every
        partition's watermark equal to the single-partition run's, which
        is what makes P-way output byte-identical. No-op for operators
        without event-time semantics."""

    def merge_sort_cols(self) -> "list[str] | None":
        """Output columns a P-way merge must stable-sort by to
        reconstruct the single-partition output; None = the output has
        no canonical order (the merge restores original row order by a
        hidden row tag instead)."""
        return None

    def partition_key_col(self) -> "str | None":
        """Column this operator's state is keyed by — a keyed shuffle on
        exactly this column makes the operator partitionable. None =
        unkeyed state (single-partition only)."""
        return None

    # checkpoint doc doubles as the save/load persistence payload
    def _save_state(self) -> dict[str, Any]:
        return {"stream_state": self.state_doc()}

    def _load_state(self, state: dict[str, Any]) -> None:
        self.load_state_doc(state.get("stream_state") or {})


def _values_of(table: Table, col: "str | None") -> "np.ndarray":
    """The numeric column to aggregate; all-ones when counting rows."""
    if col is None:
        return np.ones(table.num_rows, dtype=np.float64)
    return np.asarray(table[col], dtype=np.float64)


def _groups_of(table: Table, col: "str | None") -> list:
    if col is None:
        return [""] * table.num_rows    # single implicit group
    return [str(g) for g in table[col]]


@register_stage
class GroupedAggregator(StatefulOperator):
    """Running grouped aggregation in complete output mode: each batch
    folds into per-group accumulators and `transform` returns the CURRENT
    aggregate for every group seen so far, sorted by group key."""

    group_col = Param("key", "grouping column; rows sharing a value share "
                      "an accumulator", ptype=str)
    value_col = Param(None, "numeric column to aggregate; None counts rows",
                      ptype=str)
    agg = Param("count", "one of count|sum|mean|min|max", ptype=str,
                validator=lambda v: v in _AGGS)
    output_col = Param("aggregate", "output column holding the aggregate",
                       ptype=str)
    state_backend = Param("memory", "accumulator storage: 'memory' (one "
                          "dict) or 'spill' (bounded hot set + parquet "
                          "spill file)", ptype=str,
                          validator=lambda v: v in ("memory", "spill"))
    spill_dir = Param(None, "spill-file directory (required by the "
                      "'spill' backend)", ptype=str)
    spill_hot_keys = Param(1024, "max in-memory keys before the 'spill' "
                           "backend evicts cold keys to parquet",
                           ptype=int, validator=lambda v: v >= 1)

    # class-level default: blob/file reconstruction (`load_stage`) builds
    # via cls.__new__ and restores through load_state_doc without __init__
    _backend: "StateBackend | None" = None

    def backend(self) -> StateBackend:
        if self._backend is None:
            if self.get("state_backend") == "spill":
                d = self.get("spill_dir")
                if not d:
                    raise ValueError(
                        "state_backend='spill' requires spill_dir")
                self._backend = SpillingStateBackend(
                    d, self.get("spill_hot_keys"))
            else:
                self._backend = MemoryStateBackend()
        return self._backend

    @property
    def spilled_bytes(self) -> int:
        return self.backend().spilled_bytes

    def state_doc(self) -> dict:
        return {"groups": self.backend().doc()}

    def load_state_doc(self, doc: dict) -> None:
        self.backend().load(doc.get("groups") or {})

    def reset_state(self) -> None:
        self.backend().load({})

    def merge_sort_cols(self) -> "list[str] | None":
        return [self.get("group_col")]

    def partition_key_col(self) -> "str | None":
        return self.get("group_col")

    def _transform(self, table: Table) -> Table:
        b = self.backend()
        if table.num_rows:
            groups = _groups_of(table, self.get("group_col"))
            values = _values_of(table, self.get("value_col"))
            for g, v in zip(groups, values):
                _fold(b.acc(g), float(v))
            b.end_batch()
        agg = self.get("agg")
        items = b.items()
        return Table({
            self.get("group_col"): [k for k, _ in items],
            self.get("output_col"):
                np.array([_emit(acc, agg) for _, acc in items],
                         dtype=np.float64),
        })


@register_stage
class WindowedAggregator(StatefulOperator):
    """Tumbling-window aggregation with a watermark: rows are bucketed by
    `floor(time / window_s)`, rows older than the watermark are DROPPED
    (counted in `late_rows_dropped`), and a window is emitted exactly once
    — when the watermark (max event time seen minus `watermark_delay_s`)
    passes its end — then its state is evicted.

    Late-drop uses the watermark as of the START of the batch (the
    previous batches' event times), matching Spark: a batch cannot
    retroactively declare its own rows late. Emission uses the watermark
    AFTER folding the batch, so a single batch whose max event time
    clears `window_end + delay` finalizes that window immediately.
    `transform` returns only the windows finalized by that batch (append
    output mode), sorted by window start then group."""

    time_col = Param("time", "event-time column, in seconds", ptype=str)
    window_s = Param(60.0, "tumbling window length in seconds", ptype=float,
                     validator=lambda v: v > 0)
    group_col = Param(None, "optional sub-grouping column within windows",
                      ptype=str)
    value_col = Param(None, "numeric column to aggregate; None counts rows",
                      ptype=str)
    agg = Param("count", "one of count|sum|mean|min|max", ptype=str,
                validator=lambda v: v in _AGGS)
    output_col = Param("aggregate", "output column holding the aggregate",
                       ptype=str)
    watermark_delay_s = Param(0.0, "how long to admit out-of-order rows "
                              "past the max event time seen", ptype=float,
                              validator=lambda v: v >= 0)

    # class-level default: reconstruction via load_stage skips __init__
    # and only load_state_doc runs, which never carries a pending hint
    _time_hint: "float | None" = None

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        # {window_start(str): {group(str): [count, sum, min, max]}}
        self._windows: dict[str, dict[str, list]] = {}
        self._max_t: "float | None" = None
        self._time_hint: "float | None" = None
        self.late_rows_dropped = 0

    def state_doc(self) -> dict:
        return {
            "windows": {ws: {g: list(groups[g]) for g in sorted(groups)}
                        for ws, groups in sorted(self._windows.items(),
                                                 key=lambda kv:
                                                 float(kv[0]))},
            "max_t": self._max_t,
            "late": self.late_rows_dropped,
        }

    def load_state_doc(self, doc: dict) -> None:
        self._windows = {
            str(ws): {str(g): list(acc) for g, acc in groups.items()}
            for ws, groups in (doc.get("windows") or {}).items()}
        self._max_t = doc.get("max_t")
        self.late_rows_dropped = int(doc.get("late") or 0)

    def reset_state(self) -> None:
        self._windows = {}
        self._max_t = None
        self.late_rows_dropped = 0

    def watermark(self) -> "float | None":
        if self._max_t is None:
            return None
        return self._max_t - self.get("watermark_delay_s")

    def set_time_hint(self, t: "float | None") -> None:
        self._time_hint = t

    def merge_sort_cols(self) -> "list[str] | None":
        cols = ["window_start"]
        if self.get("group_col") is not None:
            cols.append(self.get("group_col"))
        return cols

    def partition_key_col(self) -> "str | None":
        return self.get("group_col")

    def _transform(self, table: Table) -> Table:
        win = self.get("window_s")
        low = self.watermark()          # watermark BEFORE this batch
        if table.num_rows:
            times = np.asarray(table[self.get("time_col")], dtype=np.float64)
            groups = _groups_of(table, self.get("group_col"))
            values = _values_of(table, self.get("value_col"))
            for t, g, v in zip(times, groups, values):
                t = float(t)
                if low is not None and t < low:
                    self.late_rows_dropped += 1
                    continue
                ws = float(np.floor(t / win) * win)
                bucket = self._windows.setdefault(repr(ws), {})
                _fold(bucket.setdefault(g, _new_acc()), float(v))
                if self._max_t is None or t > self._max_t:
                    self._max_t = t
        # the driver's time hint carries the GLOBAL batch max event time
        # (this partition's slice may be behind it — or empty); consumed
        # after the fold so late-drop still used the batch-START watermark
        hint, self._time_hint = self._time_hint, None
        if hint is not None and (self._max_t is None or hint > self._max_t):
            self._max_t = hint
        # finalize windows the post-batch watermark has passed
        high = self.watermark()
        agg = self.get("agg")
        done: list[tuple[float, str, list]] = []
        if high is not None:
            for ws_key in list(self._windows):
                ws = float(ws_key)
                if ws + win <= high:
                    for g, acc in self._windows.pop(ws_key).items():
                        done.append((ws, g, acc))
        done.sort(key=lambda x: (x[0], x[1]))
        cols: dict[str, Any] = {
            "window_start": np.array([d[0] for d in done], dtype=np.float64),
            "window_end": np.array([d[0] + win for d in done],
                                   dtype=np.float64),
        }
        if self.get("group_col") is not None:
            cols[self.get("group_col")] = [d[1] for d in done]
        cols[self.get("output_col")] = np.array(
            [_emit(d[2], agg) for d in done], dtype=np.float64)
        return Table(cols)

    def flush(self) -> Table:
        """Emit every still-open window regardless of watermark (end-of-
        stream drain); clears state."""
        win = self.get("window_s")
        agg = self.get("agg")
        done = [(float(ws), g, acc)
                for ws, groups in self._windows.items()
                for g, acc in groups.items()]
        done.sort(key=lambda x: (x[0], x[1]))
        self._windows = {}
        cols: dict[str, Any] = {
            "window_start": np.array([d[0] for d in done], dtype=np.float64),
            "window_end": np.array([d[0] + win for d in done],
                                   dtype=np.float64),
        }
        if self.get("group_col") is not None:
            cols[self.get("group_col")] = [d[1] for d in done]
        cols[self.get("output_col")] = np.array(
            [_emit(d[2], agg) for d in done], dtype=np.float64)
        return Table(cols)
