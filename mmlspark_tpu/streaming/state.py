"""Stateful streaming operators: running and windowed aggregation with
watermarks.

Reference: Spark's `groupBy().agg()` on a stream (complete output mode)
and `groupBy(window(...)).agg()` with `withWatermark` — the engine keeps
per-group running state across micro-batches, drops rows older than the
watermark, and finalizes a window only once the watermark passes its
end, at which point its state is evicted. The reference's serving and
anomaly pipelines run exactly these shapes over HTTP sources.

TPU redesign: the operators are ordinary registered Transformer stages —
`transform(batch)` folds the batch into held state and returns that
batch's output — so a StreamingQuery can put them inside any
PipelineModel and the registry machinery (fuzzing, R wrappers, api docs)
picks them up like any other stage. State is a JSON-able doc exposed via
`state_doc`/`load_state_doc`: the StreamingQuery snapshots it through
the commit log before every sink write (and restores the pre-batch doc
if the batch fails), which is what makes replay after kill-and-restart
produce identical output. The same doc flows through `_save_state`, so
`save/load` round-trips mid-stream state too.

Aggregates are kept as (count, sum, min, max) tuples — every supported
agg ("count", "sum", "mean", "min", "max") is derivable, and merging a
batch is O(rows) python regardless of which agg is requested.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.schema import Table
from ..core.serialize import register_stage

__all__ = ["StatefulOperator", "GroupedAggregator", "WindowedAggregator"]

_AGGS = ("count", "sum", "mean", "min", "max")


def _new_acc() -> list:
    return [0, 0.0, None, None]          # [count, sum, min, max]


def _fold(acc: list, v: float) -> None:
    acc[0] += 1
    acc[1] += v
    acc[2] = v if acc[2] is None else min(acc[2], v)
    acc[3] = v if acc[3] is None else max(acc[3], v)


def _emit(acc: list, agg: str) -> float:
    if agg == "count":
        return float(acc[0])
    if agg == "sum":
        return float(acc[1])
    if agg == "mean":
        return float(acc[1]) / acc[0] if acc[0] else float("nan")
    if agg == "min":
        return float(acc[2]) if acc[2] is not None else float("nan")
    return float(acc[3]) if acc[3] is not None else float("nan")


class StatefulOperator(Transformer):
    """Marker + contract for operators whose output depends on state folded
    across batches. StreamingQuery walks its transform for instances and
    checkpoints `state_doc()` per batch."""

    def state_doc(self) -> dict:
        """JSON-able snapshot of the held state."""
        raise NotImplementedError

    def load_state_doc(self, doc: dict) -> None:
        raise NotImplementedError

    def reset_state(self) -> None:
        self.load_state_doc({})

    # checkpoint doc doubles as the save/load persistence payload
    def _save_state(self) -> dict[str, Any]:
        return {"stream_state": self.state_doc()}

    def _load_state(self, state: dict[str, Any]) -> None:
        self.load_state_doc(state.get("stream_state") or {})


def _values_of(table: Table, col: "str | None") -> "np.ndarray":
    """The numeric column to aggregate; all-ones when counting rows."""
    if col is None:
        return np.ones(table.num_rows, dtype=np.float64)
    return np.asarray(table[col], dtype=np.float64)


def _groups_of(table: Table, col: "str | None") -> list:
    if col is None:
        return [""] * table.num_rows    # single implicit group
    return [str(g) for g in table[col]]


@register_stage
class GroupedAggregator(StatefulOperator):
    """Running grouped aggregation in complete output mode: each batch
    folds into per-group accumulators and `transform` returns the CURRENT
    aggregate for every group seen so far, sorted by group key."""

    group_col = Param("key", "grouping column; rows sharing a value share "
                      "an accumulator", ptype=str)
    value_col = Param(None, "numeric column to aggregate; None counts rows",
                      ptype=str)
    agg = Param("count", "one of count|sum|mean|min|max", ptype=str,
                validator=lambda v: v in _AGGS)
    output_col = Param("aggregate", "output column holding the aggregate",
                       ptype=str)

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._state: dict[str, list] = {}

    def state_doc(self) -> dict:
        return {"groups": {k: list(v) for k, v in self._state.items()}}

    def load_state_doc(self, doc: dict) -> None:
        self._state = {str(k): list(v)
                       for k, v in (doc.get("groups") or {}).items()}

    def reset_state(self) -> None:
        self._state = {}

    def _transform(self, table: Table) -> Table:
        if table.num_rows:
            groups = _groups_of(table, self.get("group_col"))
            values = _values_of(table, self.get("value_col"))
            for g, v in zip(groups, values):
                _fold(self._state.setdefault(g, _new_acc()), float(v))
        agg = self.get("agg")
        keys = sorted(self._state)
        return Table({
            self.get("group_col"): list(keys),
            self.get("output_col"):
                np.array([_emit(self._state[k], agg) for k in keys],
                         dtype=np.float64),
        })


@register_stage
class WindowedAggregator(StatefulOperator):
    """Tumbling-window aggregation with a watermark: rows are bucketed by
    `floor(time / window_s)`, rows older than the watermark are DROPPED
    (counted in `late_rows_dropped`), and a window is emitted exactly once
    — when the watermark (max event time seen minus `watermark_delay_s`)
    passes its end — then its state is evicted.

    Late-drop uses the watermark as of the START of the batch (the
    previous batches' event times), matching Spark: a batch cannot
    retroactively declare its own rows late. Emission uses the watermark
    AFTER folding the batch, so a single batch whose max event time
    clears `window_end + delay` finalizes that window immediately.
    `transform` returns only the windows finalized by that batch (append
    output mode), sorted by window start then group."""

    time_col = Param("time", "event-time column, in seconds", ptype=str)
    window_s = Param(60.0, "tumbling window length in seconds", ptype=float,
                     validator=lambda v: v > 0)
    group_col = Param(None, "optional sub-grouping column within windows",
                      ptype=str)
    value_col = Param(None, "numeric column to aggregate; None counts rows",
                      ptype=str)
    agg = Param("count", "one of count|sum|mean|min|max", ptype=str,
                validator=lambda v: v in _AGGS)
    output_col = Param("aggregate", "output column holding the aggregate",
                       ptype=str)
    watermark_delay_s = Param(0.0, "how long to admit out-of-order rows "
                              "past the max event time seen", ptype=float,
                              validator=lambda v: v >= 0)

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        # {window_start(str): {group(str): [count, sum, min, max]}}
        self._windows: dict[str, dict[str, list]] = {}
        self._max_t: "float | None" = None
        self.late_rows_dropped = 0

    def state_doc(self) -> dict:
        return {
            "windows": {ws: {g: list(acc) for g, acc in groups.items()}
                        for ws, groups in self._windows.items()},
            "max_t": self._max_t,
            "late": self.late_rows_dropped,
        }

    def load_state_doc(self, doc: dict) -> None:
        self._windows = {
            str(ws): {str(g): list(acc) for g, acc in groups.items()}
            for ws, groups in (doc.get("windows") or {}).items()}
        self._max_t = doc.get("max_t")
        self.late_rows_dropped = int(doc.get("late") or 0)

    def reset_state(self) -> None:
        self._windows = {}
        self._max_t = None
        self.late_rows_dropped = 0

    def watermark(self) -> "float | None":
        if self._max_t is None:
            return None
        return self._max_t - self.get("watermark_delay_s")

    def _transform(self, table: Table) -> Table:
        win = self.get("window_s")
        low = self.watermark()          # watermark BEFORE this batch
        if table.num_rows:
            times = np.asarray(table[self.get("time_col")], dtype=np.float64)
            groups = _groups_of(table, self.get("group_col"))
            values = _values_of(table, self.get("value_col"))
            for t, g, v in zip(times, groups, values):
                t = float(t)
                if low is not None and t < low:
                    self.late_rows_dropped += 1
                    continue
                ws = float(np.floor(t / win) * win)
                bucket = self._windows.setdefault(repr(ws), {})
                _fold(bucket.setdefault(g, _new_acc()), float(v))
                if self._max_t is None or t > self._max_t:
                    self._max_t = t
        # finalize windows the post-batch watermark has passed
        high = self.watermark()
        agg = self.get("agg")
        done: list[tuple[float, str, list]] = []
        if high is not None:
            for ws_key in list(self._windows):
                ws = float(ws_key)
                if ws + win <= high:
                    for g, acc in self._windows.pop(ws_key).items():
                        done.append((ws, g, acc))
        done.sort(key=lambda x: (x[0], x[1]))
        cols: dict[str, Any] = {
            "window_start": np.array([d[0] for d in done], dtype=np.float64),
            "window_end": np.array([d[0] + win for d in done],
                                   dtype=np.float64),
        }
        if self.get("group_col") is not None:
            cols[self.get("group_col")] = [d[1] for d in done]
        cols[self.get("output_col")] = np.array(
            [_emit(d[2], agg) for d in done], dtype=np.float64)
        return Table(cols)

    def flush(self) -> Table:
        """Emit every still-open window regardless of watermark (end-of-
        stream drain); clears state."""
        win = self.get("window_s")
        agg = self.get("agg")
        done = [(float(ws), g, acc)
                for ws, groups in self._windows.items()
                for g, acc in groups.items()]
        done.sort(key=lambda x: (x[0], x[1]))
        self._windows = {}
        cols: dict[str, Any] = {
            "window_start": np.array([d[0] for d in done], dtype=np.float64),
            "window_end": np.array([d[0] + win for d in done],
                                   dtype=np.float64),
        }
        if self.get("group_col") is not None:
            cols[self.get("group_col")] = [d[1] for d in done]
        cols[self.get("output_col")] = np.array(
            [_emit(d[2], agg) for d in done], dtype=np.float64)
        return Table(cols)
