"""Write-ahead commit log + state snapshots for streaming queries.

Reference contract: Spark's streaming checkpoint directory holds an
`offsets/<batchId>` file written BEFORE a batch runs and a
`commits/<batchId>` file written after the sink accepts it; on restart
the query replays the last planned-but-uncommitted batch against the
exact offsets in its plan file. That plan-first ordering is what makes
replay deterministic: the restarted query re-forms the in-flight batch
from the RECORDED offset range, not from whatever the source contains
now, so an idempotent sink sees byte-identical data for the same
batch id.

TPU redesign: one append-only JSONL log (`commits.jsonl`) carries both
record types — `{"t": "plan", "batch_id", "start", "end"}` and
`{"t": "commit", "batch_id"}` — with the serving journal's durability
idioms (io_http/journal.py): write+flush+fsync per record, torn-tail
detection with on-disk truncation at load, atomic compact via
`utils.storage.atomic_write` (tmp → fsync → os.replace → dir-fsync).
Stateful-operator snapshots live beside it as `state-<batchId>.json`,
written atomically before the sink write so a replayed batch restarts
its operators from the state that PRECEDED the crashed attempt. A
snapshot that fails to parse at recovery (bit-flip, torn pre-upgrade
write) is skipped — recovery falls back to the newest older snapshot at
or before the last commit and emits a `checkpoint.corrupt` recorder
event plus a `mmlspark_tpu_checkpoint_corrupt_total` count.
"""

from __future__ import annotations

import json
import os
import threading

from ..observability.sanitizer import allow_blocking, make_lock
from ..utils.storage import atomic_write

__all__ = ["CommitLog"]


def _note_corrupt(path: str, detail: str) -> None:
    """Count + record a snapshot that failed to parse (never raises)."""
    try:
        from ..resilience.elastic import _count, _record

        _count("mmlspark_tpu_checkpoint_corrupt_total",
               "checkpoint snapshots/manifests that failed verification")
        _record("checkpoint.corrupt", file=path, what=detail)
    except Exception:  # noqa: BLE001 — telemetry never blocks recovery
        pass


class CommitLog:
    """Plan/commit write-ahead log under `checkpoint_dir/commits.jsonl`."""

    FILENAME = "commits.jsonl"
    _STATE_FMT = "state-{:09d}.json"

    def __init__(self, checkpoint_dir: str):
        self.dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.path = os.path.join(checkpoint_dir, self.FILENAME)
        self._lock = make_lock("CommitLog._lock")
        self._plans: dict[int, dict] = {}   # batch_id -> {"start", "end"}
        self._committed: set[int] = set()
        self._load()
        self._fh = open(self.path, "a", encoding="utf-8")

    # -- load / durability (journal.py idioms) ---------------------------- #

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        good_end = 0     # byte offset just past the last intact record
        with open(self.path, "rb") as fh:
            for raw in fh:
                if not raw.endswith(b"\n"):
                    break                       # torn tail (no newline)
                line = raw.strip()
                if not line:
                    good_end += len(raw)
                    continue
                try:
                    rec = json.loads(line.decode("utf-8"))
                except (json.JSONDecodeError, UnicodeDecodeError):
                    break                       # torn record mid-append
                good_end += len(raw)
                self._apply(rec)
        # truncate the torn tail ON DISK (appending after a partial line
        # would fuse the next record onto it — see journal.py._load)
        if good_end < os.path.getsize(self.path):
            with open(self.path, "r+b") as fh:
                fh.truncate(good_end)

    def _apply(self, rec: dict) -> None:
        if rec.get("t") == "plan":
            self._plans[int(rec["batch_id"])] = {
                "start": rec.get("start"), "end": rec.get("end")}
        elif rec.get("t") == "commit":
            self._committed.add(int(rec["batch_id"]))

    def _append(self, rec: dict) -> None:
        # Write + flush under the caller's lock (preserves record order);
        # the durability fsync happens in _sync() AFTER the lock is
        # released — group commit.
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def _sync(self) -> None:
        # fsync flushes the whole fd, so records flushed by other threads
        # between our _append and this call ride along for free.
        fh = self._fh
        try:
            os.fsync(fh.fileno())
        except (OSError, ValueError):
            # fd replaced or closed by a concurrent compact()/close();
            # the compacted file is already durable (atomic_write fsyncs
            # before rename), so there is nothing left to sync.
            pass

    # -- plan / commit ---------------------------------------------------- #

    def plan(self, batch_id: int, start, end) -> None:
        """Record the offset range of `batch_id` BEFORE running it.
        Offsets are JSON-able dicts (or None for 'beginning of stream')."""
        with self._lock:
            self._plans[batch_id] = {"start": start, "end": end}
            self._append({"t": "plan", "batch_id": batch_id,
                          "start": start, "end": end})
        self._sync()

    def planned(self, batch_id: int) -> dict | None:
        """{"start", "end"} of a planned batch, or None."""
        with self._lock:
            return self._plans.get(batch_id)

    def commit(self, batch_id: int) -> None:
        with self._lock:
            if batch_id in self._committed:
                return
            self._committed.add(batch_id)
            self._append({"t": "commit", "batch_id": batch_id})
        self._sync()

    def last_committed(self) -> int:
        """Highest committed batch id; -1 when nothing has committed."""
        with self._lock:
            return max(self._committed, default=-1)

    # -- state snapshots --------------------------------------------------- #

    def _state_path(self, batch_id: int) -> str:
        return os.path.join(self.dir, self._STATE_FMT.format(batch_id))

    def write_state(self, batch_id: int, doc: dict) -> None:
        """Atomically snapshot stateful-operator state as of AFTER
        `batch_id` (atomic_write: tmp + fsync + rename, so a crash
        mid-write leaves the previous snapshot intact and a replay
        simply overwrites)."""
        atomic_write(self._state_path(batch_id), json.dumps(doc))

    def _state_batch_ids(self) -> "list[int]":
        """Batch ids of all whole-query snapshots on disk, ascending."""
        out = []
        for name in os.listdir(self.dir):
            if not (name.startswith("state-") and name.endswith(".json")):
                continue
            if self._parse_pstate(name) is not None:
                continue                        # per-partition snapshot
            try:
                out.append(int(name[len("state-"):-len(".json")]))
            except ValueError:
                continue
        return sorted(out)

    def read_state(self, batch_id: int) -> dict | None:
        """Newest intact whole-query snapshot at or before `batch_id`.

        A snapshot that no longer parses is skipped (counted and
        recorded) and recovery falls back to the next-older one — a
        stale-but-consistent restore beats discarding all state."""
        for bid in reversed([b for b in self._state_batch_ids()
                             if b <= batch_id]):
            path = self._state_path(bid)
            try:
                with open(path, encoding="utf-8") as fh:
                    return json.load(fh)
            except FileNotFoundError:
                continue
            except (json.JSONDecodeError, UnicodeDecodeError):
                _note_corrupt(path, "state-snapshot")
                continue
        return None

    # -- per-partition incremental snapshots ------------------------------- #
    #
    # A P-way query (streaming/partition.py) checkpoints each partition's
    # operator state in its own file, and only for batches where that
    # partition's state CHANGED — so a batch touching 1 of 64 partitions
    # writes one small file, not the whole state. Recovery reads, per
    # partition, the newest snapshot at or before the last committed
    # batch. The same plan/commit records gate replay; only the snapshot
    # layout is partition-aware.

    _PSTATE_FMT = "state-p{:04d}-{:09d}.json"

    def _pstate_path(self, partition: int, batch_id: int) -> str:
        return os.path.join(self.dir,
                            self._PSTATE_FMT.format(partition, batch_id))

    @staticmethod
    def _parse_pstate(name: str) -> "tuple[int, int] | None":
        """(partition, batch_id) from a per-partition snapshot filename."""
        if not (name.startswith("state-p") and name.endswith(".json")):
            return None
        body = name[len("state-p"):-len(".json")]
        part, sep, bid = body.partition("-")
        if not sep:
            return None
        try:
            return int(part), int(bid)
        except ValueError:
            return None

    def write_partition_state(self, partition: int, batch_id: int,
                              doc: dict) -> None:
        """Atomically snapshot ONE partition's operator state as of after
        `batch_id` (same atomic_write durability as `write_state`)."""
        atomic_write(self._pstate_path(partition, batch_id),
                     json.dumps(doc, sort_keys=True))

    def read_partition_state(self, partition: int,
                             batch_id: int) -> dict | None:
        """Newest intact snapshot of `partition` at or before `batch_id`
        — the incremental layout means the partition may not have written
        at `batch_id` itself if nothing changed since an earlier batch.
        Corrupt snapshots are skipped (counted + recorded) in favor of
        the next-older one."""
        bids = []
        for name in os.listdir(self.dir):
            parsed = self._parse_pstate(name)
            if parsed is not None and parsed[0] == partition \
                    and parsed[1] <= batch_id:
                bids.append(parsed[1])
        for bid in sorted(bids, reverse=True):
            path = self._pstate_path(partition, bid)
            try:
                with open(path, encoding="utf-8") as fh:
                    return json.load(fh)
            except FileNotFoundError:
                continue
            except (json.JSONDecodeError, UnicodeDecodeError):
                _note_corrupt(path, "partition-state-snapshot")
                continue
        return None

    def prune_state(self, keep_from: int) -> None:
        """Drop snapshots recovery can no longer need: whole-query
        snapshots older than `keep_from`, and per-partition snapshots
        superseded by a newer one still at or before `keep_from` (each
        partition's newest <= keep_from file must SURVIVE — with
        incremental writes it may be arbitrarily old)."""
        newest: dict[int, int] = {}     # partition -> newest bid <= keep
        pstates: list[tuple[int, int, str]] = []
        for name in os.listdir(self.dir):
            if not (name.startswith("state-") and name.endswith(".json")):
                continue
            parsed = self._parse_pstate(name)
            if parsed is not None:
                part, bid = parsed
                pstates.append((part, bid, name))
                if bid <= keep_from:
                    newest[part] = max(newest.get(part, -1), bid)
                continue
            try:
                bid = int(name[len("state-"):-len(".json")])
            except ValueError:
                continue
            if bid < keep_from:
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass
        for part, bid, name in pstates:
            if bid < newest.get(part, -1):
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass

    # -- compaction -------------------------------------------------------- #

    def compact(self) -> int:
        """Rewrite the log keeping only the last committed batch's records
        and anything after it (the commit-trimming analogue). The last
        committed plan must SURVIVE compaction: its `end` is the start
        offset of the next batch after a restart. Returns records dropped."""
        with self._lock:
            last = max(self._committed, default=-1)
            keep_plans = {b: p for b, p in self._plans.items() if b >= last}
            keep_commits = {b for b in self._committed if b >= last}
            dropped = (len(self._plans) - len(keep_plans)) + (
                len(self._committed) - len(keep_commits))
            self._plans, self._committed = keep_plans, keep_commits
            lines = []
            for b in sorted(self._plans):
                lines.append(json.dumps({
                    "t": "plan", "batch_id": b,
                    "start": self._plans[b]["start"],
                    "end": self._plans[b]["end"]}) + "\n")
            for b in sorted(self._committed):
                lines.append(json.dumps({"t": "commit", "batch_id": b}) + "\n")
            self._fh.close()
            # stop-the-world by design: writers must stay excluded
            # across the rewrite or their appends land on the replaced fd
            with allow_blocking("commit-log compact rewrite"):
                atomic_write(self.path, "".join(lines))
            self._fh = open(self.path, "a", encoding="utf-8")
            return dropped

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except OSError:
                pass
