"""StreamingQuery: the micro-batch driver loop.

Reference: Spark's `StreamingQuery` / `MicroBatchExecution` — the loop
that ties a Source, the query plan, and a Sink together: plan the next
batch's offset range into the WAL, materialize it, run the plan, hand it
to the sink keyed by batch id, then record the commit. The reference
rides this engine for everything ("deploy any streaming query as a web
service", docs/mmlspark-serving.md); here the engine itself is ~300
lines because the "query plan" is just a core.pipeline Transformer.

The perf story is compile-once/stream-forever: the SAME Transformer
instance scores every micro-batch, so any jit-compiled inner step (a
GBDT forest's bucketed scorer, a DeepModelTransformer's apply) compiles
on batch 0 and every later batch replays the cached executable —
streaming throughput equals batch-transform throughput once warm.

Exactly-once recovery (see checkpoint.py for the WAL format): on
restart, state snapshots restore stateful operators to the last
committed batch, the planned-but-uncommitted batch replays against its
RECORDED offset range, and idempotent sinks drop what a pre-crash
attempt already wrote. The kill-and-restart test in
tests/test_streaming.py asserts the end state is byte-identical to a
one-shot batch transform.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from ..core.dataplane import Lookahead
from ..core.schema import Table
from ..observability.sanitizer import make_rlock
from ..observability.metrics import get_registry
from ..observability.tracing import get_tracer
from ..resilience.policy import RetryPolicy, is_fatal_exception
from .checkpoint import CommitLog
from .sinks import MemorySink, Sink
from .sources import Source
from .state import StatefulOperator

__all__ = ["StreamingQuery"]


def _walk_stages(stage: Any) -> list:
    """Flatten a Transformer / PipelineModel tree into its leaf stages
    (Pipeline-ish stages expose a `stages` param holding children)."""
    out = []
    children = None
    if hasattr(stage, "get"):
        try:
            children = stage.get("stages")
        except (KeyError, AttributeError):
            children = None
    if children:
        for child in children:
            out.extend(_walk_stages(child))
    else:
        out.append(stage)
    return out


class StreamingQuery:
    """Drives source -> transform -> sink micro-batches.

    `transform` may be any core.pipeline Transformer/PipelineModel (its
    stateful operators are auto-discovered and checkpointed), a plain
    callable Table -> Table, or None (pass-through). With a
    `checkpoint_dir` the query is restartable with exactly-once output
    (given a replayable source and an idempotent sink); without one it is
    a best-effort in-memory stream.
    """

    def __init__(self, source: Source, transform: Any = None,
                 sink: "Sink | None" = None, *,
                 checkpoint_dir: "str | None" = None,
                 trigger_interval_s: float = 0.1,
                 compact_every: int = 100,
                 batch_retry_policy: "RetryPolicy | None" = None,
                 source_lookahead: int = 1,
                 name: str = "query",
                 metrics: Any = None,
                 tracer: Any = None,
                 fuse_pipeline: bool = True,
                 mesh: Any = None) -> None:
        self.source = source
        # PipelineModel transforms score through the whole-pipeline fusion
        # path (core/fusion.py): adjacent device-capable stages compile
        # into one XLA program per micro-batch. FusedPipelineModel still
        # exposes the `stages` param, so stateful-operator discovery below
        # walks the same leaves either way. With `mesh`, fused segments
        # compile sharded over it (byte-identical, so exactly-once replay
        # semantics are untouched).
        if fuse_pipeline and transform is not None:
            from ..core.fusion import FusedPipelineModel
            from ..core.pipeline import PipelineModel

            if (isinstance(transform, PipelineModel)
                    and not isinstance(transform, FusedPipelineModel)):
                from ..core.fusion import fuse

                transform = fuse(transform, mesh=mesh)
            elif mesh is not None and isinstance(transform,
                                                 FusedPipelineModel):
                transform.set_mesh(mesh)
        self.transform = transform
        self.sink = sink if sink is not None else MemorySink()
        self.name = name
        self.trigger_interval_s = trigger_interval_s
        self.compact_every = compact_every
        # finite per-failure-streak retry budget (was: retry forever on a
        # fixed interval); when it runs dry the query TERMINATES with
        # `exception` set so a resilience.QuerySupervisor can decide
        # whether to restart it
        self.batch_retry_policy = (
            batch_retry_policy if batch_retry_policy is not None
            else RetryPolicy(max_retries=3, base_ms=1e3 * trigger_interval_s,
                             max_ms=30_000.0, seed=0))
        # plain callables aren't walked — a closure owns its own state
        self._ops: list[StatefulOperator] = (
            [s for s in _walk_stages(transform)
             if isinstance(s, StatefulOperator)]
            if hasattr(transform, "transform") else [])
        # Async data plane: read ahead on the SOURCE only — batch N+1's
        # get_offset/get_batch overlap batch N's transform + sink write.
        # Planning and commit stay strictly ordered in process_next, so
        # exactly-once and kill-restart replay are untouched; a stale or
        # failed lookahead is discarded and the source re-read in line.
        # Single-slot (values > 1 behave as 1).
        self._lookahead = (Lookahead(name=f"source-{name}")
                           if source_lookahead > 0 else None)
        self._log = CommitLog(checkpoint_dir) if checkpoint_dir else None
        # blocking_ok: this is the one-batch-at-a-time pipeline mutex —
        # its holder performs the WAL plan/commit and sink write (all
        # I/O) by design; it still participates in lock-order checking
        self._lock = make_rlock("StreamingQuery._lock", blocking_ok=True)
        self._stop = threading.Event()
        self._closed = False
        self._failed = False
        self._thread: "threading.Thread | None" = None
        self._exception: "BaseException | None" = None
        self._last_end: "dict | None" = None
        self._next_id = 0
        self.batches_processed = 0
        self.rows_processed = 0
        self.last_progress: dict = {}
        # telemetry: every series labeled by query name; a restarted query
        # (new object, same name) keeps accumulating the same children
        self.tracer = tracer
        reg = metrics if metrics is not None else get_registry()
        self.metrics = reg
        lbl = {"query": name}
        self._m_batches = reg.counter(
            "mmlspark_tpu_streaming_batches_total",
            "micro-batches committed", labels=("query",)).labels(**lbl)
        self._m_rows = reg.counter(
            "mmlspark_tpu_streaming_rows_total",
            "rows through committed micro-batches",
            labels=("query",)).labels(**lbl)
        self._m_batch_seconds = reg.histogram(
            "mmlspark_tpu_streaming_batch_seconds",
            "micro-batch wall time, source read to sink write",
            labels=("query",)).labels(**lbl)
        self._m_wal_plan = reg.histogram(
            "mmlspark_tpu_streaming_wal_plan_seconds",
            "WAL plan-record write time", labels=("query",)).labels(**lbl)
        self._m_wal_commit = reg.histogram(
            "mmlspark_tpu_streaming_wal_commit_seconds",
            "WAL commit-record write time", labels=("query",)).labels(**lbl)
        self._m_lookahead = reg.gauge(
            "mmlspark_tpu_streaming_lookahead_hit_ratio",
            "fraction of source reads served by the lookahead",
            labels=("query",)).labels(**lbl)
        if self._log is not None:
            self._recover()

    # -- recovery --------------------------------------------------------- #

    def _recover(self) -> None:
        last = self._log.last_committed()
        if last < 0:
            return
        plan = self._log.planned(last)
        # a committed batch always has a plan (plan precedes commit), but a
        # compacted pre-upgrade log might not — start over in that case
        self._last_end = plan["end"] if plan else None
        self._next_id = last + 1
        self._recover_state(last)

    def _recover_state(self, last: int) -> None:
        """Restore stateful-operator state to the last committed batch
        (overridden by ParallelStreamingQuery for per-partition docs)."""
        if not self._ops:
            return
        doc = self._log.read_state(last)
        if doc:
            for op, op_doc in zip(self._ops, doc.get("ops", [])):
                op.load_state_doc(op_doc)

    # -- one micro-batch --------------------------------------------------- #

    def _apply(self, batch: Table) -> Table:
        if self.transform is None:
            return batch
        if hasattr(self.transform, "transform"):
            return self.transform.transform(batch)
        return self.transform(batch)

    # The four state/apply hooks factor everything a partition-parallel
    # subclass must change out of process_next, which keeps the WAL
    # ordering (plan -> snapshot -> apply -> state write -> sink ->
    # commit, rollback on any failure) in exactly one place.

    def _snapshot_state(self):
        """Pre-batch state capture, restored by `_restore_state` if the
        attempt fails."""
        return [op.state_doc() for op in self._ops]

    def _restore_state(self, saved) -> None:
        for op, doc in zip(self._ops, saved):
            op.load_state_doc(doc)

    def _apply_batch(self, bid: int, batch: Table) -> Table:
        return self._apply(batch)

    def _write_state(self, bid: int) -> None:
        """Persist post-fold state BEFORE the sink write, so a replayed
        batch restores its operators to the state that preceded the
        crashed attempt."""
        if self._log is not None and self._ops:
            self._log.write_state(
                bid, {"ops": [op.state_doc() for op in self._ops]})

    def _post_commit(self, bid: int) -> None:
        """Commit-time hook (after the WAL commit record)."""

    def _read_ahead(self, start: "dict | None"):
        """Background source read for the batch AFTER the current one:
        (end_offset, batch-or-None). Deterministic per the Source contract
        (get_batch(start, end) always yields the same rows), so a result
        claimed after a failed attempt's replay is still exact."""
        end = self.source.get_offset(start)
        if end is None or end == start or self.source.empty_range(start, end):
            return end, None
        return end, self.source.get_batch(start, end)

    def process_next(self) -> bool:
        """Run at most one micro-batch; False when no new data is
        available. Raises on batch failure (the background loop catches,
        records, and retries — state is rolled back either way, and the
        WAL plan makes the retry deterministic)."""
        with self._lock:
            bid = self._next_id
            ahead = None
            replay = self._log.planned(bid) if self._log is not None else None
            if replay is not None:
                start, end = replay["start"], replay["end"]
                if self.source.empty_range(start, end):
                    # an empty plan can only come from a crash between
                    # plan and commit of a batch whose data vanished
                    # (non-replayable source); commit it as a no-op
                    self._commit(bid, end, rows=0)
                    return True
            else:
                start = self._last_end
                hit = False
                if self._lookahead is not None:
                    hit, pre = self._lookahead.take(start)
                if hit and pre[1] is not None:
                    end, ahead = pre
                else:
                    # no pending read-ahead, or it saw no data when it ran
                    # — poll fresh so rows that arrived since aren't missed
                    end = self.source.get_offset(start)
                if end is None or end == start or \
                        self.source.empty_range(start, end):
                    return False
                if self._log is not None:
                    with self._m_wal_plan.time():
                        self._log.plan(bid, start, end)
            saved = self._snapshot_state()
            t0 = time.monotonic()
            tr = self.tracer if self.tracer is not None else get_tracer()
            with tr.start_span("streaming.batch", query=self.name,
                               batch_id=bid) as span:
                try:
                    batch = (ahead if ahead is not None
                             else self.source.get_batch(start, end))
                    # overlap the NEXT batch's source read with this batch's
                    # transform + sink write (keyed by its start offset; a
                    # replay or restart simply misses and reads in line)
                    if self._lookahead is not None:
                        nxt = end
                        self._lookahead.submit(
                            nxt, lambda: self._read_ahead(nxt))
                    out = self._apply_batch(bid, batch)
                    self._write_state(bid)
                    self.sink.add_batch(bid, out)
                except BaseException:
                    # a failed attempt must not leak half-folded state into
                    # the retry: restore the pre-batch snapshots
                    self._restore_state(saved)
                    raise
                span.set(rows=batch.num_rows)
                self._commit(bid, end, rows=batch.num_rows,
                             duration_s=time.monotonic() - t0)
            return True

    def _commit(self, bid: int, end: "dict | None", rows: int,
                duration_s: float = 0.0) -> None:
        if self._log is not None:
            with self._m_wal_commit.time():
                self._log.commit(bid)
            if self._ops:
                self._log.prune_state(keep_from=bid)
            if self.compact_every and (bid + 1) % self.compact_every == 0:
                self._log.compact()
        self._post_commit(bid)
        self.source.commit(end)
        self._last_end = end
        self._next_id = bid + 1
        self.batches_processed += 1
        self.rows_processed += rows
        self.last_progress = {
            "batch_id": bid, "num_rows": rows,
            "duration_s": duration_s, "end_offset": end,
        }
        self._m_batches.inc()
        if rows:
            self._m_rows.inc(rows)
        self._m_batch_seconds.observe(duration_s)
        if self._lookahead is not None:
            self.last_progress["lookahead_hits"] = self._lookahead.hits
            self.last_progress["lookahead_misses"] = self._lookahead.misses
            seen = self._lookahead.hits + self._lookahead.misses
            if seen:
                self._m_lookahead.set(self._lookahead.hits / seen)

    def process_all_available(self) -> int:
        """Drain everything currently available (Spark's availableNow
        trigger); returns batches processed."""
        n = 0
        while self.process_next():
            n += 1
        return n

    # -- lifecycle --------------------------------------------------------- #

    def start(self) -> "StreamingQuery":
        if self._closed:
            raise RuntimeError(
                f"query {self.name!r} was stopped; build a new query over "
                "the same checkpoint_dir to resume")
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(f"query {self.name!r} is already running")
        self._stop.clear()
        with self._lock:
            self._failed = False
        self._thread = threading.Thread(
            target=self._run, name=f"streaming-query-{self.name}",
            daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        sess = None
        while not self._stop.is_set():
            try:
                progressed = self.process_next()
            except Exception as e:  # noqa: BLE001 — classified below
                with self._lock:
                    self._exception = e
                if sess is None:
                    sess = self.batch_retry_policy.session()
                if is_fatal_exception(e) or not sess.should_retry():
                    # budget spent (or the error cannot heal): terminate
                    # with `exception` set — a QuerySupervisor above takes
                    # it from here; the WAL plan keeps a later replay exact
                    with self._lock:
                        self._failed = True
                    # last chance to get the black box out before the
                    # loop dies: record the fatal error and dump
                    try:
                        from ..observability.recorder import get_recorder

                        rec = get_recorder()
                        rec.record("streaming.fatal", query=self.name,
                                   batch_id=self._next_id,
                                   error=f"{type(e).__name__}: {e}")
                        rec.trigger_dump("exception", force=True,
                                         query=self.name)
                    except Exception:  # noqa: BLE001 — never mask the fail
                        pass
                    return
                # interruptible backoff: stop() must not wait it out
                sess.backoff(wait=self._stop.wait)
                continue
            sess = None
            if progressed:
                # a recovered query must not look failed forever
                with self._lock:
                    self._exception = None
            else:
                self._stop.wait(self.trigger_interval_s)

    @property
    def is_active(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def failed(self) -> bool:
        """True when the batch retry budget ran dry (or a fatal error hit)
        and the query terminated on its own."""
        return self._failed

    @property
    def exception(self) -> "BaseException | None":
        return self._exception

    def await_termination(self, timeout_s: "float | None" = None) -> bool:
        """Block until stop() (or forever); True if terminated."""
        if self._thread is None:
            return True
        self._thread.join(timeout_s)
        return not self._thread.is_alive()

    def stop(self) -> None:
        """Idempotent: signals the loop, joins it, and closes resources
        exactly once — safe on a never-started or already-stopped query."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._closed:
            return
        self._closed = True
        if self._lookahead is not None:
            # join any in-flight background read before closing the source
            self._lookahead.discard()
        if self._log is not None:
            self._log.close()
        self.source.close()
        self.sink.close()
