"""Streaming sources: the getOffset/getBatch/commit contract.

Reference: Spark's `Source` trait as implemented by the reference's
`HTTPSource`/`DistributedHTTPSource` (HTTPSource.scala:46-225,
DistributedHTTPSource.scala:308-343) and the built-in file/socket
sources. `getOffset` reports how far the stream extends right now,
`getBatch(start, end)` materializes the rows in an offset range, and
`commit(end)` lets the source trim anything at or before a durably
processed offset.

Replayability is the property exactly-once hangs on: a source is
REPLAYABLE when `get_batch(start, end)` returns identical rows for the
same range even after a process restart. `DirectorySource` (files are
the durable store) and `ServingSource` (the serving journal re-parks
unanswered requests) are replayable; `MemorySource` and `SocketSource`
are not across restarts (their buffers die with the process) and are
meant for tests and fire-and-forget pipelines.

Offsets are JSON-able dicts so the commit log can persist them verbatim;
`None` means "beginning of stream".
"""

from __future__ import annotations

import fnmatch
import os
import socket
import threading
from typing import Any

from ..core.schema import Table
from ..observability.sanitizer import make_lock
from ..core.table_io import read_csv, read_parquet

__all__ = ["Source", "DirectorySource", "MemorySource", "SocketSource",
           "ServingSource"]


class Source:
    """Base streaming source. Subclasses implement the offset triple."""

    def get_offset(self, start: "dict | None" = None) -> "dict | None":
        """End offset of the NEXT batch given the committed offset `start`
        (None = nothing available). Most sources ignore `start` and report
        the stream's current extent; rate-limited sources (DirectorySource
        with max_files_per_trigger) use it to bound the batch."""
        raise NotImplementedError

    def get_batch(self, start: "dict | None", end: dict) -> Table:
        """Rows in (start, end]. Must be deterministic for a fixed range —
        the commit log replays a crashed batch against its recorded range
        and the sink's idempotence only holds if the data matches."""
        raise NotImplementedError

    def commit(self, end: dict) -> None:
        """`end` is durably processed; the source may trim up to it."""

    def empty_range(self, start: "dict | None", end: dict) -> bool:
        """True when (start, end] contains no rows — lets the driver skip
        planning no-op batches for sources whose offsets move without new
        data (ServingSource's pending set shrinking on replies)."""
        return False

    def close(self) -> None:
        pass


class MemorySource(Source):
    """In-process source fed by `add_rows`; the MemoryStream analogue.

    Offsets count rows ever added: {"rows": n}. Not replayable across a
    process restart (tests and demos only).
    """

    def __init__(self) -> None:
        self._lock = make_lock("MemorySource._lock")
        self._table: "Table | None" = None
        self._base = 0          # rows trimmed by commit()

    def add_rows(self, table: Table) -> None:
        with self._lock:
            self._table = (table if self._table is None
                           else self._table.concat(table))

    def get_offset(self, start: "dict | None" = None) -> "dict | None":
        with self._lock:
            if self._table is None and self._base == 0:
                return None
            n = self._base + (self._table.num_rows if self._table else 0)
        return {"rows": n}

    def get_batch(self, start: "dict | None", end: dict) -> Table:
        lo = (start or {}).get("rows", 0)
        hi = end["rows"]
        with self._lock:
            if lo < self._base:
                raise ValueError(
                    f"offset {lo} was trimmed by commit (base {self._base}) "
                    "— MemorySource cannot replay committed rows")
            if self._table is None:
                return Table({})
            return self._table.slice(lo - self._base, hi - self._base)

    def commit(self, end: dict) -> None:
        with self._lock:
            if self._table is None:
                return
            keep_from = end["rows"] - self._base
            if keep_from > 0:
                self._table = self._table.slice(
                    keep_from, self._table.num_rows)
                self._base = end["rows"]

    def empty_range(self, start: "dict | None", end: dict) -> bool:
        return (start or {}).get("rows", 0) >= end["rows"]


class DirectorySource(Source):
    """File-tailing source: new files matching `pattern` under `path`
    become the next micro-batch (Spark's FileStreamSource).

    The offset is the sorted list of file names seen: {"files": [...]}.
    Deterministic replay holds because a planned batch names its exact
    file delta and files are immutable once they appear — writers MUST
    materialize atomically (write to a dot-prefixed temp name, then
    os.replace into place) or a half-written file becomes part of a
    batch. Format is inferred per file from the extension (.csv /
    .parquet) unless `format` pins one.
    """

    def __init__(self, path: str, pattern: str = "*", *,
                 format: "str | None" = None,
                 max_files_per_trigger: "int | None" = None,
                 **read_kwargs: Any) -> None:
        self.path = path
        self.pattern = pattern
        self.format = format
        self.max_files_per_trigger = max_files_per_trigger
        self.read_kwargs = read_kwargs

    def _list(self) -> list[str]:
        try:
            names = os.listdir(self.path)
        except FileNotFoundError:
            return []
        return sorted(
            n for n in names
            if not n.startswith(".") and fnmatch.fnmatch(n, self.pattern)
            and os.path.isfile(os.path.join(self.path, n)))

    def _read(self, name: str) -> Table:
        full = os.path.join(self.path, name)
        fmt = self.format or os.path.splitext(name)[1].lstrip(".").lower()
        if fmt == "csv":
            return read_csv(full, **self.read_kwargs)
        if fmt == "parquet":
            return read_parquet(full)
        raise ValueError(
            f"cannot infer a reader for {name!r} (format {fmt!r}); pass "
            "format='csv'|'parquet' to DirectorySource")

    def get_offset(self, start: "dict | None" = None) -> "dict | None":
        files = self._list()
        if not files:
            return None
        limit = self.max_files_per_trigger
        if limit is not None:
            # Spark's maxFilesPerTrigger: cap the batch at `limit` UNSEEN
            # files past the committed offset (rate limiting + the knob
            # tests use to force multi-batch streams over a static dir)
            done = set((start or {}).get("files", ()))
            new = [n for n in files if n not in done][:limit]
            files = sorted(done | set(new))
        return {"files": files}

    def get_batch(self, start: "dict | None", end: dict) -> Table:
        done = set((start or {}).get("files", ()))
        batch: "Table | None" = None
        for name in end["files"]:
            if name in done:
                continue
            t = self._read(name)
            batch = t if batch is None else batch.concat(t)
        return batch if batch is not None else Table({})

    def empty_range(self, start: "dict | None", end: dict) -> bool:
        done = set((start or {}).get("files", ()))
        return all(n in done for n in end["files"])


class SocketSource(Source):
    """Line-delimited text over TCP (Spark's socket source): connects as a
    CLIENT to host:port and buffers lines into a `value` column.

    Offsets count lines received: {"rows": n}. NOT replayable across a
    restart — the TCP stream is gone — so use it only for pipelines where
    at-most-once on crash is acceptable (exactly like the reference's
    socket source, which Spark documents as non-fault-tolerant).
    """

    def __init__(self, host: str, port: int,
                 encoding: str = "utf-8") -> None:
        self.host, self.port, self.encoding = host, port, encoding
        self._lock = make_lock("SocketSource._lock")
        self._lines: list[str] = []
        self._base = 0
        self._stop = threading.Event()
        self._sock = socket.create_connection((host, port))
        self._thread = threading.Thread(
            target=self._pump, name="socket-source", daemon=True)
        self._thread.start()

    def _pump(self) -> None:
        buf = b""
        try:
            while not self._stop.is_set():
                chunk = self._sock.recv(65536)
                if not chunk:
                    break
                buf += chunk
                *complete, buf = buf.split(b"\n")
                if complete:
                    decoded = [c.decode(self.encoding, "replace")
                               for c in complete]
                    with self._lock:
                        self._lines.extend(decoded)
        except OSError:
            pass   # connection torn down (close() or peer went away)

    def get_offset(self, start: "dict | None" = None) -> "dict | None":
        with self._lock:
            n = self._base + len(self._lines)
        return {"rows": n} if n else None

    def get_batch(self, start: "dict | None", end: dict) -> Table:
        lo = (start or {}).get("rows", 0)
        with self._lock:
            rows = self._lines[lo - self._base:end["rows"] - self._base]
        return Table({"value": list(rows)})

    def commit(self, end: dict) -> None:
        with self._lock:
            keep_from = end["rows"] - self._base
            if keep_from > 0:
                del self._lines[:keep_from]
                self._base = end["rows"]

    def empty_range(self, start: "dict | None", end: dict) -> bool:
        return (start or {}).get("rows", 0) >= end["rows"]

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        self._thread.join(timeout=2)


class ServingSource(Source):
    """A batch-mode ServingServer as a streaming source: pending HTTP
    requests become micro-batches of `id` + `request` rows, and a
    `ReplySink` downstream answers them — the reference's
    `readStream.server() ... writeStream.server()` loop
    (docs/mmlspark-serving.md) with a real engine in the middle.

    The offset is the sorted set of pending exchange ids: {"ids": [...]}.
    Requests stay parked in the server until replied, so a planned batch
    replays deterministically: after a crash, the serving journal re-parks
    every unanswered request at server construction and `get_batch` finds
    the planned ids still pending; ids already answered durably are
    dropped by the journal's duplicate-reply suppression on the sink side.
    """

    def __init__(self, server: Any, max_rows: "int | None" = None) -> None:
        if getattr(server, "mode", None) != "batch":
            raise ValueError(
                "ServingSource requires a ServingServer in mode='batch' "
                "(continuous mode replies inline and has no pending set)")
        self.server = server
        self.max_rows = max_rows

    @staticmethod
    def _sort_key(ex_id: str):
        # server ids are integer strings; numeric order = arrival order
        s = str(ex_id)
        return (0, int(s)) if s.isdigit() else (1, s)

    def get_offset(self, start: "dict | None" = None) -> "dict | None":
        tbl = self.server.get_batch(self.max_rows)
        ids = sorted((str(i) for i in tbl["id"]), key=self._sort_key)
        return {"ids": ids} if ids else None

    def get_batch(self, start: "dict | None", end: dict) -> Table:
        wanted = [str(i) for i in end["ids"]]
        tbl = self.server.get_batch(None)
        by_id = {str(i): req for i, req in zip(tbl["id"], tbl["request"])}
        missing = [i for i in wanted if i not in by_id]
        if missing:
            # only a durable reply removes a pending request, so a planned
            # id can be absent ONLY when a pre-crash attempt already
            # answered it — exactly-once says skip, not fail
            wanted = [i for i in wanted if i in by_id]
        return Table({"id": wanted, "request": [by_id[i] for i in wanted]})

    def empty_range(self, start: "dict | None", end: dict) -> bool:
        return not end["ids"]

    def commit(self, end: dict) -> None:
        journal = getattr(self.server, "journal", None)
        if journal is not None:
            journal.compact()
