"""Partition-parallel streaming: P shuffle partitions run one query's
stateful chain concurrently — in threads, or across a fleet of worker
processes.

Reference: Spark's stateful streaming execution — `groupBy(key)` hashes
rows across N tasks, each task owns the state for its keys, and the
driver's checkpoint ties their progress into one exactly-once commit.
Here `ParallelStreamingQuery` subclasses the micro-batch driver loop and
replaces only its state/apply hooks: the WAL plan/commit protocol,
replay rules, and sink idempotence are untouched, which is why the
kill-restart byte-identity gate keeps holding at P > 1.

Per batch the driver: runs pre-shuffle stages, computes GLOBAL time
hints (max event time per time column — every partition's watermark
advances on the whole batch, not its slice), splits rows with the
process-stable keyed hash (shuffle.py), fans slices out to the
partition workers (ALL partitions when the chain is stateful — a
complete-mode aggregate emits every group each batch and watermark
finalization fires on empty slices too), barriers, and merges by a
canonical stable sort (the last stateful operator's `merge_sort_cols`;
a hidden row tag restores source order for stateless chains). Because
keys are disjoint across partitions and per-key row order is preserved,
the merged batch is byte-identical to the P=1 run's.

Checkpoints are per-partition and INCREMENTAL: only partitions whose
state doc changed write a `state-p####-#########.json` snapshot
(deterministic serialization — state docs are key-sorted), and recovery
reads each partition's newest snapshot at or before the last commit.

Fleet mode reuses the serving production machinery end to end: workers
are `ServingFleet` processes (PR 8 lifecycle — respawn, rolling_swap,
flight-recorder dumps) speaking a small JSON protocol, the driver
routes `query/p<i>` by consistent hash through a `TargetPool`, and
membership flows through the fleet watch protocol. A worker that dies
mid-batch is respawned and answers `need_state`; the driver re-pushes
the committed state and re-sends the slice — partition-level retry,
byte-identity preserved.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Any

import numpy as np

from ..core.pipeline import pipeline_model
from ..core.schema import Table, find_unused_column_name
from ..observability.tracing import get_tracer
from .query import StreamingQuery, _walk_stages
from .shuffle import KeyedShuffle, split_by_partition
from .state import StatefulOperator

__all__ = ["ParallelStreamingQuery", "ThreadPartitionWorker",
           "PartitionWorkerFactory", "split_pipeline_at_shuffle"]


# --------------------------------------------------------------------- #
# shared helpers (driver threads AND fleet worker processes)            #
# --------------------------------------------------------------------- #


def _encode_rows(table: Table) -> dict:
    """JSON-safe columnar encoding. float64 survives the round trip
    exactly (json emits shortest-roundtrip reprs), so worker replies
    merge byte-identical to in-process transforms."""
    cols = {}
    for name in table.columns:
        col = table[name]
        if isinstance(col, np.ndarray):
            cols[name] = {"dtype": str(col.dtype), "values": col.tolist()}
        else:
            cols[name] = {"dtype": "list", "values": list(col)}
    return {"columns": cols}


def _decode_rows(doc: dict) -> Table:
    cols: dict[str, Any] = {}
    for name, spec in (doc or {}).get("columns", {}).items():
        if spec["dtype"] == "list":
            cols[name] = list(spec["values"])
        else:
            cols[name] = np.array(spec["values"],
                                  dtype=np.dtype(spec["dtype"]))
    return Table(cols)


def _chain_ops(chain: Any) -> "list[StatefulOperator]":
    if chain is None:
        return []
    return [s for s in _walk_stages(chain) if isinstance(s, StatefulOperator)]


def _set_time_hints(ops: "list[StatefulOperator]", hints: dict) -> None:
    if not hints:
        return
    for op in ops:
        try:
            tc = op.get("time_col")
        except (KeyError, AttributeError):
            continue
        h = hints.get(tc)
        if h is not None:
            op.set_time_hint(float(h))


def _load_ops_doc(ops: "list[StatefulOperator]", doc: "dict | None") -> None:
    docs = (doc or {}).get("ops") or []
    for i, op in enumerate(ops):
        if i < len(docs):
            op.load_state_doc(docs[i] or {})
        else:
            op.reset_state()


def _ops_watermark(ops: "list[StatefulOperator]") -> "float | None":
    wms = [op.watermark() for op in ops if hasattr(op, "watermark")]
    wms = [w for w in wms if w is not None]
    return min(wms) if wms else None


def _ops_spilled(ops: "list[StatefulOperator]") -> int:
    return int(sum(getattr(op, "spilled_bytes", 0) or 0 for op in ops))


def _clone_chain(chain: Any) -> Any:
    """Independent per-partition copy of the chain, state included.
    Registered stages round-trip through the no-pickle blob codec;
    anything else (ad-hoc local Transformer subclasses) deep-copies."""
    if chain is None:
        return None
    from ..core.serialize import stage_from_blob, stage_to_blob

    try:
        return stage_from_blob(stage_to_blob(chain))
    except Exception:  # noqa: BLE001 — unregistered stage: copy in-process
        import copy

        return copy.deepcopy(chain)


def _stable_sort(table: Table, cols: "list[str]") -> Table:
    """Stable sort by `cols` (ties keep input order) — the canonical
    merge order that reconstructs the P=1 output from partition
    outputs."""
    n = table.num_rows
    if n <= 1:
        return table
    keycols = [table[c] for c in cols]
    order = sorted(range(n),
                   key=lambda i: tuple(kc[i] for kc in keycols))
    return table.gather(np.array(order, dtype=np.int64))


def split_pipeline_at_shuffle(transform: Any):
    """(pre_stages, shuffle_stage_or_None, chain_stages) — the stage
    lists on either side of the pipeline's KeyedShuffle marker. With no
    marker every stage is partition-local."""
    if transform is None:
        return [], None, []
    if not hasattr(transform, "transform"):
        raise TypeError(
            "ParallelStreamingQuery needs a Transformer/PipelineModel "
            "transform (plain callables cannot be cloned per partition)")
    stages = _walk_stages(transform)
    shuffles = [s for s in stages if isinstance(s, KeyedShuffle)]
    if len(shuffles) > 1:
        raise ValueError("a pipeline may hold at most one KeyedShuffle")
    if not shuffles:
        return [], None, stages
    i = stages.index(shuffles[0])
    return stages[:i], shuffles[0], stages[i + 1:]


# --------------------------------------------------------------------- #
# thread workers                                                        #
# --------------------------------------------------------------------- #


class _Task:
    __slots__ = ("bid", "table", "hints", "event", "out", "error",
                 "enq_t", "lag_s")

    def __init__(self, bid: int, table: Table, hints: dict):
        self.bid = bid
        self.table = table
        self.hints = hints
        self.event = threading.Event()
        self.out: "Table | None" = None
        self.error: "BaseException | None" = None
        self.enq_t = time.perf_counter()
        self.lag_s = 0.0


class ThreadPartitionWorker:
    """One partition's chain on its own thread behind an input queue.
    The GIL bounds pure-python speedup, but any slice work that releases
    it — numpy kernels, native scorers, outbound IO — overlaps across
    partitions, and the barrier semantics match fleet mode exactly."""

    def __init__(self, partition: int, chain: Any,
                 ops: "list[StatefulOperator]", query_name: str = "query",
                 tracer: Any = None, depth_gauge: Any = None):
        self.partition = partition
        self.chain = chain
        self.ops = ops
        self.query_name = query_name
        self.tracer = tracer
        self._depth = depth_gauge
        self._q: "queue.Queue[_Task | None]" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run,
            name=f"partition-{query_name}-{partition}", daemon=True)
        self._thread.start()

    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    def submit(self, bid: int, table: Table, hints: dict) -> _Task:
        task = _Task(bid, table, hints)
        self._q.put(task)
        if self._depth is not None:
            self._depth.set(self._q.qsize())
        return task

    def _run(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                return
            tr = self.tracer if self.tracer is not None else get_tracer()
            try:
                with tr.start_span("streaming.partition",
                                   query=self.query_name,
                                   batch_id=task.bid,
                                   partition=self.partition) as span:
                    _set_time_hints(self.ops, task.hints)
                    task.out = (self.chain.transform(task.table)
                                if self.chain is not None else task.table)
                    span.set(rows=task.table.num_rows)
            except BaseException as e:  # noqa: BLE001 — driver re-raises
                task.error = e
            finally:
                task.lag_s = time.perf_counter() - task.enq_t
                if self._depth is not None:
                    self._depth.set(self._q.qsize())
                task.event.set()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._q.put(None)
        self._thread.join(timeout_s)


# --------------------------------------------------------------------- #
# fleet workers                                                         #
# --------------------------------------------------------------------- #


class PartitionWorkerFactory:
    """Picklable `ServingFleet` handler factory speaking the partition-
    worker protocol. The chain travels as a registry blob (base64 zip,
    no pickle), so a spawned process rebuilds it from scratch.

    JSON ops over POST /:

      {"op": "apply", "partition", "batch_id", "rows", "hints"}
          -> {"rows", "state", "watermark", "spilled_bytes", "seconds"}
          -> {"need_state": true}  when the worker cannot prove its held
             state is exactly batch_id-1 (fresh spawn, remapped
             partition, or a desync after failover) — the driver pushes
             the committed state and re-sends
      {"op": "load_state", "partition", "batch_id", "state"} -> {"ok"}
      {"op": "status"} -> held partitions, last batch ids, watermarks

    A re-sent `apply` for the batch a worker just folded returns the
    cached reply instead of folding twice — per-batch idempotence, same
    rule as the sinks.
    """

    def __init__(self, blob: "str | None", query_name: str = "query"):
        self.blob = blob
        self.query_name = query_name

    def __call__(self):
        from ..core.serialize import stage_from_blob
        from ..io_http.schema import HTTPResponseData
        from ..io_http.wire import (WIRE_CONTENT_TYPE, content_type_of,
                                    decode_message, encode_message,
                                    is_wire_content_type)

        blob = self.blob
        query_name = self.query_name
        chains: dict[int, Any] = {}
        chain_ops: dict[int, list] = {}
        last: dict[int, int] = {}            # partition -> folded through
        cache: dict[int, tuple] = {}         # p -> (bid, meta_doc, out)

        def _fresh(p: int) -> None:
            c = stage_from_blob(blob) if blob else None
            chains[p] = c
            chain_ops[p] = _chain_ops(c)

        def _apply(body: dict, in_table: "Table | None" = None):
            """-> (doc, out_table): out_table None for control replies
            (need_state); otherwise the handler frames the rows in the
            REQUEST's protocol — JSON columnar, or the shared binary
            wire when the driver opted in (`binary_wire=True`)."""
            p = int(body["partition"])
            bid = int(body["batch_id"])
            hit = cache.get(p)
            if hit is not None and hit[0] == bid:
                return hit[1], hit[2]
            if p not in chains:
                if bid != 0:
                    return {"need_state": True, "have": last.get(p)}, None
                _fresh(p)
                last[p] = -1
            if last.get(p, -2) != bid - 1:
                return {"need_state": True, "have": last.get(p)}, None
            t0 = time.perf_counter()
            table = (in_table if in_table is not None
                     else _decode_rows(body["rows"]))
            ops = chain_ops[p]
            _set_time_hints(ops, body.get("hints") or {})
            out = (chains[p].transform(table)
                   if chains[p] is not None else table)
            reply = {
                "state": {"ops": [op.state_doc() for op in ops]},
                "watermark": _ops_watermark(ops),
                "spilled_bytes": _ops_spilled(ops),
                "seconds": time.perf_counter() - t0,
            }
            last[p] = bid
            cache[p] = (bid, reply, out)
            return reply, out

        def _load_state(body: dict) -> dict:
            p = int(body["partition"])
            _fresh(p)
            _load_ops_doc(chain_ops[p], body.get("state"))
            last[p] = int(body["batch_id"])
            cache.pop(p, None)
            return {"ok": True}

        def _status() -> dict:
            return {
                "query": query_name,
                "partitions": sorted(chains),
                "last": {str(p): b for p, b in sorted(last.items())},
                "watermarks": {str(p): _ops_watermark(chain_ops[p])
                               for p in sorted(chains)},
                "spilled_bytes": {str(p): _ops_spilled(chain_ops[p])
                                  for p in sorted(chains)},
            }

        def handler(table: Table) -> Table:
            replies = []
            for req in table["request"]:
                try:
                    binary = is_wire_content_type(
                        content_type_of(req.headers))
                    in_table = None
                    if binary:
                        body, cols = decode_message(req.entity)
                        # frombuffer views are read-only; ops may fold
                        # in place, so pay one memcpy per array column
                        in_table = Table({
                            k: (np.array(v) if isinstance(v, np.ndarray)
                                else v)
                            for k, v in cols.items()})
                    else:
                        body = req.json() or {}
                    op = body.get("op")
                    if op == "apply":
                        doc, out = _apply(body, in_table)
                        if out is not None:
                            if binary:
                                replies.append(HTTPResponseData(
                                    200, "OK",
                                    {"Content-Type": WIRE_CONTENT_TYPE},
                                    encode_message(
                                        doc,
                                        {c: out[c] for c in out.columns},
                                        n_rows=out.num_rows)))
                                continue
                            doc = {"rows": _encode_rows(out), **doc}
                    elif op == "load_state":
                        doc = _load_state(body)
                    elif op == "status":
                        doc = _status()
                    else:
                        raise ValueError(f"unknown op {op!r}")
                    code, reason = 200, "OK"
                except Exception as e:  # noqa: BLE001 — reply, don't die
                    doc = {"error": f"{type(e).__name__}: {e}"}
                    code, reason = 500, "handler error"
                replies.append(HTTPResponseData(
                    code, reason, entity=json.dumps(doc).encode()))
            return Table({"reply": replies})

        return handler


# --------------------------------------------------------------------- #
# the parallel query                                                    #
# --------------------------------------------------------------------- #


class ParallelStreamingQuery(StreamingQuery):
    """StreamingQuery whose stateful chain runs P-way partition-parallel.

    The pipeline splits at its `KeyedShuffle` stage (stages before it
    run on the driver; stages after run per partition) — or, with no
    marker stage, `key_col`/`num_partitions` place the whole transform
    partition-local. Stateful operators must key their state by the
    shuffle key (`partition_key_col`); output, checkpoints, and
    kill-restart replay are byte-identical to the P=1 run.

    `workers="thread"` runs partitions on driver threads;
    `workers="fleet"` spawns `ServingFleet` worker processes (or attaches
    to a caller-supplied `fleet`) and routes slices by consistent hash.
    """

    def __init__(self, source, transform: Any = None,
                 sink=None, *,
                 key_col: "str | None" = None,
                 num_partitions: "int | None" = None,
                 workers: str = "thread",
                 num_workers: "int | None" = None,
                 fleet: Any = None,
                 fleet_kw: "dict | None" = None,
                 worker_request_timeout_s: float = 60.0,
                 binary_wire: bool = False,
                 timeline_dir: "str | None" = None,
                 **kw: Any) -> None:
        if workers not in ("thread", "fleet"):
            raise ValueError("workers must be 'thread' or 'fleet'")
        pre, shuffle, chain_stages = split_pipeline_at_shuffle(transform)
        if shuffle is not None:
            key_col = key_col or shuffle.get("key_col")
            num_partitions = num_partitions or shuffle.get("num_partitions")
        if not key_col:
            raise ValueError(
                "key_col is required (directly or via a KeyedShuffle stage)")
        self.model = transform
        self.key_col = key_col
        self.num_partitions = int(num_partitions or 2)
        self._worker_mode = workers
        self._num_workers = int(num_workers or self.num_partitions)
        self._worker_request_timeout_s = worker_request_timeout_s
        # opt-in: ship fleet apply slices over the length-prefixed binary
        # wire (io_http/wire.py) instead of JSON columnar — same rows,
        # same replies, no float round-tripping through decimal strings
        self.binary_wire = bool(binary_wire)
        self._pre = pipeline_model(*pre) if pre else None
        if any(isinstance(s, StatefulOperator) for s in pre):
            raise ValueError(
                "stateful operators must come AFTER the KeyedShuffle — "
                "driver-side state cannot be partitioned")
        self._chain = (pipeline_model(*chain_stages)
                       if chain_stages else None)
        self._template_ops = _chain_ops(self._chain)
        self._stateful = bool(self._template_ops)
        for op in self._template_ops:
            kc = op.partition_key_col()
            if kc != key_col:
                raise ValueError(
                    f"{type(op).__name__} keys its state by {kc!r} but "
                    f"the shuffle routes by {key_col!r}; they must match "
                    "for state to stay partition-local")
        self._sort_cols = (self._template_ops[-1].merge_sort_cols()
                           if self._stateful else None)
        if self._stateful and not self._sort_cols:
            raise ValueError(
                f"{type(self._template_ops[-1]).__name__} declares no "
                "merge_sort_cols — its output cannot be merged "
                "deterministically across partitions")
        tcols = set()
        for op in self._template_ops:
            if type(op).set_time_hint is StatefulOperator.set_time_hint:
                continue                      # base no-op: not time-aware
            try:
                tcols.add(op.get("time_col"))
            except (KeyError, AttributeError):
                pass
        self._time_cols = sorted(c for c in tcols if c)
        self._fresh_doc = {"ops": [op.state_doc()
                                   for op in self._template_ops]}
        P = self.num_partitions
        self._committed_docs: list = [
            json.loads(json.dumps(self._fresh_doc)) for _ in range(P)]
        self._committed_ser: list = [
            json.dumps(self._fresh_doc, sort_keys=True)] * P
        self._pending: dict[int, dict] = {}
        self._pending_commit: dict[int, tuple] = {}
        self._last_state_bid: dict[int, int] = {}
        self._pinfo: dict[int, dict] = {p: {} for p in range(P)}
        self._states_written = 0
        self.shuffle_seconds = 0.0           # cumulative split + merge
        self.partition_seconds = 0.0         # cumulative barrier wall
        self._started_workers = False
        self._workers_stopped = False
        self._workers_list: "list[ThreadPartitionWorker] | None" = None
        self._chains: "list | None" = None
        self._chain_ops_list: "list | None" = None
        self._fleet = fleet
        self._own_fleet = fleet is None
        self._fleet_kw = dict(fleet_kw or {})
        self._pool = None
        self._send_pool = None
        self._blob = None
        if workers == "thread":
            self._chains = [_clone_chain(self._chain) for _ in range(P)]
            self._chain_ops_list = [_chain_ops(c) for c in self._chains]
        elif self._chain is not None:
            from ..core.serialize import stage_to_blob

            self._blob = stage_to_blob(self._chain)
        super().__init__(source, None, sink, fuse_pipeline=False, **kw)
        reg = self.metrics

        def _children(name: str, doc: str):
            fam = reg.gauge(name, doc, labels=("query", "partition"))
            return [fam.labels(query=self.name, partition=str(p))
                    for p in range(P)]

        self._g_depth = _children(
            "mmlspark_tpu_streaming_partition_queue_depth",
            "tasks waiting per partition worker")
        self._g_lag = _children(
            "mmlspark_tpu_streaming_partition_lag_seconds",
            "submit-to-completion wall time of a partition's last slice")
        self._g_wm = _children(
            "mmlspark_tpu_streaming_partition_watermark_seconds",
            "per-partition event-time watermark")
        self._g_spill = _children(
            "mmlspark_tpu_streaming_state_spill_bytes",
            "state-backend bytes spilled to parquet, per partition")
        # opt-in per-partition telemetry history: one timeline sample per
        # committed batch (event-driven, no background thread — the
        # commit IS the cadence), recording lag/depth/watermark per
        # partition. This is the observed-history half of the ROADMAP's
        # dynamic-rebalancing item: the rebalancer needs to know how
        # skewed each partition HAS BEEN, not just how skewed it is now.
        self._timeline = None
        if timeline_dir is not None:
            from ..observability.timeline import TimelineRecorder

            self._timeline = TimelineRecorder(timeline_dir, reg)

    # -- recovery ---------------------------------------------------------- #

    def _recover_state(self, last: int) -> None:
        for p in range(self.num_partitions):
            doc = self._log.read_partition_state(p, last)
            if doc is None:
                doc = json.loads(json.dumps(self._fresh_doc))
            self._committed_docs[p] = doc
            self._committed_ser[p] = json.dumps(doc, sort_keys=True)
            if self._chains is not None:
                _load_ops_doc(self._chain_ops_list[p], doc)
        # fleet workers pick the state up lazily: their first `apply`
        # answers need_state and the driver pushes the committed doc

    # -- workers ----------------------------------------------------------- #

    def _ensure_workers(self) -> None:
        if self._started_workers:
            return
        self._started_workers = True
        if self._worker_mode == "thread":
            self._workers_list = [
                ThreadPartitionWorker(
                    p, self._chains[p], self._chain_ops_list[p],
                    query_name=self.name, tracer=self.tracer,
                    depth_gauge=self._g_depth[p])
                for p in range(self.num_partitions)]
            return
        from concurrent.futures import ThreadPoolExecutor

        from ..io_http.clients import TargetPool

        self._pool = TargetPool()
        if self._fleet is None:
            from ..io_http.serving import ServingFleet

            fr_dir = (os.path.join(self._log.dir, "flight")
                      if self._log is not None else None)
            kw = dict(self._fleet_kw)
            kw.setdefault("flight_recorder_dir", fr_dir)
            self._fleet = ServingFleet(
                PartitionWorkerFactory(self._blob, self.name),
                n_hosts=self._num_workers, **kw)
        self._fleet.watch(self._on_membership)
        if self._own_fleet:
            self._fleet.start()
        for url in list(self._fleet.urls):
            self._pool.admit(url)
        self._send_pool = ThreadPoolExecutor(
            max_workers=min(32, max(2, self.num_partitions)),
            thread_name_prefix=f"shuffle-{self.name}")

    def _on_membership(self, event: str, url: str) -> None:
        if self._pool is None:
            return
        if event == "added":
            self._pool.admit(url)
        elif event == "removed":
            self._pool.eject(url, "fleet-removed")

    def _heal(self) -> None:
        """Respawn any fleet worker that died uncleanly; membership
        callbacks re-admit the replacement into the routing pool."""
        if self._fleet is None:
            return
        try:
            dead = self._fleet.dead_slots()
        except Exception:  # noqa: BLE001 — fleet mid-stop
            return
        for slot in dead:
            try:
                self._fleet.respawn(slot)
            except Exception:  # noqa: BLE001 — retried next attempt
                pass

    def _send(self, body: dict, p: int):
        from ..io_http.schema import HTTPRequestData

        return self._pool.send(
            HTTPRequestData.from_json("/", body),
            timeout=self._worker_request_timeout_s,
            strategy="hash", key=f"{self.name}/p{p}")

    def _push_state(self, p: int, upto_bid: int) -> None:
        resp = self._send({"op": "load_state", "partition": p,
                           "batch_id": upto_bid,
                           "state": self._committed_docs[p]}, p)
        if resp.status_code != 200:
            raise RuntimeError(
                f"partition {p}: state push failed "
                f"({resp.status_code} {resp.reason})")

    def _fleet_apply_one(self, p: int, bid: int, part: Table,
                         hints: dict) -> dict:
        if self.binary_wire:
            from ..io_http.schema import HTTPRequestData
            from ..io_http.wire import WIRE_CONTENT_TYPE, encode_message

            meta = {"op": "apply", "partition": p, "batch_id": bid,
                    "hints": hints}
            req = HTTPRequestData(
                "POST", "/", {"Content-Type": WIRE_CONTENT_TYPE},
                encode_message(meta, {c: part[c] for c in part.columns},
                               n_rows=part.num_rows))
            send = lambda: self._pool.send(  # noqa: E731
                req, timeout=self._worker_request_timeout_s,
                strategy="hash", key=f"{self.name}/p{p}")
        else:
            body = {"op": "apply", "partition": p, "batch_id": bid,
                    "rows": _encode_rows(part), "hints": hints}
            send = lambda: self._send(body, p)  # noqa: E731
        last_err: "Exception | None" = None
        for attempt in range(8):
            resp = send()
            if resp.status_code in (0, 503):
                # connection-level death or no live worker: heal the
                # fleet and retry — the respawned worker answers
                # need_state and the committed state re-flows
                last_err = RuntimeError(
                    f"partition {p}: no worker reachable "
                    f"({resp.status_code} {resp.reason})")
                self._heal()
                time.sleep(min(0.1 * (attempt + 1), 1.0))
                continue
            doc = self._decode_apply_reply(resp)
            if resp.status_code != 200:
                raise RuntimeError(
                    f"partition {p} worker error: "
                    f"{doc.get('error') or resp.reason}")
            if doc.get("need_state"):
                self._push_state(p, bid - 1)
                continue
            return doc
        raise last_err or RuntimeError(
            f"partition {p}: apply did not converge")

    @staticmethod
    def _decode_apply_reply(resp) -> dict:
        """Worker apply replies arrive framed (binary wire, rows as raw
        column blocks) or as JSON columnar; either way normalize to the
        reply doc with the decoded Table stashed under ``_table``."""
        from ..io_http.wire import (content_type_of, decode_message,
                                    is_wire_content_type)

        if is_wire_content_type(content_type_of(resp.headers)):
            meta, cols = decode_message(resp.entity)
            doc = dict(meta)
            doc.pop("json_columns", None)
            doc["_table"] = Table(dict(cols))
            return doc
        return resp.json() or {}

    # -- hooks over the base micro-batch loop ------------------------------ #

    def _compute_hints(self, batch: Table) -> dict:
        hints = {}
        if batch.num_rows:
            for c in self._time_cols:
                if c in batch.columns:
                    hints[c] = float(np.max(
                        np.asarray(batch[c], dtype=np.float64)))
        return hints

    def _run_partitions(self, bid: int, parts: "list[Table]",
                        hints: dict) -> "list[Table | None]":
        P = self.num_partitions
        outs: "list[Table | None]" = [None] * P
        # stateful chains hear about EVERY batch (complete-mode emission,
        # watermark finalization on empty slices); stateless chains skip
        # empty slices, keeping partition 0 as the schema carrier
        wanted = [p for p in range(P)
                  if self._stateful or parts[p].num_rows or p == 0]
        if self._worker_mode == "thread":
            tasks = {p: self._workers_list[p].submit(bid, parts[p], hints)
                     for p in wanted}
            err: "BaseException | None" = None
            for task in tasks.values():        # full barrier BEFORE any
                task.event.wait()              # raise: rollback needs
            for p, task in sorted(tasks.items()):   # idle workers
                if task.error is not None:
                    err = err or task.error
                    continue
                outs[p] = task.out
                ops = self._chain_ops_list[p]
                if self._stateful:
                    self._pending[p] = {
                        "ops": [op.state_doc() for op in ops]}
                self._pinfo[p] = {
                    "rows_in": parts[p].num_rows,
                    "rows_out": task.out.num_rows,
                    "lag_s": task.lag_s,
                    "queue_depth": self._workers_list[p].queue_depth,
                    "watermark": _ops_watermark(ops),
                    "spilled_bytes": _ops_spilled(ops),
                }
            if err is not None:
                raise err
            return outs
        futs = {p: self._send_pool.submit(
            self._fleet_apply_one, p, bid, parts[p], hints)
            for p in wanted}
        err = None
        docs: dict[int, dict] = {}
        for p, f in sorted(futs.items()):
            try:
                docs[p] = f.result()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err = err or e
        if err is not None:
            raise err
        for p, doc in sorted(docs.items()):
            outs[p] = (doc.pop("_table") if "_table" in doc
                       else _decode_rows(doc["rows"]))
            if self._stateful:
                self._pending[p] = doc["state"]
            self._pinfo[p] = {
                "rows_in": parts[p].num_rows,
                "rows_out": outs[p].num_rows,
                "lag_s": doc.get("seconds"),
                "queue_depth": 0,
                "watermark": doc.get("watermark"),
                "spilled_bytes": doc.get("spilled_bytes", 0),
            }
        return outs

    def _apply_batch(self, bid: int, batch: Table) -> Table:
        self._ensure_workers()
        t0 = time.perf_counter()
        if self._pre is not None:
            batch = self._pre.transform(batch)
        hints = self._compute_hints(batch)
        tag = None
        if not self._stateful:
            tag = find_unused_column_name("_shuffle_row", batch)
            batch = batch.with_column(
                tag, np.arange(batch.num_rows, dtype=np.int64))
        parts = split_by_partition(batch, self.key_col,
                                   self.num_partitions)
        t1 = time.perf_counter()
        outs = self._run_partitions(bid, parts, hints)
        t2 = time.perf_counter()
        present = [o for o in outs if o is not None]
        merged = present[0]
        for o in present[1:]:
            merged = merged.concat(o)
        if self._stateful:
            missing = [c for c in self._sort_cols
                       if c not in merged.columns]
            if missing:
                raise ValueError(
                    f"merge sort columns {missing} not in partition "
                    f"output {merged.columns} — the chain's final stage "
                    "must keep its stateful operator's output columns")
            merged = _stable_sort(merged, self._sort_cols)
        else:
            merged = _stable_sort(merged, [tag])
            merged = merged.select(
                *[c for c in merged.columns if c != tag])
        t3 = time.perf_counter()
        self.shuffle_seconds += (t1 - t0) + (t3 - t2)
        self.partition_seconds += t2 - t1
        return merged

    def _snapshot_state(self):
        return list(self._committed_docs)

    def _restore_state(self, saved) -> None:
        self._pending.clear()
        self._pending_commit.clear()
        last = self._next_id - 1
        for p in range(self.num_partitions):
            doc = saved[p]
            if self._chains is not None:
                _load_ops_doc(self._chain_ops_list[p], doc)
            elif self._started_workers and self._stateful:
                try:
                    self._push_state(p, last)
                except Exception:  # noqa: BLE001 — worker answers
                    pass           # need_state on the retry instead

    def _write_state(self, bid: int) -> None:
        self._pending_commit = {}
        written = 0
        for p, doc in sorted(self._pending.items()):
            ser = json.dumps(doc, sort_keys=True)
            if ser != self._committed_ser[p]:
                if self._log is not None:
                    self._log.write_partition_state(p, bid, doc)
                self._last_state_bid[p] = bid
                written += 1
            self._pending_commit[p] = (doc, ser)
        self._pending.clear()
        self._states_written = written

    def _post_commit(self, bid: int) -> None:
        for p, (doc, ser) in self._pending_commit.items():
            self._committed_docs[p] = doc
            self._committed_ser[p] = ser
        self._pending_commit = {}
        if self._log is not None:
            self._log.prune_state(keep_from=bid)
            self._write_status(bid)
        for p in range(self.num_partitions):
            info = self._pinfo.get(p) or {}
            if info.get("lag_s") is not None:
                self._g_lag[p].set(float(info["lag_s"]))
            if info.get("watermark") is not None:
                self._g_wm[p].set(float(info["watermark"]))
            self._g_spill[p].set(float(info.get("spilled_bytes") or 0))
            self._g_depth[p].set(float(info.get("queue_depth") or 0))
        if self._timeline is not None:
            try:
                self._timeline.sample()
            except Exception:  # noqa: BLE001 — history must not fail commits
                pass

    def _commit(self, bid: int, end, rows: int,
                duration_s: float = 0.0) -> None:
        super()._commit(bid, end, rows, duration_s)
        self.last_progress.update({
            "num_partitions": self.num_partitions,
            "workers": self._worker_mode,
            "partition_states_written": self._states_written,
            "shuffle_seconds_total": self.shuffle_seconds,
            "partition_seconds_total": self.partition_seconds,
        })

    def _write_status(self, bid: int) -> None:
        """One-shot observability snapshot beside the WAL —
        `tools/diagnose.py --streaming <checkpoint_dir>` renders it."""
        doc = {
            "query": self.name,
            "mode": self._worker_mode,
            "key_col": self.key_col,
            "num_partitions": self.num_partitions,
            "batch_id": bid,
            "time": time.time(),
            "partitions": {
                str(p): dict(self._pinfo.get(p) or {},
                             last_state_bid=self._last_state_bid.get(p))
                for p in range(self.num_partitions)},
        }
        path = os.path.join(self._log.dir, "status.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass

    # -- lifecycle --------------------------------------------------------- #

    def stop(self) -> None:
        super().stop()
        if self._workers_stopped:
            return
        self._workers_stopped = True
        if self._workers_list:
            for w in self._workers_list:
                w.stop()
        if self._send_pool is not None:
            self._send_pool.shutdown(wait=False)
        if self._fleet is not None and self._own_fleet:
            try:
                self._fleet.stop()
            except Exception:  # noqa: BLE001 — already down
                pass
