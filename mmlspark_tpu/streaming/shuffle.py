"""Keyed shuffle: hash-by-key row routing between source and stateful
operators.

Reference: Spark's exchange/repartition boundary — `groupBy(key)` on a
stream inserts a hash shuffle so every row of a key lands on the SAME
partition, which is what lets per-key state live unreplicated on one
worker. The reference leans on Spark's whole shuffle service; here the
exchange is a pure function over a `Table` plus a registered marker
stage, and `streaming/partition.py` supplies the workers.

Determinism is the whole design: Python's builtin `hash` is salted per
process, so partition routing uses a keyed blake2b digest (the same
`_stable_hash` construction as io_http's consistent-hash ring). The same
key maps to the same partition in every process, every run — which is
what makes P-way output reproducible and kill-restart replay byte-exact
across driver and fleet-worker incarnations.

`split_by_partition` preserves within-partition row order (gather over
an ascending index mask), so for any key the sequence of rows a
partition sees equals that key's subsequence of the original stream —
stateful folds per key are order-identical at P=1 and P=N.
"""

from __future__ import annotations

import hashlib
from typing import Any

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.schema import Table
from ..core.serialize import register_stage

__all__ = ["stable_hash", "partition_of", "partition_ids",
           "split_by_partition", "KeyedShuffle"]


def stable_hash(key: Any) -> int:
    """Process-stable 64-bit hash of a key (via `str`)."""
    digest = hashlib.blake2b(str(key).encode("utf-8"),
                             digest_size=8).digest()
    return int.from_bytes(digest, "big")


def partition_of(key: Any, num_partitions: int) -> int:
    return stable_hash(key) % num_partitions


def partition_ids(table: Table, key_col: str,
                  num_partitions: int) -> "np.ndarray":
    """Partition id per row, same length as the table."""
    return np.array([partition_of(k, num_partitions)
                     for k in table[key_col]], dtype=np.int64)


def split_by_partition(table: Table, key_col: str,
                       num_partitions: int) -> "list[Table]":
    """Split rows into `num_partitions` tables by key hash. Every row of
    a key lands in exactly one output; each output preserves the input's
    relative row order; concatenating the outputs is a permutation of
    the input."""
    if num_partitions <= 1:
        return [table]
    if not table.num_rows:
        return [table.gather(np.zeros(0, dtype=np.int64))
                for _ in range(num_partitions)]
    pids = partition_ids(table, key_col, num_partitions)
    return [table.gather(pids == p) for p in range(num_partitions)]


@register_stage
class KeyedShuffle(Transformer):
    """The exchange boundary as a registered pipeline stage.

    Inside a `ParallelStreamingQuery` pipeline the stage is a MARKER:
    stages before it run on the driver, stages after it run once per
    partition on rows routed by `hash(key_col) % num_partitions` (the
    stage itself is cut out of both halves). Run standalone,
    `transform` annotates rows with their target partition in
    `partition_col` — useful for auditing routing and for tests.
    """

    key_col = Param("key", "column whose hash routes each row to a "
                    "partition", ptype=str)
    num_partitions = Param(2, "number of parallel partitions (P)",
                           ptype=int, validator=lambda v: v >= 1)
    partition_col = Param("partition", "output column holding the routed "
                          "partition id (standalone transform only)",
                          ptype=str)

    def _transform(self, table: Table) -> Table:
        pids = partition_ids(table, self.get("key_col"),
                             self.get("num_partitions"))
        return table.with_column(self.get("partition_col"), pids)
