"""Deep-model subsystem: architectures, jit-compiled batched inference
(the CNTKModel equivalent), in-process SPMD training (the cntk-train
equivalent), transfer-learning featurization, and a model zoo.

Reference modules replaced: src/cntk-model/ (CNTKModel.scala),
src/cntk-train/ (CNTKLearner.scala), src/image-featurizer/
(ImageFeaturizer.scala), src/downloader/ (ModelDownloader.scala).
"""

from .models import (
    MLP,
    SimpleCNN,
    ResNet,
    resnet20_cifar,
    resnet50,
    ARCHITECTURES,
    make_model,
    ModelBundle,
)
from .runner import DeepModelTransformer
from .trainer import DNNLearner, DNNModel
from .featurizer import ImageFeaturizer
from .zoo import ModelSchema, ModelDownloader, retry_with_timeout

__all__ = [
    "MLP",
    "SimpleCNN",
    "ResNet",
    "resnet20_cifar",
    "resnet50",
    "ARCHITECTURES",
    "make_model",
    "ModelBundle",
    "DeepModelTransformer",
    "DNNLearner",
    "DNNModel",
    "ImageFeaturizer",
    "ModelSchema",
    "ModelDownloader",
    "retry_with_timeout",
]
