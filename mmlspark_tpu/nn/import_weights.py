"""External pretrained-weight ingestion: torch-layout state dicts -> flax.

Reference: the transfer-learning story rests on REAL pretrained models
pulled from a remote repo by `ModelDownloader` (ModelDownloader.scala:209+,
Schema.scala:30-119 — uri/hash/layerNames/inputNode) and cut at a layer by
`ImageFeaturizer` (ImageFeaturizer.scala:92-135). The CNTK-format model file
is the interchange artifact. Here the interchange artifact is the de-facto
standard for published CNN weights: a torch-style state dict (flat
name->tensor mapping, PyTorch/torchvision naming and layouts), shipped as
`.safetensors` or `.npz` — both readable without torch itself.

What the mapper translates (torchvision ResNet naming -> nn.models.ResNet):

  conv1.weight                 -> params/stem_conv/kernel   (OIHW -> HWIO)
  bn1.{weight,bias}            -> params/stem_bn/{scale,bias}
  bn1.running_{mean,var}       -> batch_stats/stem_bn/{mean,var}
  layer<L>.<B>.conv<N>.weight  -> params/stage<L-1>_block<B>/conv<N>/kernel
  layer<L>.<B>.bn<N>.*         -> params|batch_stats/.../bn<N>/*
  layer<L>.<B>.downsample.0.*  -> .../proj_conv/kernel
  layer<L>.<B>.downsample.1.*  -> .../proj_bn/*
  fc.{weight,bias}             -> params/head/{kernel,bias}  ((out,in) -> (in,out))

The result is validated leaf-for-leaf (path and shape) against the target
module's own `init` tree, so a wrong transpose or a missing block fails
loudly at import time, not silently at serving time.

Beyond the hand-written ResNet mapper, `MapRule`/`apply_mapping_spec`
define a DECLARATIVE mapping language (anchored regex -> target path +
layout transform) so new checkpoint families are a rule table, not a new
parser; `TRANSFORMER_SPEC` maps HF-style flat encoder state dicts
(`encoder.layer.<i>.attention.self.query.weight`, torch (out,in) layouts)
onto nn.models.TransformerEncoder. Note the architecture here is pre-LN
(ln before attention/mlp, final ln before pooling): checkpoints from
post-LN models (original BERT) carry the same tensor NAMES but different
math — importing one gives a well-formed model that is not
weight-equivalent to its source. The spec documents naming + layout, not
architectural equivalence.
"""

from __future__ import annotations

import os
import re
from typing import Any, Callable, Mapping, NamedTuple

import numpy as np

__all__ = [
    "load_state_dict",
    "torch_resnet_to_flax",
    "import_torch_resnet",
    "MapRule",
    "apply_mapping_spec",
    "TRANSFORMER_SPEC",
    "torch_transformer_to_flax",
    "import_torch_transformer",
    "import_external_weights",
    "IMPORTERS",
]


def load_state_dict(path: str) -> dict[str, np.ndarray]:
    """Read a flat name->array state dict from `.safetensors` or `.npz`.

    Both formats are readable with numpy-only code paths (safetensors via
    its numpy loader), so importing published weights needs no torch
    runtime — the analogue of the reference reading CNTK model bytes
    without the training toolchain (SerializableFunction.scala:85+)."""
    ext = os.path.splitext(path)[1].lower()
    if ext == ".safetensors":
        from safetensors.numpy import load_file

        return dict(load_file(path))
    if ext == ".npz":
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    raise ValueError(
        f"unsupported weight format {ext!r}; expected .safetensors or .npz"
    )


_LAYER_RE = re.compile(
    r"^layer(?P<stage>\d+)\.(?P<block>\d+)\.(?P<rest>.+)$"
)


def _assign(tree: dict, path: tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def _conv_kernel(w: np.ndarray) -> np.ndarray:
    """torch conv weight OIHW -> flax HWIO."""
    if w.ndim != 4:
        raise ValueError(f"conv weight must be 4-D, got {w.shape}")
    return np.transpose(w, (2, 3, 1, 0))


def _map_bn(rest: str, prefix: tuple[str, ...], value, params, batch_stats,
            bn_name: str) -> bool:
    leaf = rest.split(".")[-1]
    if leaf == "weight":
        _assign(params, prefix + (bn_name, "scale"), value)
    elif leaf == "bias":
        _assign(params, prefix + (bn_name, "bias"), value)
    elif leaf == "running_mean":
        _assign(batch_stats, prefix + (bn_name, "mean"), value)
    elif leaf == "running_var":
        _assign(batch_stats, prefix + (bn_name, "var"), value)
    elif leaf == "num_batches_tracked":
        return True                                  # torch-only bookkeeping
    else:
        return False
    return True


def torch_resnet_to_flax(
    state_dict: Mapping[str, np.ndarray],
) -> dict[str, Any]:
    """Map a torchvision-style ResNet state dict to nn.models.ResNet
    variables ({"params": ..., "batch_stats": ...}). Raises ValueError on
    any unrecognized key — silent drops are how transposed/missing weights
    slip through to produce garbage activations."""
    params: dict[str, Any] = {}
    batch_stats: dict[str, Any] = {}
    for name, value in state_dict.items():
        value = np.asarray(value)
        if name == "conv1.weight":
            _assign(params, ("stem_conv", "kernel"), _conv_kernel(value))
            continue
        if name.startswith("bn1."):
            if _map_bn(name, (), value, params, batch_stats, "stem_bn"):
                continue
            raise ValueError(f"unrecognized stem bn key {name!r}")
        if name == "fc.weight":
            _assign(params, ("head", "kernel"), np.transpose(value, (1, 0)))
            continue
        if name == "fc.bias":
            _assign(params, ("head", "bias"), value)
            continue
        m = _LAYER_RE.match(name)
        if m is None:
            raise ValueError(f"unrecognized state-dict key {name!r}")
        stage = int(m.group("stage")) - 1            # torch layer1 -> stage0
        block = f"stage{stage}_block{int(m.group('block'))}"
        rest = m.group("rest")
        cm = re.match(r"^conv(\d+)\.weight$", rest)
        if cm:
            _assign(params, (block, f"conv{cm.group(1)}", "kernel"),
                    _conv_kernel(value))
            continue
        bm = re.match(r"^bn(\d+)\.(.+)$", rest)
        if bm and _map_bn(rest, (block,), value, params, batch_stats,
                          f"bn{bm.group(1)}"):
            continue
        dm = re.match(r"^downsample\.(\d)\.(.+)$", rest)
        if dm:
            if dm.group(1) == "0" and dm.group(2) == "weight":
                _assign(params, (block, "proj_conv", "kernel"),
                        _conv_kernel(value))
                continue
            if dm.group(1) == "1" and _map_bn(
                rest, (block,), value, params, batch_stats, "proj_bn"
            ):
                continue
        raise ValueError(f"unrecognized state-dict key {name!r}")
    return {"params": params, "batch_stats": batch_stats}


# --------------------------------------------------------------------- #
# declarative mapping specs                                             #
# --------------------------------------------------------------------- #


class MapRule(NamedTuple):
    """One mapping rule: `pattern` is an anchored regex over state-dict
    keys; `target` is a '/'-joined destination path whose FIRST segment
    names the collection (params | batch_stats), either a template string
    (regex group expansion via m.expand) or a callable(match) -> str;
    None drops the tensor (framework-only bookkeeping). `transform`
    (value, ctx) -> value converts torch layouts to flax (ctx carries
    model config the shapes alone can't determine, e.g. num_heads)."""

    pattern: str
    target: "str | Callable | None"
    transform: "Callable[[np.ndarray, dict], np.ndarray] | None" = None


def apply_mapping_spec(
    state_dict: Mapping[str, np.ndarray],
    rules: "list[MapRule]",
    ctx: "dict | None" = None,
) -> dict[str, Any]:
    """Run a rule table over a flat state dict -> flax variables.

    First matching rule wins; a key no rule matches raises (silent drops
    are how transposed/missing weights slip through to garbage
    activations — same contract as the hand-written ResNet mapper)."""
    ctx = ctx or {}
    compiled = [(re.compile(r.pattern), r) for r in rules]
    out: dict[str, Any] = {"params": {}, "batch_stats": {}}
    for name, value in state_dict.items():
        for cre, rule in compiled:
            m = cre.fullmatch(name)
            if m is None:
                continue
            if rule.target is None:
                break
            target = (rule.target(m) if callable(rule.target)
                      else m.expand(rule.target))
            path = tuple(target.split("/"))
            if path[0] not in out:
                raise ValueError(
                    f"rule for {name!r} targets unknown collection {path[0]!r}"
                )
            v = np.asarray(value)
            if rule.transform is not None:
                v = rule.transform(v, ctx)
            _assign(out[path[0]], path[1:], v)
            break
        else:
            raise ValueError(f"unrecognized state-dict key {name!r}")
    return out


def _t_transpose(v, ctx):
    """torch Dense (out, in) -> flax (in, out)."""
    return np.transpose(v, (1, 0))


def _t_qkv_kernel(v, ctx):
    """torch (D, D) projection -> flax MHA DenseGeneral (D, H, D//H)."""
    d_model, h = v.shape[1], ctx["num_heads"]
    return np.transpose(v, (1, 0)).reshape(d_model, h, v.shape[0] // h)


def _t_qkv_bias(v, ctx):
    h = ctx["num_heads"]
    return v.reshape(h, v.shape[0] // h)


def _t_attn_out_kernel(v, ctx):
    """torch (D_out, D_in) output projection -> flax (H, D_in//H, D_out)."""
    h = ctx["num_heads"]
    return np.transpose(v, (1, 0)).reshape(h, v.shape[1] // h, v.shape[0])


# HF-style flat naming for a PRE-LN encoder (see module docstring for the
# post-LN caveat): attention.ln / mlp.ln are the pre-attention and pre-mlp
# layer norms, final_layer_norm closes the stack, classifier is the head.
TRANSFORMER_SPEC: "list[MapRule]" = [
    MapRule(r"embeddings\.word_embeddings\.weight", "params/embed/embedding"),
    MapRule(r"embeddings\.position_embeddings\.weight", "params/pos_embed"),
    MapRule(r"stem\.weight", "params/stem/kernel", _t_transpose),
    MapRule(r"stem\.bias", "params/stem/bias"),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.attention\.ln\.weight",
            r"params/ln_attn_\g<i>/scale"),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.attention\.ln\.bias",
            r"params/ln_attn_\g<i>/bias"),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.attention\.self\."
            r"(?P<proj>query|key|value)\.weight",
            r"params/attn_\g<i>/\g<proj>/kernel", _t_qkv_kernel),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.attention\.self\."
            r"(?P<proj>query|key|value)\.bias",
            r"params/attn_\g<i>/\g<proj>/bias", _t_qkv_bias),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.attention\.output\.dense\.weight",
            r"params/attn_\g<i>/out/kernel", _t_attn_out_kernel),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.attention\.output\.dense\.bias",
            r"params/attn_\g<i>/out/bias"),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.mlp\.ln\.weight",
            r"params/ln_mlp_\g<i>/scale"),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.mlp\.ln\.bias",
            r"params/ln_mlp_\g<i>/bias"),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.intermediate\.dense\.weight",
            r"params/mlp_up_\g<i>/kernel", _t_transpose),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.intermediate\.dense\.bias",
            r"params/mlp_up_\g<i>/bias"),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.output\.dense\.weight",
            r"params/mlp_down_\g<i>/kernel", _t_transpose),
    MapRule(r"encoder\.layer\.(?P<i>\d+)\.output\.dense\.bias",
            r"params/mlp_down_\g<i>/bias"),
    MapRule(r"final_layer_norm\.weight", "params/ln_final/scale"),
    MapRule(r"final_layer_norm\.bias", "params/ln_final/bias"),
    MapRule(r"classifier\.weight", "params/head/kernel", _t_transpose),
    MapRule(r"classifier\.bias", "params/head/bias"),
    MapRule(r".*\.num_batches_tracked", None),
]


def torch_transformer_to_flax(
    state_dict: Mapping[str, np.ndarray], num_heads: int,
) -> dict[str, Any]:
    """Map an HF-style flat encoder state dict onto
    nn.models.TransformerEncoder variables. num_heads is required: the
    fused (D, D) projection shapes cannot determine the head split."""
    return apply_mapping_spec(
        state_dict, TRANSFORMER_SPEC, {"num_heads": int(num_heads)}
    )


def _tree_leaves(tree: Any, prefix: str = "") -> dict[str, tuple[int, ...]]:
    out: dict[str, tuple[int, ...]] = {}
    if isinstance(tree, Mapping):
        for k, v in tree.items():
            out.update(_tree_leaves(v, f"{prefix}/{k}" if prefix else str(k)))
        return out
    out[prefix] = tuple(np.shape(tree))
    return out


def import_torch_resnet(
    path: str,
    architecture: str = "resnet50",
    num_outputs: int | None = None,
    input_shape: tuple[int, ...] = (224, 224, 3),
    preprocess: dict | None = None,
    class_labels=None,
    **config,
):
    """Load torch-layout ResNet weights into a ready-to-serve ModelBundle.

    The imported tree is validated leaf-for-leaf against the architecture's
    own init tree: every path must exist on both sides with the same shape.
    `num_outputs` defaults to the checkpoint's fc row count."""
    import jax.numpy as jnp

    from .models import ModelBundle

    sd = load_state_dict(path)
    variables = torch_resnet_to_flax(sd)
    if num_outputs is None:
        fc = sd.get("fc.weight")
        if fc is None:
            raise ValueError("state dict has no fc.weight; pass num_outputs")
        num_outputs = int(np.asarray(fc).shape[0])

    bundle = ModelBundle.init(
        architecture, input_shape=tuple(input_shape), seed=0,
        class_labels=class_labels,
        preprocess=dict(
            preprocess
            if preprocess is not None
            # torchvision ImageNet normalization, scaled to 0-255 inputs
            else {"mean": [123.675, 116.28, 103.53],
                  "std": [58.395, 57.12, 57.375]}
        ),
        num_outputs=int(num_outputs), **config,
    )
    return _validate_and_install(bundle, variables, architecture)


def _validate_and_install(bundle, variables, architecture: str):
    """Leaf-for-leaf validation against the architecture's own init tree
    (every path present on both sides, same shape), then install the
    imported arrays as float32 device arrays. Shared by every importer so
    a new family can't skip the check."""
    import jax.numpy as jnp

    want = _tree_leaves(bundle.variables)
    got = _tree_leaves(variables)
    missing = sorted(set(want) - set(got))
    extra = sorted(set(got) - set(want))
    mis = [k for k in want if k in got and want[k] != got[k]]
    if missing or extra or mis:
        detail = "; ".join(
            filter(None, [
                f"missing {missing[:5]}" if missing else "",
                f"unexpected {extra[:5]}" if extra else "",
                f"shape mismatch {[ (k, got[k], want[k]) for k in mis[:5] ]}"
                if mis else "",
            ])
        )
        raise ValueError(f"imported weights do not fit {architecture}: {detail}")
    bundle.variables = {
        k: _as_jnp(variables.get(k, {}), jnp) for k in bundle.variables
    }
    return bundle


def import_torch_transformer(
    path: str,
    architecture: str = "transformer",
    num_outputs: int | None = None,
    input_shape: tuple[int, ...] = (),
    preprocess: dict | None = None,
    class_labels=None,
    **config,
):
    """Load HF-style flat encoder weights into a ready-to-serve
    ModelBundle (the second imported family next to ResNet; reference
    parity anchor: ModelDownloader ingesting arbitrary published models,
    Schema.scala:30-119).

    Model dimensions are inferred from the checkpoint where shapes
    determine them (d_model/vocab_size from the embedding, num_layers
    from the layer indexes, d_ff from the mlp width, max_len from the
    position table, num_outputs from the classifier); `num_heads` cannot
    be inferred and must come from config (default 4)."""
    sd = load_state_dict(path)
    cfg = dict(config)
    emb = sd.get("embeddings.word_embeddings.weight")
    stem = sd.get("stem.weight")
    if emb is not None:
        cfg.setdefault("vocab_size", int(emb.shape[0]))
        cfg.setdefault("d_model", int(emb.shape[1]))
    elif stem is not None:
        cfg.setdefault("vocab_size", 0)
        cfg.setdefault("d_model", int(stem.shape[0]))
    else:
        raise ValueError(
            "state dict has neither embeddings.word_embeddings.weight nor "
            "stem.weight; not an encoder checkpoint this spec understands"
        )
    layer_ids = [
        int(m.group(1)) for m in
        (re.match(r"encoder\.layer\.(\d+)\.", k) for k in sd)
        if m is not None
    ]
    if not layer_ids:
        raise ValueError("state dict has no encoder.layer.<i> tensors")
    cfg.setdefault("num_layers", max(layer_ids) + 1)
    up0 = sd.get("encoder.layer.0.intermediate.dense.weight")
    if up0 is not None:
        cfg.setdefault("d_ff", int(up0.shape[0]))
    pos = sd.get("embeddings.position_embeddings.weight")
    if pos is not None:
        cfg.setdefault("max_len", int(pos.shape[0]))
    if num_outputs is None:
        head = sd.get("classifier.weight")
        if head is None:
            raise ValueError("state dict has no classifier.weight; "
                             "pass num_outputs")
        num_outputs = int(head.shape[0])
    cfg.setdefault("num_heads", 4)
    if cfg["d_model"] % cfg["num_heads"]:
        raise ValueError(
            f"d_model {cfg['d_model']} is not divisible by num_heads "
            f"{cfg['num_heads']}"
        )
    variables = torch_transformer_to_flax(sd, num_heads=cfg["num_heads"])

    from .models import ModelBundle

    if not input_shape:
        # one token position is enough to trace init; the pos table is
        # sized by max_len, not by the probe length
        input_shape = (8,) if cfg.get("vocab_size") else (8, 1)
    bundle = ModelBundle.init(
        architecture, input_shape=tuple(input_shape), seed=0,
        class_labels=class_labels, preprocess=dict(preprocess or {}),
        num_outputs=int(num_outputs), **cfg,
    )
    return _validate_and_install(bundle, variables, architecture)


# architecture name -> importer; zoo.import_external dispatches here, so
# registering a new family makes it fetchable/verifiable end to end
IMPORTERS: "dict[str, Callable]" = {
    "resnet": import_torch_resnet,
    "resnet50": import_torch_resnet,
    "resnet20_cifar": import_torch_resnet,
    "transformer": import_torch_transformer,
}


def import_external_weights(path: str, architecture: str, **kw):
    """Dispatch an external checkpoint to its family importer."""
    imp = IMPORTERS.get(architecture)
    if imp is None:
        raise ValueError(
            f"no weight importer registered for architecture "
            f"{architecture!r}; known: {sorted(IMPORTERS)}"
        )
    return imp(path, architecture=architecture, **kw)


def _as_jnp(tree, jnp):
    if isinstance(tree, Mapping):
        return {k: _as_jnp(v, jnp) for k, v in tree.items()}
    return jnp.asarray(np.asarray(tree, np.float32))
