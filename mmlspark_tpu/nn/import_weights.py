"""External pretrained-weight ingestion: torch-layout state dicts -> flax.

Reference: the transfer-learning story rests on REAL pretrained models
pulled from a remote repo by `ModelDownloader` (ModelDownloader.scala:209+,
Schema.scala:30-119 — uri/hash/layerNames/inputNode) and cut at a layer by
`ImageFeaturizer` (ImageFeaturizer.scala:92-135). The CNTK-format model file
is the interchange artifact. Here the interchange artifact is the de-facto
standard for published CNN weights: a torch-style state dict (flat
name->tensor mapping, PyTorch/torchvision naming and layouts), shipped as
`.safetensors` or `.npz` — both readable without torch itself.

What the mapper translates (torchvision ResNet naming -> nn.models.ResNet):

  conv1.weight                 -> params/stem_conv/kernel   (OIHW -> HWIO)
  bn1.{weight,bias}            -> params/stem_bn/{scale,bias}
  bn1.running_{mean,var}       -> batch_stats/stem_bn/{mean,var}
  layer<L>.<B>.conv<N>.weight  -> params/stage<L-1>_block<B>/conv<N>/kernel
  layer<L>.<B>.bn<N>.*         -> params|batch_stats/.../bn<N>/*
  layer<L>.<B>.downsample.0.*  -> .../proj_conv/kernel
  layer<L>.<B>.downsample.1.*  -> .../proj_bn/*
  fc.{weight,bias}             -> params/head/{kernel,bias}  ((out,in) -> (in,out))

The result is validated leaf-for-leaf (path and shape) against the target
module's own `init` tree, so a wrong transpose or a missing block fails
loudly at import time, not silently at serving time.
"""

from __future__ import annotations

import os
import re
from typing import Any, Mapping

import numpy as np

__all__ = [
    "load_state_dict",
    "torch_resnet_to_flax",
    "import_torch_resnet",
]


def load_state_dict(path: str) -> dict[str, np.ndarray]:
    """Read a flat name->array state dict from `.safetensors` or `.npz`.

    Both formats are readable with numpy-only code paths (safetensors via
    its numpy loader), so importing published weights needs no torch
    runtime — the analogue of the reference reading CNTK model bytes
    without the training toolchain (SerializableFunction.scala:85+)."""
    ext = os.path.splitext(path)[1].lower()
    if ext == ".safetensors":
        from safetensors.numpy import load_file

        return dict(load_file(path))
    if ext == ".npz":
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    raise ValueError(
        f"unsupported weight format {ext!r}; expected .safetensors or .npz"
    )


_LAYER_RE = re.compile(
    r"^layer(?P<stage>\d+)\.(?P<block>\d+)\.(?P<rest>.+)$"
)


def _assign(tree: dict, path: tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def _conv_kernel(w: np.ndarray) -> np.ndarray:
    """torch conv weight OIHW -> flax HWIO."""
    if w.ndim != 4:
        raise ValueError(f"conv weight must be 4-D, got {w.shape}")
    return np.transpose(w, (2, 3, 1, 0))


def _map_bn(rest: str, prefix: tuple[str, ...], value, params, batch_stats,
            bn_name: str) -> bool:
    leaf = rest.split(".")[-1]
    if leaf == "weight":
        _assign(params, prefix + (bn_name, "scale"), value)
    elif leaf == "bias":
        _assign(params, prefix + (bn_name, "bias"), value)
    elif leaf == "running_mean":
        _assign(batch_stats, prefix + (bn_name, "mean"), value)
    elif leaf == "running_var":
        _assign(batch_stats, prefix + (bn_name, "var"), value)
    elif leaf == "num_batches_tracked":
        return True                                  # torch-only bookkeeping
    else:
        return False
    return True


def torch_resnet_to_flax(
    state_dict: Mapping[str, np.ndarray],
) -> dict[str, Any]:
    """Map a torchvision-style ResNet state dict to nn.models.ResNet
    variables ({"params": ..., "batch_stats": ...}). Raises ValueError on
    any unrecognized key — silent drops are how transposed/missing weights
    slip through to produce garbage activations."""
    params: dict[str, Any] = {}
    batch_stats: dict[str, Any] = {}
    for name, value in state_dict.items():
        value = np.asarray(value)
        if name == "conv1.weight":
            _assign(params, ("stem_conv", "kernel"), _conv_kernel(value))
            continue
        if name.startswith("bn1."):
            if _map_bn(name, (), value, params, batch_stats, "stem_bn"):
                continue
            raise ValueError(f"unrecognized stem bn key {name!r}")
        if name == "fc.weight":
            _assign(params, ("head", "kernel"), np.transpose(value, (1, 0)))
            continue
        if name == "fc.bias":
            _assign(params, ("head", "bias"), value)
            continue
        m = _LAYER_RE.match(name)
        if m is None:
            raise ValueError(f"unrecognized state-dict key {name!r}")
        stage = int(m.group("stage")) - 1            # torch layer1 -> stage0
        block = f"stage{stage}_block{int(m.group('block'))}"
        rest = m.group("rest")
        cm = re.match(r"^conv(\d+)\.weight$", rest)
        if cm:
            _assign(params, (block, f"conv{cm.group(1)}", "kernel"),
                    _conv_kernel(value))
            continue
        bm = re.match(r"^bn(\d+)\.(.+)$", rest)
        if bm and _map_bn(rest, (block,), value, params, batch_stats,
                          f"bn{bm.group(1)}"):
            continue
        dm = re.match(r"^downsample\.(\d)\.(.+)$", rest)
        if dm:
            if dm.group(1) == "0" and dm.group(2) == "weight":
                _assign(params, (block, "proj_conv", "kernel"),
                        _conv_kernel(value))
                continue
            if dm.group(1) == "1" and _map_bn(
                rest, (block,), value, params, batch_stats, "proj_bn"
            ):
                continue
        raise ValueError(f"unrecognized state-dict key {name!r}")
    return {"params": params, "batch_stats": batch_stats}


def _tree_leaves(tree: Any, prefix: str = "") -> dict[str, tuple[int, ...]]:
    out: dict[str, tuple[int, ...]] = {}
    if isinstance(tree, Mapping):
        for k, v in tree.items():
            out.update(_tree_leaves(v, f"{prefix}/{k}" if prefix else str(k)))
        return out
    out[prefix] = tuple(np.shape(tree))
    return out


def import_torch_resnet(
    path: str,
    architecture: str = "resnet50",
    num_outputs: int | None = None,
    input_shape: tuple[int, ...] = (224, 224, 3),
    preprocess: dict | None = None,
    class_labels=None,
    **config,
):
    """Load torch-layout ResNet weights into a ready-to-serve ModelBundle.

    The imported tree is validated leaf-for-leaf against the architecture's
    own init tree: every path must exist on both sides with the same shape.
    `num_outputs` defaults to the checkpoint's fc row count."""
    import jax.numpy as jnp

    from .models import ModelBundle

    sd = load_state_dict(path)
    variables = torch_resnet_to_flax(sd)
    if num_outputs is None:
        fc = sd.get("fc.weight")
        if fc is None:
            raise ValueError("state dict has no fc.weight; pass num_outputs")
        num_outputs = int(np.asarray(fc).shape[0])

    bundle = ModelBundle.init(
        architecture, input_shape=tuple(input_shape), seed=0,
        class_labels=class_labels,
        preprocess=dict(
            preprocess
            if preprocess is not None
            # torchvision ImageNet normalization, scaled to 0-255 inputs
            else {"mean": [123.675, 116.28, 103.53],
                  "std": [58.395, 57.12, 57.375]}
        ),
        num_outputs=int(num_outputs), **config,
    )
    want = _tree_leaves(bundle.variables)
    got = _tree_leaves(variables)
    missing = sorted(set(want) - set(got))
    extra = sorted(set(got) - set(want))
    mis = [k for k in want if k in got and want[k] != got[k]]
    if missing or extra or mis:
        detail = "; ".join(
            filter(None, [
                f"missing {missing[:5]}" if missing else "",
                f"unexpected {extra[:5]}" if extra else "",
                f"shape mismatch {[ (k, got[k], want[k]) for k in mis[:5] ]}"
                if mis else "",
            ])
        )
        raise ValueError(f"imported weights do not fit {architecture}: {detail}")
    bundle.variables = {
        "params": _as_jnp(variables["params"], jnp),
        "batch_stats": _as_jnp(variables["batch_stats"], jnp),
    }
    return bundle


def _as_jnp(tree, jnp):
    if isinstance(tree, Mapping):
        return {k: _as_jnp(v, jnp) for k, v in tree.items()}
    return jnp.asarray(np.asarray(tree, np.float32))
