"""Single-device attention implementations: dense, chunked, Pallas flash.

The reference has no sequence-model family at all (SURVEY.md §5.7); this
module is the single-device half of the beyond-reference attention stack —
the cross-device half (ring / Ulysses sequence parallelism over the mesh)
lives in `parallel.ring_attention` and implements identical math.

Three tiers, one contract (inputs (B, T, H, D), output (B, T, H, D)):

- ``dense_attention`` (re-exported from parallel.ring_attention): full
  (T, T) score matrix. The reference implementation every other tier is
  tested against; O(T^2) HBM, fine for short sequences.
- ``chunked_attention``: online-softmax over key/value chunks via
  `lax.scan` (the Rabe-Staats memory-efficient formulation). O(T) memory,
  differentiable (XLA derives the backward through the scan), works on
  every backend — the long-sequence TRAINING path on one device.
- ``flash_attention``: a Pallas TPU kernel for the forward hot path —
  the (block_q, block_k) score tile lives only in VMEM, never HBM, with
  the online-softmax running max / denominator / accumulator carried in
  VMEM scratch across the sequential key-block grid dimension.
  DIFFERENTIABLE via `jax.custom_vjp`: the kernel also emits the per-row
  logsumexp, and the backward is the standard flash recomputation as a
  pure-XLA k-block scan (compiles on every backend; O(T) score memory).

The chunked and flash tiers compute scores and the softmax accumulator in
float32 whatever the input dtype (bf16 inputs stay bf16 through the
projections; the numerically sensitive reduction is f32 — the standard
TPU recipe). The dense tier is the unmodified reference math from
`parallel.ring_attention` and follows the INPUT dtype throughout — with
bf16 inputs it is the least accurate tier, not the most; prefer chunked
or flash for bf16 serving.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..parallel.ring_attention import dense_attention

__all__ = ["dense_attention", "chunked_attention", "flash_attention",
           "SelfAttention"]

_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp/max NaN-free


def _pad_seq(x, mult):
    t = x.shape[1]
    pad = (-t) % mult
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
    return x, t


# --------------------------------------------------------------------- #
# chunked (memory-efficient, differentiable)                            #
# --------------------------------------------------------------------- #

def chunked_attention(q, k, v, causal: bool = False,
                      q_chunk: int = 128, k_chunk: int = 128):
    """Online-softmax attention over k/v chunks; O(T) memory.

    q: (B, Tq, H, D); k, v: (B, Tk, H, D) -> (B, Tq, H, D), matching
    `dense_attention` (tested bit-close against it). Differentiable —
    XLA transposes the scan for the backward pass; pair with
    `jax.checkpoint` on the caller for long sequences.
    """
    orig_dtype = q.dtype
    b, tq_orig, h, d = q.shape
    tk_orig = k.shape[1]
    q_chunk = min(q_chunk, max(tq_orig, 1))
    k_chunk = min(k_chunk, max(tk_orig, 1))
    q, tq = _pad_seq(q, q_chunk)
    k, tk = _pad_seq(k, k_chunk)
    v, _ = _pad_seq(v, k_chunk)
    nq, nk = q.shape[1] // q_chunk, k.shape[1] // k_chunk
    scale = d ** -0.5

    # (nq, B, qc, H, D) so scan carries one q-chunk at a time
    qr = jnp.moveaxis(q.reshape(b, nq, q_chunk, h, d), 1, 0)
    kr = jnp.moveaxis(k.reshape(b, nk, k_chunk, h, d), 1, 0)
    vr = jnp.moveaxis(v.reshape(b, nk, k_chunk, h, d), 1, 0)

    kpos = jnp.arange(nk * k_chunk).reshape(nk, k_chunk)
    k_valid = kpos < tk                                       # pad mask

    def one_q_chunk(qi, qb):
        qpos = qi * q_chunk + jnp.arange(q_chunk)

        def body(carry, xs):
            m, l, acc = carry
            kb, vb, kp, kv_ok = xs
            s = jnp.einsum("bqhd,bkhd->bhqk", qb, kb,
                           preferred_element_type=jnp.float32) * scale
            ok = kv_ok[None, :]
            if causal:
                ok = ok & (qpos[:, None] >= kp[None, :])
            s = jnp.where(ok[None, None], s, _NEG_INF)
            m_new = jnp.maximum(m, s.max(-1))
            p = jnp.exp(s - m_new[..., None])
            # masked entries contribute 0 even when the whole row is
            # masked (then m_new == _NEG_INF and exp(s - m_new) == 1)
            p = jnp.where(ok[None, None], p, 0.0)
            corr = jnp.exp(m - m_new)
            l = l * corr + p.sum(-1)
            acc = acc * corr[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32),
                preferred_element_type=jnp.float32)
            return (m_new, l, acc), None

        # + 0*qb: the carry inherits qb's type — under shard_map (the
        # Ulysses local core) that includes the varying-over-seq-axis
        # tag, which a plain zeros/full init would lack
        zvar = 0.0 * qb.astype(jnp.float32).transpose(0, 2, 1, 3)
        m0 = zvar[..., 0] + _NEG_INF                      # (B, H, qc)
        l0 = zvar[..., 0]
        a0 = zvar                                         # (B, H, qc, D)
        (m, l, acc), _ = jax.lax.scan(body, (m0, l0, a0),
                                      (kr, vr, kpos, k_valid))
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        # rows with no visible key (all masked) -> zeros, as dense does
        out = jnp.where((l > 0)[..., None], out, 0.0)
        return jnp.moveaxis(out, 1, 2)                        # (B, qc, H, D)

    outs = jax.lax.map(lambda xs: one_q_chunk(*xs),
                       (jnp.arange(nq), qr))                  # (nq,B,qc,H,D)
    out = jnp.moveaxis(outs, 0, 1).reshape(b, nq * q_chunk, h, d)
    return out[:, :tq].astype(orig_dtype)


# --------------------------------------------------------------------- #
# Pallas flash forward                                                  #
# --------------------------------------------------------------------- #

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc, *,
                  block_q, block_k, num_kv, causal, tk_valid, scale):
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    kv = pl.program_id(2)

    @pl.when(kv == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    qb = q_ref[0]                                             # (bq, D)
    kb = k_ref[0]                                             # (bk, D)
    s = jax.lax.dot_general(
        qb, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale           # (bq, bk)

    kpos = kv * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    ok = kpos < tk_valid
    if causal:
        qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        ok = ok & (qpos >= kpos)
    s = jnp.where(ok, s, _NEG_INF)

    m_prev = m_sc[...]                                        # (bq, 1)
    m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
    p = jnp.exp(s - m_new)                                    # (bq, bk)
    # masked entries must contribute 0 even when the whole row is masked
    # (then m_new == _NEG_INF and exp(s - m_new) == 1, not 0)
    p = jnp.where(ok, p, 0.0)
    corr = jnp.exp(m_prev - m_new)                            # (bq, 1)
    l_sc[...] = l_sc[...] * corr + p.sum(-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                   # (bq, D)
    acc_sc[...] = acc_sc[...] * corr + pv
    m_sc[...] = m_new

    @pl.when(kv == num_kv - 1)
    def _finalize():
        l = l_sc[...]
        out = acc_sc[...] / jnp.maximum(l, 1e-30)
        out = jnp.where(l > 0, out, 0.0)
        o_ref[0] = out.astype(o_ref.dtype)
        # per-row logsumexp, the backward pass's softmax residual;
        # +inf on fully-masked rows makes exp(s - lse) vanish there
        lse = jnp.where(
            l > 0, m_sc[...] + jnp.log(jnp.maximum(l, 1e-30)), jnp.inf)
        lse_ref[...] = lse.reshape(1, block_q)


def _flash_fwd_lse(q, k, v, causal, block_q, block_k, interpret):
    """Pallas forward; returns (out (B,Tq,H,D), lse (B,H,Tq) f32)."""
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    orig_dtype = q.dtype
    b, tq_orig, h, d = q.shape
    tk_orig = k.shape[1]
    block_q = min(block_q, max(tq_orig, 1))
    block_k = min(block_k, max(tk_orig, 1))
    q, tq = _pad_seq(q, block_q)
    k, tk = _pad_seq(k, block_k)
    v, _ = _pad_seq(v, block_k)

    # (B*H, T, D): one grid row per (batch, head)
    def bh(x):
        return jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1], d)

    qf, kf, vf = bh(q), bh(k), bh(v)
    nq, nk = qf.shape[1] // block_q, kf.shape[1] // block_k

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, num_kv=nk,
        causal=causal, tk_valid=tk, scale=d ** -0.5)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, qi, kv: (bh_, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, qi, kv: (bh_, kv, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, qi, kv: (bh_, kv, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, qi, kv: (bh_, qi, 0)),
            pl.BlockSpec((1, block_q), lambda bh_, qi, kv: (bh_, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, orig_dtype),
            jax.ShapeDtypeStruct(qf.shape[:2], jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)

    out = out.reshape(b, h, out.shape[1], d)   # already orig_dtype via
    out = jnp.moveaxis(out, 1, 2)[:, :tq]      # pallas out_shape
    lse = lse.reshape(b, h, -1)[:, :, :tq]     # (B, H, Tq)
    return out, lse


def _flash_bwd_xla(q, k, v, out, lse, do, causal, k_chunk):
    """Flash-attention backward as a pure-XLA scan over k blocks (the
    standard dV/dK/dQ recomputation driven by the saved logsumexp).
    Pure XLA by design: it compiles on every backend and avoids the
    interpret-vs-Mosaic gap the histogram kernels hit on real v5e, while
    keeping O(T) score memory like the forward."""
    f32 = jnp.float32
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = d ** -0.5
    qf = jnp.moveaxis(q, 2, 1).astype(f32)            # (B, H, Tq, D)
    dof = jnp.moveaxis(do, 2, 1).astype(f32)
    of = jnp.moveaxis(out, 2, 1).astype(f32)
    delta = (dof * of).sum(-1)                        # (B, H, Tq)

    k_chunk = min(k_chunk, max(tk, 1))
    kp_, _ = _pad_seq(k, k_chunk)
    vp_, _ = _pad_seq(v, k_chunk)
    kf = jnp.moveaxis(kp_, 2, 1).astype(f32)          # (B, H, Tk+, D)
    vf = jnp.moveaxis(vp_, 2, 1).astype(f32)
    nk = kf.shape[2] // k_chunk
    kr = jnp.moveaxis(kf.reshape(b, h, nk, k_chunk, d), 2, 0)
    vr = jnp.moveaxis(vf.reshape(b, h, nk, k_chunk, d), 2, 0)
    kpos = jnp.arange(nk * k_chunk).reshape(nk, k_chunk)
    qpos = jnp.arange(tq)

    def body(dq_acc, xs):
        kb, vb, kp = xs                               # (B,H,kc,D), (kc,)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb,
                       preferred_element_type=f32) * scale
        ok = (kp < tk)[None, None, None, :]
        if causal:
            ok = ok & (qpos[:, None] >= kp[None, :])[None, None]
        # lse is +inf on fully-masked rows -> p = 0 there
        p = jnp.where(ok, jnp.exp(s - lse[..., None]), 0.0)
        dv_b = jnp.einsum("bhqk,bhqd->bhkd", p, dof,
                          preferred_element_type=f32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vb,
                        preferred_element_type=f32)
        ds = p * (dp - delta[..., None])
        dq_acc = dq_acc + jnp.einsum(
            "bhqk,bhkd->bhqd", ds, kb, preferred_element_type=f32) * scale
        dk_b = jnp.einsum("bhqk,bhqd->bhkd", ds, qf,
                          preferred_element_type=f32) * scale
        return dq_acc, (dk_b, dv_b)

    dq, (dks, dvs) = jax.lax.scan(
        body, jnp.zeros_like(qf), (kr, vr, kpos))
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, nk * k_chunk, d)[:, :, :tk]
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, nk * k_chunk, d)[:, :, :tk]
    return (jnp.moveaxis(dq, 1, 2).astype(q.dtype),
            jnp.moveaxis(dk, 1, 2).astype(k.dtype),
            jnp.moveaxis(dv, 1, 2).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_lse(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_diff_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_lse(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_diff_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    return _flash_bwd_xla(q, k, v, out, lse, do, causal, block_k)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Pallas TPU flash attention, DIFFERENTIABLE: the forward is the
    Pallas online-softmax kernel (score tile only in VMEM) and the
    backward is the standard flash recomputation as a pure-XLA k-block
    scan driven by the kernel's saved logsumexp. Same contract as
    `dense_attention`. `interpret=True` runs the forward kernel on CPU
    for tests."""
    return _flash_diff(q, k, v, causal, block_q, block_k, interpret)


# --------------------------------------------------------------------- #
# param-compatible self-attention module                                #
# --------------------------------------------------------------------- #

class SelfAttention(nn.Module):
    """Multi-head self-attention with a selectable attention core.

    Parameter tree is IDENTICAL to flax's nn.MultiHeadDotProductAttention
    (submodules query/key/value/out with the same DenseGeneral layouts) so
    checkpoints, the serialize registry, and the HF import spec
    (import_weights.TRANSFORMER_SPEC -> params/attn_i/query/kernel ...)
    are impl-agnostic.

    impl: "dense" (reference math), "chunked" (O(T) scan, differentiable),
    "flash" (Pallas kernel on TPU, differentiable via custom_vjp; off-TPU
    it transparently uses the chunked tier so the same model file runs
    everywhere).
    """

    num_heads: int
    dtype: Any = jnp.float32
    impl: str = "dense"
    causal: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(f"d_model={d_model} not divisible by "
                             f"num_heads={self.num_heads}")
        head_dim = d_model // self.num_heads
        proj = functools.partial(
            nn.DenseGeneral, features=(self.num_heads, head_dim),
            dtype=self.dtype)
        q = proj(name="query")(x)
        k = proj(name="key")(x)
        v = proj(name="value")(x)

        impl = self.impl
        if impl == "flash" and jax.default_backend() != "tpu":
            impl = "chunked"
        if impl == "dense":
            out = dense_attention(q, k, v, causal=self.causal)
        elif impl == "chunked":
            out = chunked_attention(q, k, v, causal=self.causal)
        elif impl == "flash":
            out = flash_attention(q, k, v, causal=self.causal)
        else:
            raise ValueError(f"unknown attention impl {self.impl!r}")
        return nn.DenseGeneral(features=d_model, axis=(-2, -1),
                               dtype=self.dtype, name="out")(out)
