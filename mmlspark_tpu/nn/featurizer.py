"""ImageFeaturizer — transfer-learning featurization via a truncated forward.

Reference: `ImageFeaturizer` (src/image-featurizer/src/main/scala/
ImageFeaturizer.scala:36-189): resize → CHW unroll (`UnrollImage`) → CNTKModel
with the output node chosen by `layerNames(cutOutputLayers)` (:92-135).
TPU redesign: resize is `jax.image.resize` fused into the same jit program
as the forward pass; the "cut" output is a flax captured intermediate
addressed by layer path — no graph surgery on a serialized model.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import Param
from ..core.pipeline import Model
from ..core.schema import SCORE_KIND, Table
from ..core.serialize import register_stage
from .models import ModelBundle
from .runner import DeepModelTransformer

__all__ = ["ImageFeaturizer"]


@register_stage
class ImageFeaturizer(DeepModelTransformer):
    """Featurize images with a truncated pretrained model.

    cut_output_layers=0 returns final logits (head on); >=1 returns the
    pooled features / deeper intermediate, counting back from the head —
    matching the reference's cutOutputLayers semantics
    (ImageFeaturizer.scala:92-135)."""

    cut_output_layers = Param(1, "how many layers to cut from the output", ptype=int)
    layer_name = Param(None, "explicit layer path (overrides cut_output_layers)", ptype=str)
    output_col = Param("features_out", "featurized output column", ptype=str)
    resize_to = Param(None, "(h, w) to resize inputs to the model's input size")

    def _fetch_name(self) -> str:
        if self.get("layer_name"):
            return self.get("layer_name")
        cut = int(self.get("cut_output_layers"))
        if cut <= 0:
            return "logits"
        names = self.bundle.layer_names()
        if not names:
            return "logits"
        # cut=k drops the last k layers: cut=1 skips the head and returns
        # the layer feeding it (reference cutOutputLayers default)
        idx = max(len(names) - 1 - cut, 0)
        return names[idx]

    def _transform(self, table: Table) -> Table:
        if self.bundle is None:
            raise ValueError("ImageFeaturizer has no model; call set_model()")
        col = table[self.get("input_col")]
        x = np.stack(col) if isinstance(col, list) else np.asarray(col)
        target = self.get("resize_to") or self.bundle.input_shape[:2]
        if target and tuple(x.shape[1:3]) != tuple(target):
            th, tw = int(target[0]), int(target[1])
            x = np.asarray(
                jax.image.resize(
                    jnp.asarray(x, jnp.float32),
                    (x.shape[0], th, tw, x.shape[3]),
                    method="bilinear",
                )
            )
        tmp = table.with_column(self.get("input_col"), x)
        self.set(fetch_dict={self.get("output_col"): self._fetch_name()})
        out = DeepModelTransformer._transform(self, tmp)
        # restore the original image column; flatten features to (n, d)
        feats = np.asarray(out[self.get("output_col")])
        if feats.ndim > 2:
            feats = feats.reshape(feats.shape[0], -1)
        return (
            out.with_column(self.get("input_col"), table[self.get("input_col")])
            .with_column(self.get("output_col"), feats.astype(np.float64))
            .with_meta(self.get("output_col"), {SCORE_KIND: "features"})
        )

    def device_kernel(self):
        """Fusion kernel: resize -> truncated forward -> flatten as ONE
        device program (the staged path already computes the resize and
        forward in float32, so the float64 output cast after read-back is
        an exact widening — fused and staged bytes match)."""
        from ..core.fusion import DeviceKernel

        if self.bundle is None:
            return "no model bundle attached (call set_model())"
        if self.get("use_mesh"):
            return "mesh-sharded apply manages its own device placement"
        in_col = self.get("input_col")
        out_col = self.get("output_col")
        forward = self._forward_fn((self._fetch_name(),))
        target = self.get("resize_to") or self.bundle.input_shape[:2]

        def fn(params, cols):
            x = cols[in_col].astype(jnp.float32)
            if target and tuple(x.shape[1:3]) != tuple(target):
                th, tw = int(target[0]), int(target[1])
                x = jax.image.resize(
                    x, (x.shape[0], th, tw, x.shape[3]), method="bilinear")
            (feats,) = forward(params, x)
            if feats.ndim > 2:
                feats = feats.reshape(feats.shape[0], -1)
            return {out_col: feats}

        def ready(table: Table):
            col = table[in_col]
            if not (isinstance(col, np.ndarray) and col.ndim == 4):
                return f"column {in_col!r} is not a uniform NHWC batch"
            return True

        return DeviceKernel(
            fn=fn, input_cols=(in_col,), output_cols=(out_col,),
            params=self._device_variables(), name="ImageFeaturizer",
            out_dtypes={out_col: np.float64},
            out_meta={out_col: {SCORE_KIND: "features"}}, ready=ready)
