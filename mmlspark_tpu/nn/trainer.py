"""DNNLearner — in-process SPMD deep-model training.

Reference: `CNTKLearner` (src/cntk-train/src/main/scala/CNTKLearner.scala:
85-234) trains OUT-OF-BAND: data staged to HDFS, scp'd to GPU hosts, then
`mpirun cntk configFile=...` over an ssh ring (CommandBuilders.scala:149-267).
TPU redesign: none of that exists. Training is one jit-compiled train step
over a `jax.sharding.Mesh` — batch sharded on the data axis, variables
replicated — and XLA inserts the gradient all-reduce on ICI automatically
(the pjit data-parallel recipe). Multi-host = same program under
`jax.distributed.initialize` (parallel/mesh.py), no hostfiles or ssh.

Checkpoint/resume: flax-serialized snapshots through
`resilience.elastic.TrainingCheckpointer` (atomic, blake2b-verified,
manifest + retention) — the parity for brainscript's model snapshots
(BrainscriptBuilder.scala:16-151 output config), hardened for
preemptible fleets. The cursor is (epoch, batch): end-of-epoch
checkpoints store (epoch+1, 0); a PreemptionGuard drain mid-epoch on
the streamed path stores (epoch, step+1), and resume replays the numpy
shuffle stream and per-step fold_in positions so the resumed fit is
byte-identical to an uninterrupted one on the same mesh.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.dataplane import Prefetcher
from ..core.params import HasFeaturesCol, HasLabelCol, Param
from ..core.pipeline import Estimator, Model
from ..core.schema import SCORE_KIND, Table
from ..core.serialize import register_stage
from ..observability.tracing import get_tracer
from ..parallel.mesh import DATA_AXIS, get_mesh
from .models import ModelBundle
from .runner import DeepModelTransformer

__all__ = ["DNNLearner", "DNNModel"]


_OPTIMIZERS: dict[str, Callable[..., optax.GradientTransformation]] = {
    "adam": optax.adam,
    "adamw": optax.adamw,
    "sgd": optax.sgd,
    "momentum": lambda lr: optax.sgd(lr, momentum=0.9),
    "rmsprop": optax.rmsprop,
}


@register_stage
class DNNLearner(HasFeaturesCol, HasLabelCol, Estimator):
    """Fit a deep model on a Table (the CNTKLearner surface, in-process)."""

    architecture = Param("mlp", "architecture name (nn.models.ARCHITECTURES)", ptype=str)
    model_config = Param({}, "architecture config kwargs")
    loss = Param("softmax_ce", "softmax_ce | mse", ptype=str)
    optimizer = Param("adam", "adam|adamw|sgd|momentum|rmsprop", ptype=str)
    learning_rate = Param(1e-3, "base learning rate", ptype=float)
    epochs = Param(5, "epochs over the table", ptype=int)
    batch_size = Param(128, "global batch size", ptype=int)
    use_mesh = Param(True, "data-parallel over the mesh data axis", ptype=bool)
    seed = Param(0, "init + shuffle seed", ptype=int)
    checkpoint_dir = Param(None, "epoch checkpoint directory (resume if present)", ptype=str)
    checkpoint_every_n = Param(1, "checkpoint every N epochs (needs checkpoint_dir)", ptype=int)
    init_bundle_path = Param(None, "warm start from a saved ModelBundle", ptype=str)
    bfloat16 = Param(True, "compute in bfloat16 (f32 params)", ptype=bool)
    # jax.checkpoint over the forward: activations are recomputed in the
    # backward pass instead of stored — HBM for FLOPs, the standard lever
    # for training bigger batches per chip (SURVEY "HBM bandwidth" stance)
    remat = Param(False, "rematerialize the forward in the backward pass", ptype=bool)

    # optional: transfer learning — freeze all but these param path prefixes
    trainable_prefixes = Param(None, "list of param path prefixes to train (None=all)")
    # One dispatch per EPOCH (jitted lax.scan over minibatches on
    # device-resident data) instead of one per step — per-dispatch latency
    # dominates small-table training when the device is remote. Gated by a
    # memory budget; over-budget tables stream batch-by-batch.
    fused_epochs = Param(True, "scan a whole epoch in one dispatch", ptype=bool)
    fused_epoch_budget_mb = Param(
        512, "max table MB resident on device for the fused epoch path", ptype=int
    )
    # Streamed (non-fused) epochs: gather + upload of minibatch N+1 and its
    # fold_in rng overlap the device's train step on minibatch N. Safe with
    # donate_argnums=(0,1,2): only params/batch_stats/opt_state are donated,
    # never the prefetched batch buffers. Batch order and per-step rngs are
    # depth-invariant, so training is bit-identical at any depth.
    prefetch_depth = Param(
        2, "minibatches prepared ahead in the streamed epoch loop (0 = sync)",
        ptype=int,
    )

    # Elastic data-parallel fit over ServingFleet worker PROCESSES
    # (resilience/elastic_fleet.py): the driver owns the batch order and
    # optimizer, workers own gradient shards, and the fleet may grow or
    # shrink mid-fit without changing the resulting model's bytes.
    elastic_workers = Param(
        0, "fit data-parallel over N elastic fleet workers (0 = in-process)",
        ptype=int,
    )
    elastic_num_virtual = Param(
        32, "virtual shards for the elastic fit (fixes the gradient merge "
        "order independently of the live worker count)", ptype=int,
    )

    init_bundle: ModelBundle | None = None  # programmatic warm start

    def _fit(self, table: Table) -> "DNNModel":
        if int(self.get("elastic_workers") or 0) > 0:
            if self.init_bundle is not None or self.get("init_bundle_path"):
                raise ValueError(
                    "elastic_workers does not support warm starts "
                    "(init_bundle / init_bundle_path)")
            if self.get("trainable_prefixes"):
                raise ValueError(
                    "elastic_workers does not support trainable_prefixes")
            from ..resilience.elastic_fleet import elastic_fit_dnn

            return elastic_fit_dnn(self, table)
        x_col = table[self.get("features_col")]
        x = np.stack(x_col) if isinstance(x_col, list) else np.asarray(x_col)
        y = np.asarray(table[self.get("label_col")])
        n = x.shape[0]
        # max+1, NOT unique-count: a CV fold may lack the highest class, and
        # non-contiguous labels (0,2) need a head wide enough for label 2
        num_classes = int(y.max()) + 1 if self.get("loss") == "softmax_ce" else 1

        bundle = self._initial_bundle(x, num_classes)
        mesh = get_mesh() if self.get("use_mesh") else None
        tx = _OPTIMIZERS[self.get("optimizer")](self.get("learning_rate"))

        params = bundle.variables.get("params", bundle.variables)
        batch_stats = bundle.variables.get("batch_stats", {})
        frozen_mask = self._trainable_mask(params)
        if frozen_mask is not None:
            tx = optax.multi_transform(
                {"train": tx, "freeze": optax.set_to_zero()}, frozen_mask
            )
        opt_state = tx.init(params)
        module = bundle.module
        loss_kind = self.get("loss")
        has_bn = bool(batch_stats)

        use_remat = bool(self.get("remat"))

        def _apply_bn(params, batch_stats, bx, step_rng):
            out, updates = module.apply(
                {"params": params, "batch_stats": batch_stats}, bx,
                train=True, mutable=["batch_stats"],
                rngs={"dropout": step_rng},
            )
            return out, updates["batch_stats"]

        def _apply_plain(params, bx, step_rng):
            return module.apply({"params": params}, bx, train=True,
                                rngs={"dropout": step_rng})

        if use_remat:
            _apply_bn = jax.checkpoint(_apply_bn)
            _apply_plain = jax.checkpoint(_apply_plain)

        def loss_fn(params, batch_stats, bx, by, step_rng):
            # a dropout rng is always supplied (flax ignores unused rngs),
            # so stochastic-regularization models train without special
            # casing; deterministic models are unaffected
            if has_bn:
                logits, new_stats = _apply_bn(params, batch_stats, bx, step_rng)
            else:
                logits = _apply_plain(params, bx, step_rng)
                new_stats = batch_stats
            if loss_kind == "softmax_ce":
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), by.astype(jnp.int32)
                ).mean()
            else:
                loss = jnp.mean((logits.squeeze(-1) - by.astype(jnp.float32)) ** 2)
            return loss, new_stats

        def train_step(params, batch_stats, opt_state, bx, by, step_rng):
            (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch_stats, bx, by, step_rng
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, new_stats, opt_state, loss

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            data = NamedSharding(mesh, P(DATA_AXIS))
            step = jax.jit(
                train_step,
                in_shardings=(repl, repl, repl, data, data, repl),
                out_shardings=(repl, repl, repl, repl),
                donate_argnums=(0, 1, 2),
            )
        else:
            step = jax.jit(train_step, donate_argnums=(0, 1, 2))
        base_rng = jax.random.PRNGKey(int(self.get("seed")) + 1)

        bs = int(self.get("batch_size"))
        bs = min(bs, n)  # small tables: never a zero-step epoch
        if mesh is not None:
            d = mesh.shape[DATA_AXIS]
            bs = max((bs // d) * d, d)
        rng = np.random.default_rng(self.get("seed"))
        ckpt = self._checkpointer()
        (start_epoch, start_batch, params, batch_stats,
         opt_state) = self._maybe_resume(ckpt, params, batch_stats, opt_state)
        # replay the shuffle stream for completed epochs: the epoch we
        # resume into must draw the same permutation it drew originally,
        # or the resumed fit diverges from the uninterrupted one
        for _ in range(start_epoch):
            rng.permutation(n)

        steps = (n - bs) // bs + 1 if n >= bs else 0
        fused = (
            bool(self.get("fused_epochs"))
            and steps > 1
            and x.nbytes + y.nbytes
            <= int(self.get("fused_epoch_budget_mb")) * 2**20
        )
        epoch_fn = None
        if fused:
            # whole table resident on device (replicated under a mesh so the
            # per-step gather by shuffled global index stays local); batches
            # re-shard onto the data axis inside the scan
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                repl = NamedSharding(mesh, P())
                xd = jax.device_put(x, repl)
                yd = jax.device_put(y, repl)
                data_spec = NamedSharding(mesh, P(DATA_AXIS))
            else:
                xd, yd = jnp.asarray(x), jnp.asarray(y)
                data_spec = None

            def epoch_body(carry, xs):
                p, bst, os_ = carry
                idx, step_rng = xs
                bx, by = xd[idx], yd[idx]
                if data_spec is not None:
                    bx = jax.lax.with_sharding_constraint(bx, data_spec)
                    by = jax.lax.with_sharding_constraint(by, data_spec)
                p, bst, os_, loss = train_step(p, bst, os_, bx, by, step_rng)
                return (p, bst, os_), loss

            def run_epoch(params, batch_stats, opt_state, order, epoch_rng):
                # fold_in(k) matches the per-step loop path exactly, so a
                # dropout model trains identically fused or streamed
                keys = jax.vmap(
                    lambda i: jax.random.fold_in(epoch_rng, i)
                )(jnp.arange(order.shape[0]))
                (p, bst, os_), losses = jax.lax.scan(
                    epoch_body, (params, batch_stats, opt_state), (order, keys)
                )
                return p, bst, os_, losses.mean()

            epoch_fn = jax.jit(run_epoch, donate_argnums=(0, 1, 2))

        from ..resilience.elastic import preempt_now

        log = self._log()
        tracer = get_tracer()
        for epoch in range(start_epoch, int(self.get("epochs"))):
            # a mid-epoch cursor can only come from the streamed path, so
            # the resumed-into epoch streams even when fusion is on — the
            # two paths fold the same per-step rng at the same positions
            resume_k = start_batch if epoch == start_epoch else 0
            use_fused = fused and not resume_k
            with tracer.start_span("trainer.epoch", epoch=epoch,
                                   fused=use_fused, steps=steps) as ep_span:
                order = rng.permutation(n)
                # drop the ragged tail (shuffled: all rows seen across
                # epochs); XLA compiles one batch shape
                epoch_rng = jax.random.fold_in(base_rng, epoch)
                if use_fused:
                    idx = jnp.asarray(
                        order[: steps * bs].reshape(steps, bs), jnp.int32
                    )
                    params, batch_stats, opt_state, mean_loss = epoch_fn(
                        params, batch_stats, opt_state, idx, epoch_rng
                    )
                    mean_loss = float(mean_loss)
                else:
                    def prep(ki, _order=order, _rng=epoch_rng):
                        k, i = ki
                        idx = _order[i : i + bs]
                        return (k, jnp.asarray(x[idx]), jnp.asarray(y[idx]),
                                jax.random.fold_in(_rng, k))

                    losses = []
                    for k, bx, by, step_rng in Prefetcher(
                        itertools.islice(
                            enumerate(range(0, n - bs + 1, bs)),
                            resume_k, None),
                        prep,
                        depth=int(self.get("prefetch_depth")), name="trainer",
                    ):
                        params, batch_stats, opt_state, loss = step(
                            params, batch_stats, opt_state, bx, by, step_rng
                        )
                        losses.append(loss)
                        preempt_now(
                            None,
                            lambda: self._maybe_checkpoint(
                                ckpt, epoch, k + 1, params, batch_stats,
                                opt_state, force=True),
                            "dnn-train")
                    mean_loss = (
                        float(jnp.mean(jnp.stack(losses)))
                        if losses else float("nan")
                    )
                ep_span.set(loss=mean_loss)
                if log:
                    log(f"epoch {epoch + 1}/{self.get('epochs')}: "
                        f"loss={mean_loss:.4f}")
                self._maybe_checkpoint(
                    ckpt, epoch + 1, 0, params, batch_stats, opt_state)
                preempt_now(
                    None,
                    lambda: self._maybe_checkpoint(
                        ckpt, epoch + 1, 0, params, batch_stats, opt_state,
                        force=True),
                    "dnn-train")

        variables = {"params": jax.device_get(params)}
        if has_bn:
            variables["batch_stats"] = jax.device_get(batch_stats)
        bundle.variables = variables
        model = DNNModel(
            features_col=self.get("features_col"),
            prediction_col="prediction",
        )
        model.set_bundle(bundle, classifier=loss_kind == "softmax_ce")
        return model

    # ------------------------------------------------------------------ #

    def _initial_bundle(self, x: np.ndarray, num_classes: int) -> ModelBundle:
        path = self.get("init_bundle_path")
        if self.init_bundle is not None:
            import dataclasses

            # DEEP copy of the variable arrays: the train step donates its
            # param buffers, and a shallow copy would let that donation
            # delete the caller's bundle arrays ("Array has been deleted"
            # on any later use of the warm-start bundle)
            fresh = jax.tree.map(jnp.array, self.init_bundle.variables)
            return dataclasses.replace(self.init_bundle, variables=fresh)
        if path:
            return ModelBundle.load(path)
        cfg = dict(self.get("model_config"))
        cfg.setdefault("num_outputs", max(num_classes, 1))
        if self.get("bfloat16"):
            cfg.setdefault("dtype", jnp.bfloat16)
        return ModelBundle.init(
            self.get("architecture"), x.shape[1:], seed=self.get("seed"), **cfg
        )

    def _trainable_mask(self, params):
        """Pytree of {"train","freeze"} labels for optax.multi_transform —
        the reference's transfer-learning layer cut (ImageFeaturizer
        cutOutputLayers) expressed as frozen parameter subtrees."""
        prefixes = self.get("trainable_prefixes")
        if not prefixes:
            return None

        def build(tree, prefix=""):
            if isinstance(tree, dict):
                return {k: build(v, f"{prefix}.{k}" if prefix else k)
                        for k, v in tree.items()}
            return "train" if any(prefix.startswith(p) for p in prefixes) else "freeze"

        return build(params)

    def _checkpointer(self):
        d = self.get("checkpoint_dir")
        if not d:
            return None
        from ..resilience.elastic import TrainingCheckpointer

        return TrainingCheckpointer(d)

    def _state_template(self, params, batch_stats, opt_state) -> dict:
        return {
            "epoch": 0,
            "batch": 0,
            "params": jax.device_get(params),
            "batch_stats": jax.device_get(batch_stats),
            "opt_state": jax.device_get(opt_state),
        }

    def _maybe_checkpoint(self, ckpt, epoch, batch, params, batch_stats,
                          opt_state, force: bool = False) -> "str | None":
        """Snapshot the resume cursor (epoch, batch) + full f32 training
        state. Cursor semantics: resume AT epoch, AT batch — end-of-epoch
        writes (epoch+1, 0), a mid-epoch drain writes (epoch, step+1)."""
        if ckpt is None:
            return None
        every = max(int(self.get("checkpoint_every_n")), 1)
        if not force and (batch != 0 or epoch % every != 0):
            return None
        from flax import serialization

        state = self._state_template(params, batch_stats, opt_state)
        state.update(epoch=int(epoch), batch=int(batch))
        tag = f"epoch-{epoch:04d}" + (f"-step-{batch:05d}" if batch else "")
        return ckpt.save(serialization.to_bytes(state), tag=tag,
                         meta={"epoch": int(epoch), "batch": int(batch),
                               "seed": int(self.get("seed"))})

    def _maybe_resume(self, ckpt, params, batch_stats, opt_state):
        if ckpt is None:
            return 0, 0, params, batch_stats, opt_state
        loaded = ckpt.load_latest()
        if loaded is None:
            return 0, 0, params, batch_stats, opt_state
        payload, entry = loaded
        log = self._log()
        meta = entry.get("meta") or {}
        if "seed" in meta and int(meta["seed"]) != int(self.get("seed")):
            if log:
                log(f"ignoring checkpoint {entry['file']}: "
                    f"seed {meta['seed']} != {self.get('seed')}")
            return 0, 0, params, batch_stats, opt_state
        from flax import serialization

        state = serialization.from_bytes(
            self._state_template(params, batch_stats, opt_state), payload)
        if log:
            log(f"resuming from {entry['file']} at epoch "
                f"{state['epoch']} batch {state['batch']}")
        return (int(state["epoch"]), int(state["batch"]), state["params"],
                state["batch_stats"], state["opt_state"])

    def _log(self):
        import logging

        logger = logging.getLogger("mmlspark_tpu.nn")
        return logger.info


@register_stage
class DNNModel(DeepModelTransformer):
    """Fitted DNNLearner output: DeepModelTransformer + argmax prediction."""

    prediction_col = Param("prediction", "predicted label column", ptype=str)
    classifier = Param(True, "argmax labels (vs raw regression output)", ptype=bool)

    features_col = Param("features", "input features column", ptype=str)

    def set_bundle(self, bundle: ModelBundle, classifier: bool = True) -> "DNNModel":
        self.set_model(bundle)
        self.set(input_col=self.get("features_col"), classifier=classifier)
        return self

    def _transform(self, table: Table) -> Table:
        self.set(input_col=self.get("features_col"))
        if self.get("classifier"):
            self.set(fetch_dict={"probability": "probability", "raw_prediction": "logits"})
        else:
            self.set(fetch_dict={self.get("prediction_col"): "logits"})
        out = DeepModelTransformer._transform(self, table)
        if self.get("classifier"):
            prob = np.asarray(out["probability"])
            labels = np.argmax(prob, axis=-1).astype(np.float64)
            out = out.with_column(
                self.get("prediction_col"), labels,
                meta={SCORE_KIND: "predicted_label"},
            )
        else:
            arr = np.asarray(out[self.get("prediction_col")])
            if arr.ndim == 2 and arr.shape[1] == 1:
                out = out.with_column(
                    self.get("prediction_col"), arr[:, 0],
                    meta={SCORE_KIND: "prediction"},
                )
        return out
