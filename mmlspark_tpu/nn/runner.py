"""DeepModelTransformer — jit-compiled batched DNN inference as a pipeline
stage.

Reference: `CNTKModel` (src/cntk-model/src/main/scala/CNTKModel.scala:147-516)
— feedDict/fetchDict params (:206-225), FixedMiniBatchTransformer batching
(:475-479), per-partition model clone + per-row `model.evaluate` JNI calls
(:30-141). TPU redesign: the model's variables live in device memory ONCE
(not re-cloned per partition, CNTKModel.scala:83), the forward pass is one
jit-compiled program per batch shape, and rows are processed in fixed-size
minibatches padded to a static shape so XLA compiles exactly once. With a
mesh, inference runs data-parallel: batch sharded over DATA_AXIS, variables
replicated.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataplane import (AsyncReadback, ExecutableCache, Prefetcher,
                              ShapeBucketer)
from ..core.params import Param
from ..core.pipeline import Model
from ..core.schema import SCORE_KIND, Table
from ..core.serialize import register_stage
from ..observability.tracing import get_tracer
from ..parallel.mesh import DATA_AXIS, get_mesh
from .models import ModelBundle

__all__ = ["DeepModelTransformer"]


def _fetch_from_intermediates(state: dict, path: str):
    node: Any = state["intermediates"]
    for part in path.split("."):
        node = node[part]
    if isinstance(node, dict):
        node = node["__call__"]
    if isinstance(node, (tuple, list)):
        node = node[0]
    return node


@register_stage
class DeepModelTransformer(Model):
    """Batched forward pass of a ModelBundle over a Table column.

    fetch_dict maps output column -> "logits" | "probability" |
    "<intermediate path>" (a layer name from bundle.layer_names())."""

    input_col = Param("features", "input column (stacked to (n, ...))", ptype=str)
    fetch_dict = Param(
        {"output": "logits"}, "output column -> logits|probability|<layer path>"
    )
    mini_batch_size = Param(64, "rows per compiled device batch", ptype=int)
    use_mesh = Param(False, "shard batches over the data mesh axis", ptype=bool)
    # One host->device transfer + ONE dispatch for the whole table (a jitted
    # lax.scan over minibatches) instead of one dispatch per minibatch.
    # Per-dispatch latency dominates batched transforms when the device is
    # remote (the reference pays the same cost per JNI evaluate call,
    # CNTKModel.scala:131-138); bounded by fused_dispatch_budget_mb so huge
    # tables still stream batch-by-batch.
    fused_dispatch = Param(True, "scan all minibatches in one dispatch", ptype=bool)
    fused_dispatch_budget_mb = Param(
        512, "max input MB eligible for the fused single-dispatch path", ptype=int
    )
    bfloat16 = Param(
        False, "run the forward in bfloat16 (MXU-native; outputs stay float32)",
        ptype=bool,
    )
    # Async data plane (non-fused path): a bounded background thread
    # featurizes/pads/uploads minibatch N+1 while the device computes
    # minibatch N, and host readback of minibatch N-1 overlaps both.
    # Depth 0 is the strictly sequential fallback — outputs are
    # byte-identical at any depth (shapes and order never change).
    prefetch_depth = Param(
        2, "minibatches prepared ahead of device compute (0 = sequential)",
        ptype=int,
    )
    # Ragged tails pad to a power-of-two bucket ladder (<= mini_batch_size)
    # instead of all the way up to mini_batch_size: less wasted tail
    # compute, and the compiled-shape set stays a small closed ladder.
    shape_buckets = Param(
        True, "pad ragged tails to a pow-2 bucket ladder (vs full batch)",
        ptype=bool,
    )

    bundle: ModelBundle | None = None
    _apply_cache: dict | None = None
    _outbytes_cache: dict | None = None
    _exec_cache: ExecutableCache | None = None
    #: stats from the most recent pipelined (non-fused) _transform:
    #: prepare/wait seconds, overlap_fraction, executable-cache counters
    last_pipeline_stats: dict | None = None

    def set_model(self, bundle: ModelBundle) -> "DeepModelTransformer":
        self.bundle = bundle
        self._apply_cache = {}
        self._outbytes_cache = {}
        self._exec_cache = ExecutableCache()
        return self

    # ------------------------------------------------------------------ #

    def _forward_fn(self, fetches: tuple[str, ...]):
        bundle = self.bundle
        module = bundle.module
        need_caps = any(f not in ("logits", "probability") for f in fetches)
        mean = np.asarray(bundle.preprocess.get("mean", 0.0), np.float32)
        std = np.asarray(bundle.preprocess.get("std", 1.0), np.float32)
        use_bf16 = bool(self.get("bfloat16"))

        def forward(variables, x):
            x = (x.astype(jnp.float32) - mean) / std
            if use_bf16:
                x = x.astype(jnp.bfloat16)
            if need_caps:
                logits, state = module.apply(
                    variables, x, train=False,
                    capture_intermediates=True, mutable=["intermediates"],
                )
            else:
                logits = module.apply(variables, x, train=False)
                state = None
            logits = logits.astype(jnp.float32)
            outs = []
            for f in fetches:
                if f == "logits":
                    outs.append(logits)
                elif f == "probability":
                    outs.append(jax.nn.softmax(logits, axis=-1))
                else:
                    outs.append(
                        _fetch_from_intermediates(state, f).astype(jnp.float32)
                    )
            return tuple(outs)

        return forward

    def _make_apply(self, fetches: tuple[str, ...]):
        forward = self._forward_fn(fetches)
        if self.get("use_mesh"):
            mesh = get_mesh()
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            data = NamedSharding(mesh, P(DATA_AXIS))
            return jax.jit(forward, in_shardings=(repl, data),
                           out_shardings=repl)
        return jax.jit(forward)

    def _make_apply_fused(self, fetches: tuple[str, ...]):
        """Jit of scan(forward) over (nb, bs, ...) — whole table, one dispatch."""
        forward = self._forward_fn(fetches)

        def scanned(variables, xall):
            def body(_, xb):
                return 0, forward(variables, xb)

            _, outs = jax.lax.scan(body, 0, xall)
            return outs                                # tuple of (nb, bs, ...)

        if self.get("use_mesh"):
            mesh = get_mesh()
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            data = NamedSharding(mesh, P(None, DATA_AXIS))
            return jax.jit(scanned, in_shardings=(repl, data),
                           out_shardings=repl)
        return jax.jit(scanned)

    def _transform(self, table: Table) -> Table:
        if self.bundle is None:
            raise ValueError("DeepModelTransformer has no model; call set_model()")
        col = table[self.get("input_col")]
        x = np.stack(col) if isinstance(col, list) else np.asarray(col)
        n = x.shape[0]
        fetch = dict(self.get("fetch_dict"))
        fetches = tuple(fetch.values())

        bs = int(self.get("mini_batch_size"))
        d = 1
        if self.get("use_mesh"):
            d = get_mesh().shape[DATA_AXIS]
            bs = ((bs + d - 1) // d) * d

        pad = (-n) % bs
        fused = bool(self.get("fused_dispatch"))
        if fused:
            # the fused scan holds the inputs AND every fetched output for
            # the WHOLE table on device at once — a narrow input with a wide
            # intermediate fetch can dwarf x.nbytes, so budget both sides.
            # The per-batch output size is an eval_shape (abstract trace);
            # cache it so per-request transforms (serving) don't re-trace
            # the model just to size its outputs.
            if self._outbytes_cache is None:
                self._outbytes_cache = {}
            okey = (fetches, bs, x.shape[1:], str(x.dtype), id(self.bundle))
            if okey not in self._outbytes_cache:
                out_abs = jax.eval_shape(
                    self._forward_fn(fetches),
                    self.bundle.variables,
                    jax.ShapeDtypeStruct((bs, *x.shape[1:]), x.dtype),
                )
                self._outbytes_cache[okey] = sum(
                    int(np.prod(o.shape)) * o.dtype.itemsize for o in out_abs
                )
            per_batch = self._outbytes_cache[okey]
            row_bytes = x.nbytes // n if n else 0
            total = row_bytes * (n + pad) + per_batch * ((n + pad) // bs)
            fused = total <= int(self.get("fused_dispatch_budget_mb")) * 2**20

        if self._apply_cache is None:
            self._apply_cache = {}
        # id(bundle) in the key: assigning a new bundle directly (without
        # set_model) must not score with stale cached/cast weights
        key = (fetches, bs, self.get("use_mesh"),
               self.get("bfloat16"), id(self.bundle), fused)
        if key not in self._apply_cache:
            variables = self.bundle.variables
            if self.get("bfloat16"):
                # cast weights ONCE; per-call casting would re-upload them
                variables = jax.tree.map(
                    lambda a: a.astype(jnp.bfloat16)
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
                    variables,
                )
            make = self._make_apply_fused if fused else self._make_apply
            self._apply_cache[key] = (make(fetches), variables)
        apply_fn, variables = self._apply_cache[key]

        if fused:
            if pad:
                x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)])
            nb = len(x) // bs
            outs = apply_fn(variables, jnp.asarray(x.reshape(nb, bs, *x.shape[1:])))
            cols = [np.asarray(o).reshape(nb * bs, *o.shape[2:])[:n] for o in outs]
        else:
            cols = self._transform_pipelined(x, bs, d, key, apply_fn, variables,
                                             fetches)

        out = table
        for (col_name, fetch_name), arr in zip(fetch.items(), cols):
            kind = "probability" if fetch_name == "probability" else "raw_prediction"
            out = out.with_column(col_name, arr, meta={SCORE_KIND: kind})
        return out

    def _transform_pipelined(self, x: np.ndarray, bs: int, d: int, family,
                             apply_fn, variables,
                             fetches: tuple[str, ...]) -> list[np.ndarray]:
        """Non-fused loop on the async data plane: prepare (slice + pad +
        upload) of minibatch N+1 overlaps device compute on N, and host
        readback lags one batch so it overlaps too. Shapes, batch order,
        and per-row outputs are identical at every prefetch depth."""
        n = x.shape[0]
        bucketer = (ShapeBucketer(bs, shards=d)
                    if self.get("shape_buckets") else None)
        if self._exec_cache is None:
            self._exec_cache = ExecutableCache()

        def prepare(i: int):
            chunk = x[i:i + bs]
            m = chunk.shape[0]
            if bucketer is not None:
                padded, _ = bucketer.pad(chunk)
            elif m < bs:
                padded = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], bs - m, axis=0)])
            else:
                padded = chunk
            return jnp.asarray(padded), m

        prefetch = Prefetcher(range(0, n, bs), prepare,
                              depth=int(self.get("prefetch_depth")),
                              name="runner")
        # fetch = block on the device result and slice the padding off;
        # lag 1 keeps batch N-1's readback behind batch N's dispatch
        readback = AsyncReadback(
            lambda om: tuple(np.asarray(a)[:om[1]] for a in om[0]), lag=1)
        chunks: list[tuple[np.ndarray, ...]] = []
        tracer = get_tracer()
        with tracer.start_span("runner.transform", rows=n, batch_size=bs):
            for xb, m in prefetch:
                shape_key = (int(xb.shape[0]), tuple(xb.shape[1:]),
                             str(xb.dtype))
                with tracer.start_span("runner.step", padded=int(xb.shape[0]),
                                       rows=m):
                    # jit compiles once per entry here; the counters make
                    # ragged shapes defeating the ladder visible
                    # (recompiles > 0)
                    fn = self._exec_cache.get_or_build(family, shape_key,
                                                       lambda: apply_fn)
                    chunks.extend(readback.push((fn(variables, xb), m)))
            chunks.extend(readback.drain())
        self.last_pipeline_stats = {
            **prefetch.stats,
            "overlap_fraction": prefetch.overlap_fraction(),
            "prefetch_depth": prefetch.depth,
            "bucket_ladder": list(bucketer.ladder) if bucketer else [bs],
            **self._exec_cache.stats(),
        }
        return [np.concatenate([c[j] for c in chunks])
                for j in range(len(fetches))]

    # -- fusion --------------------------------------------------------- #

    def _device_variables(self):
        """The bundle's variables as the fusion kernel's device-resident
        params (bfloat16-cast once here, mirroring _apply_cache)."""
        variables = self.bundle.variables
        if self.get("bfloat16"):
            variables = jax.tree.map(
                lambda a: a.astype(jnp.bfloat16)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
                variables,
            )
        return variables

    def _tp_forward_fn(self, fetches: tuple[str, ...], mesh):
        """Column-parallel forward for the fused tensor-parallel path, or
        None when this model can't take it (then the fused engine's default
        — rows sharded, variables replicated — applies).

        Only the hand-rolled MLP layout qualifies: its forward is a chain
        of Dense+relu, which maps exactly onto `gathered_column_parallel`
        (each chip computes a full-contraction slice of the output
        features, then a tiled all_gather reassembles them) — identical
        arithmetic to the unsharded matmul, so byte-identity holds.
        Returns (forward, variable_shardings)."""
        from ..parallel.mesh import MODEL_AXIS
        from ..parallel.tensor_parallel import (dense_column_shardings,
                                                dense_column_specs,
                                                gathered_column_parallel)
        try:
            from jax import shard_map
        except ImportError:  # jax < 0.5: shard_map lives under experimental
            import functools

            from jax.experimental.shard_map import shard_map as _shard_map

            # the old rep-checker cannot see that the tiled all_gather
            # replicates the output over the model axis; new jax proves it
            shard_map = functools.partial(_shard_map, check_rep=False)
        from jax.sharding import PartitionSpec as P

        bundle = self.bundle
        n_model = int(dict(mesh.shape).get(MODEL_AXIS, 1))
        if n_model <= 1:
            return None  # pure data parallelism: nothing to specialize
        if bundle.architecture != "mlp":
            return None
        if any(f not in ("logits", "probability") for f in fetches):
            return None  # intermediate captures need module.apply
        if self.get("bfloat16"):
            return None  # bf16 accumulation order voids byte-identity
        variables = bundle.variables
        if set(variables) != {"params"}:
            return None
        params = variables["params"]
        if "head" not in params:
            return None
        names = sorted((nm for nm in params if nm.startswith("dense_")),
                       key=lambda nm: int(nm.split("_", 1)[1]))
        names.append("head")
        if set(names) != set(params):
            return None
        for nm in names:
            layer = params[nm]
            k, b = layer.get("kernel"), layer.get("bias")
            if (k is None or b is None
                    or np.ndim(k) != 2 or np.ndim(b) != 1
                    or jnp.asarray(k).dtype != jnp.float32):
                return None
            if k.shape[1] % n_model:
                return None  # output features must split evenly

        mean = np.asarray(bundle.preprocess.get("mean", 0.0), np.float32)
        std = np.asarray(bundle.preprocess.get("std", 1.0), np.float32)
        # gather schedule: XLA's monolithic all_gather by default; the
        # hand-scheduled collective-permute ring (same bytes, each step
        # independently schedulable) when the phase ledger showed the
        # gather NOT overlapping compute on this mesh.  bench's TP rung
        # measures both and prints which schedule hides the collective.
        ring = os.environ.get("MMLSPARK_TPU_RING_GATHER", "") == "1"

        def tp_body(variables, x):
            p = variables["params"]
            h = x.reshape((x.shape[0], -1))
            for nm in names:
                h = gathered_column_parallel(
                    h, p[nm]["kernel"], p[nm]["bias"], MODEL_AXIS, ring=ring)
                if nm != "head":
                    h = jax.nn.relu(h)
            return h

        specs = {"params": dense_column_specs(params)}
        body = shard_map(tp_body, mesh=mesh,
                         in_specs=(specs, P(DATA_AXIS, None)),
                         out_specs=P(DATA_AXIS, None))

        def forward(variables, x):
            x = (x.astype(jnp.float32) - mean) / std
            logits = body(variables, x).astype(jnp.float32)
            return tuple(jax.nn.softmax(logits, axis=-1)
                         if f == "probability" else logits
                         for f in fetches)

        shardings = {"params": dense_column_shardings(mesh, params)}
        return forward, shardings

    def device_kernel(self):
        """Fusion kernel (core/fusion.py): the same `_forward_fn` the staged
        path jits, with the variables passed as device-resident params.
        The forward is row-independent (eval mode — no batch statistics),
        so the engine's chunking/padding cannot change any row's value.
        Under a mesh the engine row-shards batches by default; a mesh with
        a >1 model axis additionally swaps in the column-parallel forward
        via `mesh_fn` (weights sharded on output features)."""
        from ..core.fusion import DeviceKernel

        if self.bundle is None:
            return "no model bundle attached (call set_model())"
        fetch = dict(self.get("fetch_dict"))
        fetches = tuple(fetch.values())
        out_cols = tuple(fetch.keys())
        in_col = self.get("input_col")
        forward = self._forward_fn(fetches)

        def fn(params, cols):
            outs = forward(params, cols[in_col])
            return dict(zip(out_cols, outs))

        def ready(table: Table):
            if isinstance(table[in_col], list):
                return f"column {in_col!r} is a ragged list (host stacks it)"
            return True

        def mesh_fn(mesh):
            tp = self._tp_forward_fn(fetches, mesh)
            if tp is None:
                return None
            tp_forward, shardings = tp

            def tp_fn(params, cols):
                outs = tp_forward(params, cols[in_col])
                return dict(zip(out_cols, outs))

            return tp_fn, shardings

        meta = {c: {SCORE_KIND: "probability" if f == "probability"
                    else "raw_prediction"} for c, f in fetch.items()}
        return DeviceKernel(
            fn=fn, input_cols=(in_col,), output_cols=out_cols,
            params=self._device_variables(), name="DeepModelTransformer",
            out_dtypes={c: np.float32 for c in out_cols},
            out_meta=meta, ready=ready, mesh_fn=mesh_fn,
            mesh_desc=("rows P(data); dense kernels column-parallel "
                       "P(None,model) + tiled all_gather when the mesh has "
                       "a >1 model axis, else variables replicated"))

    # -- persistence ---------------------------------------------------- #

    def _save_state(self) -> dict[str, Any]:
        import base64
        import io

        if self.bundle is None:
            return {}
        import tempfile, os

        with tempfile.NamedTemporaryFile(delete=False) as fh:
            tmp = fh.name
        try:
            self.bundle.save(tmp)
            with open(tmp, "rb") as fh2:
                blob = fh2.read()
        finally:
            os.unlink(tmp)
        return {"bundle": base64.b64encode(blob).decode()}

    def _load_state(self, state: dict[str, Any]) -> None:
        import base64
        import os
        import tempfile

        if not state.get("bundle"):
            return
        blob = base64.b64decode(state["bundle"])
        with tempfile.NamedTemporaryFile(delete=False) as fh:
            fh.write(blob)
            tmp = fh.name
        try:
            self.bundle = ModelBundle.load(tmp)
        finally:
            os.unlink(tmp)
        self._apply_cache = {}
        self._exec_cache = ExecutableCache()
