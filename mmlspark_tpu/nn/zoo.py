"""Model zoo: schemas, repository, integrity-checked fetch.

Reference: `ModelDownloader` (src/downloader/src/main/scala/
ModelDownloader.scala:209+) — remote Azure-blob repo → local/HDFS repo, with
`ModelSchema` metadata (uri, hash, size, layerNames, inputNode;
Schema.scala:30+) and `FaultToleranceUtils.retryWithTimeout`
(ModelDownloader.scala:37-46). TPU equivalent: a filesystem repository of
ModelBundle files with sha256 integrity checks; remote sources are any
fsspec-style path (local path or file:// URI; http gated on environment).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from .models import ModelBundle

__all__ = ["ModelSchema", "ModelDownloader", "retry_with_timeout"]


def retry_with_timeout(fn: Callable, timeout_s: float = 60.0, retries: int = 3):
    """Reference: FaultToleranceUtils.retryWithTimeout
    (ModelDownloader.scala:37-46). Each attempt runs in a worker thread and
    is bounded by ``timeout_s`` even if ``fn`` hangs (the reference bounds
    the Await on the future the same way)."""
    import queue as _queue
    import threading

    last: Exception | None = None
    for attempt in range(retries):
        # one daemon thread per attempt: a hung fn neither blocks the caller
        # past timeout_s nor prevents interpreter exit (ThreadPoolExecutor
        # workers are non-daemon and joined at shutdown, so they can't be
        # used here); a timed-out attempt is retried like any other failure
        result_q: _queue.Queue = _queue.Queue(maxsize=1)

        def run(q=result_q):
            try:
                q.put((True, fn()))
            except Exception as e:  # noqa: BLE001 — retry semantics
                q.put((False, e))

        threading.Thread(target=run, daemon=True).start()
        try:
            ok, value = result_q.get(timeout=timeout_s)
        except _queue.Empty:
            last = TimeoutError(
                f"operation exceeded {timeout_s}s (attempt {attempt + 1})"
            )
        else:
            if ok:
                return value
            last = value
        if attempt < retries - 1:
            time.sleep(min(2**attempt, 10))
    raise last  # type: ignore[misc]


@dataclass
class ModelSchema:
    """Metadata for one zoo model (reference Schema.scala:30+)."""

    name: str
    uri: str                         # source path / file:// URI
    sha256: str | None = None
    architecture: str | None = None
    input_shape: tuple[int, ...] = ()
    num_outputs: int | None = None
    class_labels: list | None = None
    extra: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name, "uri": self.uri, "sha256": self.sha256,
            "architecture": self.architecture,
            "input_shape": list(self.input_shape),
            "num_outputs": self.num_outputs,
            "class_labels": self.class_labels, "extra": self.extra,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ModelSchema":
        return ModelSchema(
            name=d["name"], uri=d["uri"], sha256=d.get("sha256"),
            architecture=d.get("architecture"),
            input_shape=tuple(d.get("input_shape", ())),
            num_outputs=d.get("num_outputs"),
            class_labels=d.get("class_labels"), extra=d.get("extra", {}),
        )


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ModelDownloader:
    """Local repository of ModelBundles with integrity checking.

    `local_repo/index.json` lists ModelSchemas; bundle files live next to it.
    `download_model` copies from `schema.uri` (resolving file:// / local
    paths), verifies sha256, and registers the model in the index."""

    def __init__(self, local_repo: str):
        self.local_repo = local_repo
        os.makedirs(local_repo, exist_ok=True)
        self._index_path = os.path.join(local_repo, "index.json")

    # -- index ---------------------------------------------------------- #

    def models(self) -> list[ModelSchema]:
        if not os.path.exists(self._index_path):
            return []
        with open(self._index_path) as fh:
            return [ModelSchema.from_dict(d) for d in json.load(fh)]

    def get_model(self, name: str) -> ModelSchema:
        for s in self.models():
            if s.name == name:
                return s
        raise KeyError(f"model {name!r} not in repo {self.local_repo}")

    def _write_index(self, schemas: list[ModelSchema]) -> None:
        tmp = self._index_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump([s.to_dict() for s in schemas], fh, indent=2)
        os.replace(tmp, self._index_path)

    # -- fetch ---------------------------------------------------------- #

    def local_path(self, name: str) -> str:
        return os.path.join(self.local_repo, f"{name}.model")

    # suffixes a dot-prefixed work file can carry: ".tmp" while fetching,
    # or a bare external-format extension after import_external's rename
    # strips ".tmp" mid-conversion (a crash there orphans the renamed file)
    _WORK_SUFFIXES = (".tmp", ".safetensors", ".npz", ".pt", ".bin")

    def sweep_orphan_tmps(self, min_age_s: float = 3600.0) -> int:
        """Remove stale work files left by abandoned (timed-out or crashed)
        copy/convert workers: `*.tmp` (mkstemp artifacts, the index
        writer's rename source) plus dot-prefixed files with an
        external-format extension (import_external's post-rename tmp).
        Deliberately narrow — installed bundles (`*.model`), the index,
        and foreign dot-files (e.g. `.nfs*` silly-renames) never match.
        Age-gated: a fresh tmp may still be written by a live worker
        thread. Returns the number removed."""
        removed = 0
        now = time.time()
        for fname in os.listdir(self.local_repo):
            is_work = fname.endswith(".tmp") or (
                fname.startswith(".") and fname.endswith(self._WORK_SUFFIXES)
            )
            if not is_work:
                continue
            path = os.path.join(self.local_repo, fname)
            try:
                if now - os.path.getmtime(path) > min_age_s:
                    os.unlink(path)
                    removed += 1
            except OSError:
                pass  # raced with a concurrent sweep/writer
        return removed

    def _fetch_verified(self, schema: ModelSchema, suffix: str = ".tmp") -> str:
        """Fetch schema.uri into a fresh tmp in the repo and sha256-verify
        it. Returns the tmp path (caller installs/converts then removes).

        Unique tmp per attempt, and the WORKER never touches the install
        destination: a timed-out attempt's abandoned thread can only ever
        finish writing its own orphan tmp (age-swept by sweep_orphan_tmps
        on later fetches) — it cannot install an unverified file behind a
        later sha check."""
        import tempfile

        self.sweep_orphan_tmps()

        # a COMMITTED index must work from any checkout path, so schema.uri
        # may be repo-relative: resolve scheme-less relative uris against
        # the repo directory
        uri = schema.uri
        if "://" not in uri and not os.path.isabs(uri):
            uri = os.path.join(self.local_repo, uri)

        def copy():
            fd, tmp = tempfile.mkstemp(
                prefix=f".{schema.name}.", suffix=suffix,
                dir=self.local_repo,
            )
            os.close(fd)
            try:
                # scheme-dispatched fetch: local, file://, http(s)://, or
                # fsspec-backed cloud stores (utils.storage — the
                # HadoopUtils/remote-repo analogue)
                from ..utils.storage import copy_to_local

                copy_to_local(uri, tmp)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
            return tmp

        tmp = retry_with_timeout(copy)
        try:
            if schema.sha256:
                got = _sha256(tmp)
                if got != schema.sha256:
                    raise IOError(
                        f"hash mismatch for {schema.name}: got {got[:12]}…, "
                        f"want {schema.sha256[:12]}…"
                    )
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return tmp

    def _register(self, schema: ModelSchema) -> None:
        schemas = [s for s in self.models() if s.name != schema.name]
        schemas.append(schema)
        self._write_index(schemas)

    def download_model(self, schema: ModelSchema, force: bool = False) -> str:
        """Fetch + verify + register; returns the local bundle path."""
        dest = self.local_path(schema.name)
        if os.path.exists(dest) and not force:
            return dest
        tmp = self._fetch_verified(schema)
        try:
            os.replace(tmp, dest)  # verify-then-install, main thread only
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        self._register(schema)
        return dest

    def _verify_sha(self, name: str, path: str) -> None:
        """Verify an indexed artifact's committed hash before serving it;
        un-indexed names (ad-hoc files) are served unverified."""
        try:
            schema = self.get_model(name)
        except KeyError:
            return
        if schema.sha256:
            got = _sha256(path)
            if got != schema.sha256:
                raise IOError(
                    f"hash mismatch for {name}: got {got[:12]}…, "
                    f"want {schema.sha256[:12]}…"
                )

    def load_bundle(self, name: str) -> ModelBundle:
        path = self.local_path(name)
        self._verify_sha(name, path)
        return ModelBundle.load(path)

    def import_external(self, schema: ModelSchema, force: bool = False) -> str:
        """Fetch EXTERNAL-format pretrained weights (torch-layout
        `.safetensors`/`.npz` state dict at `schema.uri`), convert them to a
        native ModelBundle, and register the model — the reference's
        remote-repo ingestion of published CNTK models
        (ModelDownloader.scala:209+, Schema.scala:30-119). The artifact is
        sha256-verified BEFORE conversion; the converted bundle is what
        lands in the repo."""
        dest = self.local_path(schema.name)
        if os.path.exists(dest) and not force:
            return dest
        suffix = os.path.splitext(schema.uri)[1] or ".safetensors"
        tmp = self._fetch_verified(schema, suffix=suffix + ".tmp")
        try:
            # the loader dispatches on extension; the verified tmp carries
            # "<ext>.tmp", so hand it over under its real extension
            typed = tmp[: -len(".tmp")]
            os.replace(tmp, typed)
            tmp = typed
            from .import_weights import import_external_weights

            kw = dict(schema.extra.get("config", {}))
            if schema.input_shape:
                kw["input_shape"] = tuple(schema.input_shape)
            bundle = import_external_weights(
                tmp,
                architecture=schema.architecture or "resnet50",
                num_outputs=schema.num_outputs,
                class_labels=schema.class_labels,
                **kw,
            )
            bundle.save(dest)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        self._register(schema)
        return dest

    # -- publish (the reference's uploader role) ------------------------- #

    def publish(self, bundle: ModelBundle, name: str,
                class_labels: list | None = None,
                relative_uri: bool = False,
                extra: dict | None = None) -> ModelSchema:
        """`relative_uri=True` writes a repo-relative uri so the index can
        be COMMITTED and served from any checkout path (the stocked-zoo
        story, ModelDownloader.scala:209+)."""
        path = self.local_path(name)
        bundle.save(path)
        schema = ModelSchema(
            name=name,
            uri=(os.path.basename(path) if relative_uri
                 else "file://" + path),
            sha256=_sha256(path),
            architecture=bundle.architecture,
            input_shape=bundle.input_shape,
            num_outputs=bundle.config.get("num_outputs"),
            class_labels=class_labels or bundle.class_labels,
            extra=dict(extra or {}),
        )
        self._register(schema)
        return schema

    # -- GBDT artifacts: the zoo serves boosters too --------------------- #
    # The reference's zoo is CNTK-only because its GBDT rides Spark MLlib
    # persistence; here the booster's LightGBM-format model.txt IS the
    # interchange artifact (docs/scope.md), so the same repo stocks both.

    def publish_booster(self, booster, name: str,
                        extra: dict | None = None) -> ModelSchema:
        path = self.local_path(name)
        txt = booster.to_lightgbm_text()
        with open(path, "w") as fh:
            fh.write(txt)
        schema = ModelSchema(
            name=name, uri=os.path.basename(path), sha256=_sha256(path),
            architecture="gbdt",
            extra={"format": "lightgbm_model_txt", **(extra or {})},
        )
        self._register(schema)
        return schema

    def load_booster(self, name: str):
        """Load a published GBDT artifact (LightGBM model.txt format —
        `Booster.load_native_model` autodetects)."""
        from ..gbdt.booster import Booster

        schema = self.get_model(name)
        if schema.architecture != "gbdt":
            raise ValueError(
                f"{name!r} is a {schema.architecture!r} bundle, not a "
                "gbdt artifact — use load_bundle"
            )
        path = self.local_path(name)
        self._verify_sha(name, path)
        return Booster.load_native_model(path)
