"""Model architectures + the ModelBundle container.

Reference: the CNTK side ships opaque serialized `Function` graphs
(src/cntk-model/src/main/scala/SerializableFunction.scala:85+) whose layers
are addressed by name for transfer learning (`ImageFeaturizer.scala:92-135`
cutOutputLayers/layerNames). TPU-first equivalent: flax modules with
deterministic layer naming; intermediates are captured by flax's
`capture_intermediates` and addressed with the same dotted-path idea.

All models run NHWC with channel dims that map well to the MXU's 128-lane
tiling; compute in bfloat16 with float32 params/accumulations is handled by
the `dtype` argument (the standard flax mixed-precision recipe).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "MLP",
    "SimpleCNN",
    "ResNet",
    "TransformerEncoder",
    "resnet20_cifar",
    "resnet50",
    "ARCHITECTURES",
    "make_model",
    "ModelBundle",
]


class MLP(nn.Module):
    """Plain fully-connected classifier/regressor."""

    features: Sequence[int] = (128, 64)
    num_outputs: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
        return nn.Dense(self.num_outputs, dtype=self.dtype, name="head")(x)


class SimpleCNN(nn.Module):
    """Small conv net (the role of the reference's ConvNet notebook model,
    `DeepLearning - CIFAR10 Convolutional Network.ipynb`)."""

    num_outputs: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for i, f in enumerate((64, 128, 256)):
            x = nn.Conv(f, (3, 3), dtype=self.dtype, name=f"conv_{i}")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype, name="dense_0")(x)
        x = nn.relu(x)
        return nn.Dense(self.num_outputs, dtype=self.dtype, name="head")(x)


class ResNetBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         name="bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), use_bias=False, dtype=self.dtype,
                    name="conv2")(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         scale_init=nn.initializers.zeros_init(), name="bn2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype,
                               name="proj_conv")(residual)
            residual = nn.BatchNorm(use_running_average=not train,
                                    dtype=self.dtype, name="proj_bn")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv1")(x)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         name="bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         name="bn2")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv3")(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         scale_init=nn.initializers.zeros_init(), name="bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype,
                               name="proj_conv")(residual)
            residual = nn.BatchNorm(use_running_average=not train,
                                    dtype=self.dtype, name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet family. `stage_sizes`/`bottleneck` select the variant:
    resnet20 CIFAR (3,3,3 basic), resnet50 (3,4,6,3 bottleneck), etc."""

    stage_sizes: Sequence[int] = (3, 3, 3)
    num_outputs: int = 10
    num_filters: int = 16
    bottleneck: bool = False
    stem_strides: int = 1          # 1 for CIFAR-size inputs, 2 for ImageNet
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        block = BottleneckBlock if self.bottleneck else ResNetBlock
        if self.stem_strides == 1:
            x = nn.Conv(self.num_filters, (3, 3), use_bias=False,
                        dtype=self.dtype, name="stem_conv")(x)
        else:
            x = nn.Conv(self.num_filters, (7, 7), (2, 2), use_bias=False,
                        dtype=self.dtype, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         name="stem_bn")(x)
        x = nn.relu(x)
        if self.stem_strides != 1:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, size in enumerate(self.stage_sizes):
            for j in range(size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block(self.num_filters * 2**i, strides=strides,
                          dtype=self.dtype, name=f"stage{i}_block{j}")(x, train)
        x = jnp.mean(x, axis=(1, 2), keepdims=False)
        self.sow("intermediates", "pooled_features", x)
        return nn.Dense(self.num_outputs, dtype=jnp.float32, name="head")(x)


class TransformerEncoder(nn.Module):
    """Sequence classifier/regressor: pre-LN transformer encoder blocks
    over (batch, seq, feat) inputs — the sequence-model family the
    reference lacks entirely (SURVEY.md §5.7). Token-id inputs embed via
    `vocab_size`; continuous inputs project via a Dense stem. Attention is
    standard dense MHA here; the sharded ring/Ulysses variants in
    `parallel.ring_attention` drop into the same block shape for long
    sequences (they implement identical math)."""

    num_layers: int = 2
    d_model: int = 64
    num_heads: int = 4
    d_ff: int = 128
    num_outputs: int = 2
    vocab_size: int = 0             # >0: int token inputs, embed; 0: project
    max_len: int = 512
    dropout_rate: float = 0.0
    # attention core (nn/attention.py): "dense" (reference math),
    # "chunked" (O(T) online-softmax scan), "flash" (Pallas TPU kernel,
    # differentiable via custom_vjp; falls back to chunked off-TPU).
    # Param trees are identical across impls, so a model trained with one
    # loads and serves with any other.
    attention_impl: str = "dense"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.vocab_size > 0:
            h = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                         name="embed")(x.astype(jnp.int32))
        else:
            if x.ndim == 2:          # (batch, seq) scalars -> (batch, seq, 1)
                x = x[:, :, None]
            h = nn.Dense(self.d_model, dtype=self.dtype, name="stem")(
                x.astype(self.dtype))
        if h.shape[1] > self.max_len:
            raise ValueError(
                f"sequence length {h.shape[1]} exceeds max_len={self.max_len}; "
                "raise max_len in the model config"
            )
        # param stays float32 (the mixed-precision recipe: f32 params, cast
        # at use) — creating it in bf16 would also optimize it in bf16 and
        # tiny position updates would round to zero
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.max_len, self.d_model), jnp.float32,
        )
        h = h + pos[: h.shape[1]][None, :, :].astype(self.dtype)
        if self.attention_impl != "dense" and self.dropout_rate > 0:
            raise ValueError(
                "attention dropout is only implemented for the dense core; "
                f"got attention_impl={self.attention_impl!r} with "
                f"dropout_rate={self.dropout_rate}")
        for i in range(self.num_layers):
            y = nn.LayerNorm(dtype=self.dtype, name=f"ln_attn_{i}")(h)
            if self.attention_impl == "dense":
                y = nn.MultiHeadDotProductAttention(
                    num_heads=self.num_heads, dtype=self.dtype,
                    dropout_rate=self.dropout_rate, deterministic=not train,
                    name=f"attn_{i}",
                )(y)
            else:
                from .attention import SelfAttention

                y = SelfAttention(
                    num_heads=self.num_heads, dtype=self.dtype,
                    impl=self.attention_impl, name=f"attn_{i}",
                )(y, train=train)
            h = h + y
            y = nn.LayerNorm(dtype=self.dtype, name=f"ln_mlp_{i}")(h)
            y = nn.Dense(self.d_ff, dtype=self.dtype, name=f"mlp_up_{i}")(y)
            y = nn.gelu(y)
            y = nn.Dense(self.d_model, dtype=self.dtype, name=f"mlp_down_{i}")(y)
            h = h + y
        h = nn.LayerNorm(dtype=self.dtype, name="ln_final")(h)
        pooled = h.mean(axis=1)
        self.sow("intermediates", "pooled_features", pooled)
        return nn.Dense(self.num_outputs, dtype=jnp.float32, name="head")(pooled)


def resnet20_cifar(num_outputs: int = 10, dtype=jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(3, 3, 3), num_filters=16,
                  num_outputs=num_outputs, dtype=dtype)


def resnet50(num_outputs: int = 1000, dtype=jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_filters=64, bottleneck=True,
                  stem_strides=2, num_outputs=num_outputs, dtype=dtype)


# Architecture registry: name -> factory(**config). The zoo's ModelSchema
# references architectures by name (the reference's ModelSchema carries a
# remote URI instead, downloader/Schema.scala:30+).
ARCHITECTURES: dict[str, Callable[..., nn.Module]] = {
    "mlp": lambda **kw: MLP(**kw),
    "simple_cnn": lambda **kw: SimpleCNN(**kw),
    "resnet20_cifar": lambda **kw: resnet20_cifar(**kw),
    "resnet50": lambda **kw: resnet50(**kw),
    "resnet": lambda **kw: ResNet(**kw),
    "transformer": lambda **kw: TransformerEncoder(**kw),
}


def make_model(architecture: str, **config) -> nn.Module:
    if architecture not in ARCHITECTURES:
        raise ValueError(
            f"unknown architecture {architecture!r}; have {sorted(ARCHITECTURES)}"
        )
    return ARCHITECTURES[architecture](**config)


@dataclass
class ModelBundle:
    """A saved/loadable model: architecture name + config + variables.

    Role of the reference's serialized CNTK Function + ModelSchema metadata
    (SerializableFunction.scala:85+, downloader/Schema.scala:30+)."""

    architecture: str
    config: dict[str, Any]
    variables: dict[str, Any]          # {"params": ..., "batch_stats": ...}
    input_shape: tuple[int, ...] = ()  # per-example shape, e.g. (32, 32, 3)
    class_labels: list | None = None
    preprocess: dict[str, Any] = field(default_factory=dict)  # mean/std etc.

    _module: nn.Module | None = None

    @property
    def module(self) -> nn.Module:
        if self._module is None:
            cfg = dict(self.config)
            if cfg.get("dtype") == "bfloat16":
                cfg["dtype"] = jnp.bfloat16
            elif cfg.get("dtype") == "float32":
                cfg["dtype"] = jnp.float32
            self._module = make_model(self.architecture, **cfg)
        return self._module

    @staticmethod
    def init(architecture: str, input_shape: tuple[int, ...], seed: int = 0,
             class_labels=None, preprocess=None, **config) -> "ModelBundle":
        bundle = ModelBundle(
            architecture=architecture,
            config=config,
            variables={},
            input_shape=tuple(input_shape),
            class_labels=class_labels,
            preprocess=dict(preprocess or {}),
        )
        x = jnp.zeros((1, *input_shape), jnp.float32)
        bundle.variables = bundle.module.init(jax.random.PRNGKey(seed), x)
        return bundle

    def save(self, path: str) -> None:
        import json
        from flax import serialization

        cfg = {
            k: ("bfloat16" if v is jnp.bfloat16 else "float32" if v is jnp.float32 else v)
            for k, v in self.config.items()
        }
        header = json.dumps({
            "architecture": self.architecture,
            "config": cfg,
            "input_shape": list(self.input_shape),
            "class_labels": self.class_labels,
            "preprocess": self.preprocess,
        }).encode()
        blob = serialization.to_bytes(self.variables)
        with open(path, "wb") as fh:
            fh.write(len(header).to_bytes(8, "little"))
            fh.write(header)
            fh.write(blob)

    @staticmethod
    def load(path: str) -> "ModelBundle":
        import json
        from flax import serialization

        with open(path, "rb") as fh:
            hlen = int.from_bytes(fh.read(8), "little")
            header = json.loads(fh.read(hlen).decode())
            blob = fh.read()
        bundle = ModelBundle(
            architecture=header["architecture"],
            config=header["config"],
            variables={},
            input_shape=tuple(header["input_shape"]),
            class_labels=header.get("class_labels"),
            preprocess=header.get("preprocess", {}),
        )
        x = jnp.zeros((1, *bundle.input_shape), jnp.float32)
        template = bundle.module.init(jax.random.PRNGKey(0), x)
        bundle.variables = serialization.from_bytes(template, blob)
        return bundle

    def layer_names(self) -> list[str]:
        """Dotted paths of all submodules (the reference's layerNames,
        ImageFeaturizer.scala:92-135)."""
        x = jnp.zeros((1, *self.input_shape), jnp.float32)
        _, state = self.module.apply(
            self.variables, x, train=False,
            capture_intermediates=True, mutable=["intermediates"],
        )
        names: list[str] = []

        def walk(tree, prefix):
            for k, v in tree.items():
                p = f"{prefix}.{k}" if prefix else k
                if isinstance(v, dict):
                    walk(v, p)
                else:
                    # "__call__" leaves name the module; sown values (e.g.
                    # pooled_features) name themselves
                    names.append(prefix if k == "__call__" else p)

        walk(state["intermediates"], "")
        # dedupe, keep order; drop the root module's own output ("") — that
        # is just the logits, addressable as "logits"
        seen: dict[str, None] = {}
        for nme in names:
            if nme:
                seen.setdefault(nme, None)
        return list(seen)
