"""Recommendation subsystem: SAR + ranking evaluation/tuning.

Reference module replaced: src/recommendation/ — `SAR`/`SARModel`
(SAR.scala:36-205, SARModel.scala:21-167), `RecommendationIndexer`
(RecommendationIndexer.scala:16-130), `RankingAdapter`
(RankingAdapter.scala:66-151), `RankingEvaluator`/`AdvancedRankingMetrics`
(RankingEvaluator.scala:14-151), `RankingTrainValidationSplit`
(RankingTrainValidationSplit.scala:22-337).
"""

from .indexer import RecommendationIndexer, RecommendationIndexerModel
from .sar import SAR, SARModel
from .ranking import (
    RankingAdapter,
    RankingEvaluator,
    RankingTrainValidationSplit,
    ranking_metrics,
)
from .resident import SARTopKScorer, serve_recommender

__all__ = [
    "RecommendationIndexer",
    "RecommendationIndexerModel",
    "SAR",
    "SARModel",
    "SARTopKScorer",
    "RankingAdapter",
    "RankingEvaluator",
    "RankingTrainValidationSplit",
    "ranking_metrics",
    "serve_recommender",
]
