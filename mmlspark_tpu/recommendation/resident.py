"""Device-resident SAR serving: pinned similarity, fused top-k scoring.

The GBDT hot path (io_http/serving.py + core/fusion.ResidentExecutor)
pins a fused segment's params on device once and scores request batches
through a persistent executable per bucket rung. This module puts the
SAR recommender on the same rails:

- `SARTopKScorer` wraps a fitted `SARModel` as a registered Transformer
  whose `device_kernel()` is one fused program — gather the requested
  users' affinity rows, multiply into the device-pinned item-item
  similarity matrix, mask seen items, `lax.top_k` — so the whole
  user-id -> recommendations computation is a single XLA executable per
  ladder rung.
- `SARHotPath` specializes `_HotPath` for two output columns
  (recommendation ids + ratings per request) and counts its traffic
  under the `sar_resident` route label, so
  `mmlspark_tpu_serving_path_total{path="sar_resident"}` separates SAR
  traffic from GBDT's `resident` in one process's scrape.
- `serve_recommender` is the `serve_model` twin: full-ladder warmup
  gates /readyz, every rung's resident reply is byte-compared against
  the handler path before it may route (divergence disables the route,
  never changes answers), readback completes lag-1 async, and steady
  state is zero-recompile because the bucket ladder closes the shape
  set.

Similarity layout: the kernel keeps `similarity` as a dense row-major
(I, I) operand of a plain `@` — the contract a later Pallas
blocked-sparse kernel slots into (same operand, blocked CSR under the
hood) without touching the serving path.
"""

from __future__ import annotations

import json
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.fusion import DeviceKernel, fuse
from ..core.params import Param
from ..core.pipeline import Model, PipelineModel
from ..core.schema import Table
from ..core.serialize import register_stage
from ..io_http.schema import (HTTPRequestData, HTTPResponseData,
                              RequestDecoder, parse_request)
from ..io_http.serving import ServingServer, _HotPath
from .sar import SARModel

__all__ = ["SARTopKScorer", "SARHotPath", "serve_recommender", "topk_reply"]

# the two output columns every SAR scoring path produces, in reply order
TOPK_COLS = ("recommendations", "ratings")


@register_stage
class SARTopKScorer(Model):
    """Top-k recommendation scoring as a fusable pipeline stage.

    Consumes a `features` column of user ids — (n, 1) float, the
    RequestDecoder's output shape — and produces `recommendations`
    (int64 item ids, -1 for exhausted/invalid slots) and `ratings`
    (float64 scores, 0.0 on those slots), row-aligned with
    `SARModel.recommend_for_all_users`. The kernel is total over any
    float input: out-of-range or non-integral user ids yield all-(-1)
    rows instead of failing the batch, so padded/garbage rows can ride
    through the resident executor and the route contract stays
    byte-deterministic."""

    user_col = Param("user", "request field carrying the user id", ptype=str)
    k = Param(10, "recommendations per user", ptype=int)
    remove_seen = Param(True, "mask items the user already interacted with",
                        ptype=bool)

    user_affinity: np.ndarray | None = None    # (U, I) float32
    item_similarity: np.ndarray | None = None  # (I, I) float32
    seen: np.ndarray | None = None             # (U, I) bool

    _kernel: "DeviceKernel | None" = None
    _host_fn = None

    @classmethod
    def from_model(cls, model: SARModel, k: int = 10,
                   remove_seen: bool = True) -> "SARTopKScorer":
        scorer = cls(user_col=model.get("user_col"), k=int(k),
                     remove_seen=bool(remove_seen))
        scorer.user_affinity = model.user_affinity
        scorer.item_similarity = model.item_similarity
        scorer.seen = model.seen
        return scorer

    def device_kernel(self) -> "DeviceKernel | str":
        if self.user_affinity is None or self.item_similarity is None:
            return "scorer holds no fitted SAR state"
        if self._kernel is not None:
            return self._kernel
        n_users, n_items = self.user_affinity.shape
        k = min(int(self.get("k")), n_items)
        mask_seen = bool(self.get("remove_seen")) and self.seen is not None
        params = {"affinity": self.user_affinity,
                  "similarity": self.item_similarity}
        if mask_seen:
            params["seen"] = self.seen

        def fn(p, cols):
            raw = cols["features"][:, 0]
            ids = raw.astype(jnp.int32)
            # total over any float payload: out-of-range / fractional /
            # NaN user ids score a clamped row but reply all-invalid
            valid = (ids >= 0) & (ids < n_users) & (raw == ids.astype(raw.dtype))
            safe = jnp.clip(ids, 0, n_users - 1)
            scores = p["affinity"][safe] @ p["similarity"]
            if mask_seen:
                scores = jnp.where(p["seen"][safe], -jnp.inf, scores)
            vals, idx = jax.lax.top_k(scores, k)
            # -inf slots = fewer than k unseen items, same convention as
            # SARModel.recommend_for_all_users
            bad = ~jnp.isfinite(vals) | ~valid[:, None]
            return {"recommendations": jnp.where(bad, -1, idx),
                    "ratings": jnp.where(bad, 0.0, vals)}

        self._kernel = DeviceKernel(
            fn=fn,
            input_cols=("features",),
            output_cols=TOPK_COLS,
            params=params,
            name="SARTopKScorer",
            out_dtypes={"recommendations": np.int64, "ratings": np.float64},
            mesh_desc="rows P(data) / similarity+affinity replicated",
        )
        return self._kernel

    def _transform(self, table: Table) -> Table:
        """Host fallback, same program run through jax.jit directly (the
        fused path is the serving route; this keeps bare `transform`
        correct for staged pipelines and tests)."""
        kern = self.device_kernel()
        if isinstance(kern, str):
            raise ValueError(kern)
        if "features" in table:
            feats = np.asarray(table["features"], np.float64)
        else:
            feats = np.asarray(table[self.get("user_col")],
                               np.float64).reshape(-1, 1)
        if self._host_fn is None:
            self._host_fn = jax.jit(kern.fn)
        outs = self._host_fn(kern.params, {"features": jnp.asarray(feats)})
        result = table
        for c in kern.output_cols:
            arr = np.asarray(outs[c])
            want = kern.out_dtypes.get(c)
            if want is not None and arr.dtype != np.dtype(want):
                arr = arr.astype(want)
            result = result.with_column(c, arr)
        return result

    def _save_state(self) -> dict[str, Any]:
        return {
            "user_affinity": self.user_affinity,
            "item_similarity": self.item_similarity,
            "seen": self.seen.astype(np.uint8) if self.seen is not None else None,
        }

    def _load_state(self, state: dict[str, Any]) -> None:
        self.user_affinity = np.asarray(state["user_affinity"], np.float32)
        self.item_similarity = np.asarray(state["item_similarity"], np.float32)
        seen = state.get("seen")
        self.seen = None if seen is None else np.asarray(seen, bool)
        self._kernel = None
        self._host_fn = None


def topk_reply(table: Table, reply_col: str = "reply") -> Table:
    """`make_reply` for the two-column top-k schema: one JSON body per row
    carrying both lists, byte-for-byte what `SARHotPath.replies_for`
    produces (tolist() -> Python ints/floats -> json.dumps)."""
    ids = np.asarray(table["recommendations"]).tolist()
    ratings = np.asarray(table["ratings"]).tolist()
    replies = [HTTPResponseData(
        status_code=200, reason="OK",
        headers={"Content-Type": "application/json"},
        entity=json.dumps(
            {"recommendations": i, "ratings": r}).encode(),
    ) for i, r in zip(ids, ratings)]
    return table.with_column(reply_col, replies)


class SARHotPath(_HotPath):
    """The SAR resident fast lane: same routing, warmup byte-compare, and
    readback machinery as the GBDT `_HotPath`, specialized for the
    two-column top-k reply and counted under its own route label."""

    resident_label = "sar_resident"

    def fetch_values(self, outs, n_valid: int, ledger=None):
        res = self.executor.fetch(outs, n_valid, ledger=ledger)
        return res["recommendations"], res["ratings"]

    def replies_for(self, vals, binary_mask=None
                    ) -> "list[HTTPResponseData]":
        # the two-column top-k reply stays JSON regardless of Accept —
        # binary negotiation covers single-value scoring replies only
        ids, ratings = vals
        return [HTTPResponseData(
            status_code=200, reason="OK",
            headers={"Content-Type": "application/json"},
            entity=json.dumps(
                {"recommendations": i, "ratings": r}).encode(),
        ) for i, r in zip(np.asarray(ids).tolist(),
                          np.asarray(ratings).tolist())]


def serve_recommender(
    model: SARModel,
    k: int = 10,
    remove_seen: bool = True,
    host: str = "127.0.0.1",
    port: int = 0,
    mesh=None,
    hot_path: bool = True,
    **server_kw,
) -> ServingServer:
    """Deploy a fitted `SARModel`: JSON `{user: id}` in,
    `{recommendations: [...], ratings: [...]}` out.

    The similarity matrix and affinity table pin on device once inside
    the fused segment; the handler path and the resident route execute
    the SAME jitted program with the SAME pinned params
    (`_FusedSegment._build` caches both), so warmup's per-rung byte
    comparison holds by construction and any divergence disables the
    fast lane rather than changing answers. `serve_model(sar_model, ...)`
    delegates here."""
    if model.user_affinity is None or model.item_similarity is None:
        raise ValueError("serve_recommender needs a fitted SARModel")
    scorer = SARTopKScorer.from_model(model, k=k, remove_seen=remove_seen)
    fused = fuse(PipelineModel([scorer]), mesh=mesh)
    user_col = model.get("user_col")
    # one decoder serves the handler fast path AND the resident route,
    # so the cached schema and its hit/fallback counts stay unified
    decoder = RequestDecoder([user_col])
    hp = None
    if hot_path:
        try:
            rex = fused.resident_executor()
        except Exception:  # noqa: BLE001 — the fast lane is strictly optional
            rex = None
        if rex is not None and not isinstance(rex, str) \
                and rex.upload_cols == ("features",):
            hp = SARHotPath(rex, decoder, "features", "recommendations",
                            readback_lag=fused.get("readback_lag"))

    def handler(table: Table) -> Table:
        reqs = list(table["request"])
        feats = decoder.decode(reqs)
        if feats is not None:
            scored = fused.transform(
                Table({"request": reqs, "features": feats}))
            return topk_reply(scored)
        t = parse_request(table)
        if user_col not in t:
            raise ValueError(f"request missing field {user_col!r}")
        t = t.with_column(
            "features",
            np.asarray(t[user_col], np.float64).reshape(-1, 1))
        return topk_reply(fused.transform(t))

    server_kw.setdefault("bucket_batches", True)
    # user id 0 always exists in a fitted model's id space, and 0.0 is
    # f32-exact — warmup compiles and byte-verifies every ladder rung
    server_kw.setdefault("warmup_request",
                         HTTPRequestData.from_json("/", {user_col: 0}))
    if hp is not None:
        server_kw.setdefault("bucket_multiple_of", hp.executor.data_axis_size)
    return ServingServer(handler, host=host, port=port, hot_path=hp,
                         **server_kw).start()
