"""Ranking evaluation + per-user split tuning.

Reference: src/recommendation/src/main/scala/ — `RankingEvaluator` /
`AdvancedRankingMetrics` (RankingEvaluator.scala:14-151: ndcgAt, map,
precisionAtk, recallAtK, diversityAtK, maxDiversity, mrr, fcp),
`RankingAdapter(Model)` (RankingAdapter.scala:66-151: wrap a recommender so
evaluators see (prediction, label) id lists), `RankingTrainValidationSplit`
(RankingTrainValidationSplit.scala:22-337: per-user stratified split :88+,
grid evaluation).
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np

from ..core.params import Param
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import Table
from ..core.serialize import register_stage

__all__ = [
    "ranking_metrics",
    "RankingEvaluator",
    "RankingAdapter",
    "RankingTrainValidationSplit",
]


def ranking_metrics(predictions: Iterable[Iterable[int]],
                    labels: Iterable[Iterable[int]],
                    k: int, n_items: int | None = None) -> dict[str, float]:
    """All metrics of AdvancedRankingMetrics (RankingEvaluator.scala:30-151)
    over per-user (predicted ids, relevant ids)."""
    preds = [list(p)[:k] for p in predictions]
    lab_lists = [list(l) for l in labels]
    users = [(p, ll, set(ll)) for p, ll in zip(preds, lab_lists) if ll]
    if not users:
        raise ValueError("no users with ground-truth items")

    precisions, recalls, ndcgs, aps, mrrs, fcps = [], [], [], [], [], []
    all_rec: set[int] = set()
    all_lab: set[int] = set()
    for p, ll, l in users:
        hits_mask = [1.0 if i in l else 0.0 for i in p]
        hits = sum(hits_mask)
        precisions.append(hits / k)
        # reference recallAtK divides by |predictions| (RankingEvaluator.scala)
        recalls.append(hits / max(len(p), 1))
        # ndcg@k
        dcg = sum(h / np.log2(r + 2) for r, h in enumerate(hits_mask))
        idcg = sum(1.0 / np.log2(r + 2) for r in range(min(len(l), k)))
        ndcgs.append(dcg / idcg if idcg > 0 else 0.0)
        # average precision, normalized by |labels| (Spark RankingMetrics)
        cum, ap = 0.0, 0.0
        for r, h in enumerate(hits_mask):
            if h:
                cum += 1.0
                ap += cum / (r + 1)
        aps.append(ap / len(l))
        # mrr
        rr = 0.0
        for r, h in enumerate(hits_mask):
            if h:
                rr = 1.0 / (r + 1)
                break
        mrrs.append(rr)
        # fcp: fraction of concordant pairs — for predicted items that both
        # appear in the label list, their predicted order must match the
        # label-list (relevance) order
        rank_of = {item: r for r, item in enumerate(ll)}
        both = [i for i in p if i in rank_of]
        pairs = concordant = 0
        for a in range(len(both)):
            for b_ in range(a + 1, len(both)):
                pairs += 1
                if rank_of[both[a]] < rank_of[both[b_]]:
                    concordant += 1
        fcps.append(concordant / pairs if pairs else 0.0)
        all_rec.update(i for i in p if i >= 0)
        all_lab.update(l)

    out = {
        "precisionAtk": float(np.mean(precisions)),
        "recallAtK": float(np.mean(recalls)),
        "ndcgAt": float(np.mean(ndcgs)),
        "map": float(np.mean(aps)),
        "mrr": float(np.mean(mrrs)),
        "fcp": float(np.mean(fcps)),
    }
    if n_items:
        out["diversityAtK"] = len(all_rec) / n_items
        out["maxDiversity"] = len(all_rec | all_lab) / n_items
    return out


@register_stage
class RankingEvaluator(Transformer):
    """Table{prediction: id lists, label: id lists} -> one-row metric table
    (RankingEvaluator.scala:14-151)."""

    k = Param(10, "cutoff", ptype=int)
    metric_name = Param("ndcgAt", "metric to report", ptype=str)
    prediction_col = Param("prediction", "recommended id list column", ptype=str)
    label_col = Param("label", "relevant id list column", ptype=str)
    n_items = Param(None, "item count (enables diversity metrics)", ptype=int)

    def evaluate(self, table: Table) -> float:
        m = ranking_metrics(
            table[self.get("prediction_col")], table[self.get("label_col")],
            self.get("k"), self.get("n_items"),
        )
        return m[self.get("metric_name")]

    def _transform(self, table: Table) -> Table:
        m = ranking_metrics(
            table[self.get("prediction_col")], table[self.get("label_col")],
            self.get("k"), self.get("n_items"),
        )
        return Table({name: np.asarray([v]) for name, v in m.items()})


@register_stage
class RankingAdapter(Estimator):
    """Wrap a recommender estimator so its output evaluates like a ranking
    problem (RankingAdapter.scala:66-151)."""

    recommender = Param(None, "estimator producing a SARModel-like model", required=True)
    k = Param(10, "recommendations per user", ptype=int)
    user_col = Param("user", "user id column", ptype=str)
    item_col = Param("item", "item id column", ptype=str)

    def _save_state(self):
        return {"recommender": self.get("recommender")}

    def _load_state(self, state):
        self.set(recommender=state["recommender"])

    def params_to_dict(self):
        d = dict(self._values)
        d.pop("recommender", None)
        return d

    def _fit(self, table: Table) -> "RankingAdapterModel":
        fitted = self.get("recommender").fit(table)
        m = RankingAdapterModel(
            k=self.get("k"), user_col=self.get("user_col"),
            item_col=self.get("item_col"),
        )
        m.recommender_model = fitted
        return m


@register_stage
class RankingAdapterModel(Model):
    k = Param(10, "recommendations per user", ptype=int)
    user_col = Param("user", "user id column", ptype=str)
    item_col = Param("item", "item id column", ptype=str)

    recommender_model: Any = None

    def _save_state(self):
        return {"recommender_model": self.recommender_model}

    def _load_state(self, state):
        self.recommender_model = state["recommender_model"]

    def _transform(self, table: Table) -> Table:
        """Test interactions -> per-user (prediction, label) id lists."""
        recs = self.recommender_model.recommend_for_all_users(self.get("k"))
        rec_map = {int(u): list(map(int, row)) for u, row in
                   zip(recs[self.get("user_col")], recs["recommendations"])}
        u = np.asarray(table[self.get("user_col")], np.int64)
        it = np.asarray(table[self.get("item_col")], np.int64)
        truth: dict[int, list[int]] = {}
        for uu, ii in zip(u, it):
            truth.setdefault(int(uu), []).append(int(ii))
        users = sorted(truth)
        return Table({
            self.get("user_col"): np.asarray(users, np.float64),
            "prediction": [rec_map.get(uu, []) for uu in users],
            "label": [truth[uu] for uu in users],
        })


@register_stage
class RankingTrainValidationSplit(Estimator):
    """Per-user stratified split + grid evaluation
    (RankingTrainValidationSplit.scala:22-337)."""

    recommender = Param(None, "recommender estimator", required=True)
    user_col = Param("user", "user id column", ptype=str)
    item_col = Param("item", "item id column", ptype=str)
    train_ratio = Param(0.75, "per-user train fraction", ptype=float)
    min_ratings_per_user = Param(1, "drop users with fewer events", ptype=int)
    k = Param(10, "evaluation cutoff", ptype=int)
    metric_name = Param("ndcgAt", "selection metric", ptype=str)
    param_maps = Param(None, "list of param dicts to evaluate (None = [{}])")
    seed = Param(0, "shuffle seed", ptype=int)

    def _save_state(self):
        return {"recommender": self.get("recommender")}

    def _load_state(self, state):
        self.set(recommender=state["recommender"])

    def params_to_dict(self):
        d = dict(self._values)
        d.pop("recommender", None)
        return d

    def split(self, table: Table) -> tuple[Table, Table]:
        """Per-user stratified split (:88+): each user's events split by
        train_ratio, preserving at least one event on each side when
        possible."""
        u = np.asarray(table[self.get("user_col")], np.int64)
        rng = np.random.default_rng(self.get("seed"))
        train_mask = np.zeros(len(u), bool)
        for uu in np.unique(u):
            idx = np.nonzero(u == uu)[0]
            if len(idx) < self.get("min_ratings_per_user"):
                continue
            perm = rng.permutation(idx)
            n_train = int(round(len(idx) * self.get("train_ratio")))
            n_train = min(max(n_train, 1), len(idx) - 1) if len(idx) > 1 else 1
            train_mask[perm[:n_train]] = True
        test_mask = ~train_mask
        # drop users entirely filtered out
        keep = np.zeros(len(u), bool)
        for uu in np.unique(u):
            idx = np.nonzero(u == uu)[0]
            if train_mask[idx].any():
                keep[idx] = True
        return (table.gather(np.nonzero(train_mask & keep)[0]),
                table.gather(np.nonzero(test_mask & keep)[0]))

    def _fit(self, table: Table) -> "RankingTrainValidationSplitModel":
        train, test = self.split(table)
        maps = self.get("param_maps") or [{}]
        evaluator = RankingEvaluator(
            k=self.get("k"), metric_name=self.get("metric_name"),
        )
        results = []
        for pm in maps:
            est = self.get("recommender").copy(pm)
            adapter = RankingAdapter(
                recommender=est, k=self.get("k"),
                user_col=self.get("user_col"), item_col=self.get("item_col"),
            ).fit(train)
            scored = adapter.transform(test)
            results.append(evaluator.evaluate(scored))
        best = int(np.argmax(results))
        model = RankingTrainValidationSplitModel()
        model.best_model = self.get("recommender").copy(maps[best]).fit(table)
        model.validation_metrics = results
        model.best_params = dict(maps[best])
        return model


@register_stage
class RankingTrainValidationSplitModel(Model):
    best_model: Any = None
    validation_metrics: list = []
    best_params: dict = {}

    def _save_state(self):
        return {"best_model": self.best_model,
                "validation_metrics": list(self.validation_metrics),
                "best_params": dict(self.best_params)}

    def _load_state(self, state):
        self.best_model = state["best_model"]
        self.validation_metrics = state["validation_metrics"]
        self.best_params = state["best_params"]

    def _transform(self, table: Table) -> Table:
        return self.best_model.transform(table)
