"""RecommendationIndexer — map raw user/item values to dense int ids.

Reference: src/recommendation/src/main/scala/RecommendationIndexer.scala:
16-130 (string indexer pair + inverse transform for recommendations).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..core.params import Param
from ..core.pipeline import Estimator, Model
from ..core.schema import Table, as_scalar
from ..core.serialize import register_stage

__all__ = ["RecommendationIndexer", "RecommendationIndexerModel"]


@register_stage
class RecommendationIndexer(Estimator):
    user_input_col = Param(None, "raw user column", required=True, ptype=str)
    user_output_col = Param(None, "indexed user column", required=True, ptype=str)
    item_input_col = Param(None, "raw item column", required=True, ptype=str)
    item_output_col = Param(None, "indexed item column", required=True, ptype=str)
    rating_col = Param(None, "rating column (passed through)", ptype=str)

    def _fit(self, table: Table) -> "RecommendationIndexerModel":
        users = sorted({as_scalar(v) for v in table[self.get("user_input_col")]})
        items = sorted({as_scalar(v) for v in table[self.get("item_input_col")]})
        m = RecommendationIndexerModel(
            user_input_col=self.get("user_input_col"),
            user_output_col=self.get("user_output_col"),
            item_input_col=self.get("item_input_col"),
            item_output_col=self.get("item_output_col"),
        )
        m.user_levels = users
        m.item_levels = items
        return m


@register_stage
class RecommendationIndexerModel(Model):
    user_input_col = Param(None, "raw user column", required=True, ptype=str)
    user_output_col = Param(None, "indexed user column", required=True, ptype=str)
    item_input_col = Param(None, "raw item column", required=True, ptype=str)
    item_output_col = Param(None, "indexed item column", required=True, ptype=str)

    user_levels: list = []
    item_levels: list = []

    @property
    def n_users(self) -> int:
        """Full user vocabulary size (for SAR.set_indexer_model)."""
        return len(self.user_levels)

    @property
    def n_items(self) -> int:
        """Full item vocabulary size (for SAR.set_indexer_model)."""
        return len(self.item_levels)

    def _transform(self, table: Table) -> Table:
        u_map = {v: i for i, v in enumerate(self.user_levels)}
        i_map = {v: i for i, v in enumerate(self.item_levels)}
        u = np.asarray([u_map[as_scalar(v)] for v in table[self.get("user_input_col")]],
                       np.float64)
        it = np.asarray([i_map[as_scalar(v)] for v in table[self.get("item_input_col")]],
                        np.float64)
        return (table.with_column(self.get("user_output_col"), u)
                .with_column(self.get("item_output_col"), it))

    def recover_user(self, idx: int) -> Any:
        return self.user_levels[int(idx)]

    def recover_item(self, idx: int) -> Any:
        return self.item_levels[int(idx)]

    def inverse_transform_items(self, item_ids) -> list:
        """Recommendation id lists -> raw item values
        (RecommendationIndexer.scala inverse transform)."""
        return [[self.item_levels[int(i)] for i in row] for row in item_ids]

    def _save_state(self) -> dict[str, Any]:
        return {"user_levels": list(self.user_levels),
                "item_levels": list(self.item_levels)}

    def _load_state(self, state: dict[str, Any]) -> None:
        self.user_levels = state["user_levels"]
        self.item_levels = state["item_levels"]
