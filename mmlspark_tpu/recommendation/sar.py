"""SAR — Smart Adaptive Recommendations.

Reference: src/recommendation/src/main/scala/SAR.scala:36-205 —
user-item affinity with time decay (:82-117: affinity = rating ×
2^(-Δt_minutes / (time_decay_coeff·24·60)), summed per (user, item)) and
item-item similarity from distinct-user co-occurrence with
cooccurrence/jaccard/lift normalization and a support threshold (:119-205);
SARModel scoring (SARModel.scala:95-130) = user-affinity × item-similarity
matrix product + top-k.

TPU redesign: the reference builds these with Spark groupBys, per-row UDFs
and a breeze BlockMatrix multiply. Here the whole computation is three dense
device ops — a scatter-add affinity build, ONE (I×U)@(U×I) matmul on the MXU
for co-occurrence, and ONE (U×I)@(I×I) matmul + `lax.top_k` for
recommendations.
"""

from __future__ import annotations

from datetime import datetime
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import Param
from ..core.pipeline import Estimator, Model
from ..core.schema import Table
from ..core.serialize import register_stage

__all__ = ["SAR", "SARModel"]


# Module-level jitted scoring programs: jax.jit caches the executable per
# input shape, so repeated SARModel calls neither re-trace nor recompile.
@jax.jit
def _affinity_scores(affinity, similarity):
    return affinity @ similarity


@partial(jax.jit, static_argnames=("k",))
def _block_topk(affinity_rows, similarity, k):
    return jax.lax.top_k(affinity_rows @ similarity, k)


@partial(jax.jit, static_argnames=("k",))
def _block_topk_unseen(affinity_rows, similarity, seen_rows, k):
    scores = affinity_rows @ similarity
    return jax.lax.top_k(jnp.where(seen_rows, -jnp.inf, scores), k)


def _to_minutes(values, fmt: str | None) -> np.ndarray:
    """Timestamps (epoch seconds, numpy datetimes, or strings with fmt) ->
    float minutes."""
    vals = list(values)
    if not vals:
        return np.zeros(0)
    v0 = vals[0]
    if isinstance(v0, (int, float, np.number)):
        return np.asarray(vals, np.float64) / 60.0
    if isinstance(v0, np.datetime64):
        return np.asarray(vals).astype("datetime64[s]").astype(np.float64) / 60.0
    fmt = fmt or "%Y-%m-%d %H:%M:%S"
    return np.asarray(
        [datetime.strptime(str(v), fmt).timestamp() for v in vals], np.float64
    ) / 60.0


@register_stage
class SAR(Estimator):
    """Reference params: SARParams (SAR.scala:39-56) + Spark ALS-style cols."""

    user_col = Param("user", "indexed user id column", ptype=str)
    item_col = Param("item", "indexed item id column", ptype=str)
    rating_col = Param(None, "rating column (optional)", ptype=str)
    time_col = Param(None, "activity timestamp column (optional)", ptype=str)
    similarity_function = Param("jaccard", "jaccard | lift | cooccurrence", ptype=str)
    support_threshold = Param(4, "min co-occurrence to keep a similarity", ptype=int)
    time_decay_coeff = Param(30, "half-life in days for affinity decay", ptype=int)
    start_time = Param(None, "reference time (default: max activity time)", ptype=str)
    activity_time_format = Param("%Y-%m-%d %H:%M:%S", "strptime format", ptype=str)
    start_time_format = Param("%Y-%m-%d %H:%M:%S", "strptime format", ptype=str)
    num_users = Param(None, "explicit user vocabulary size (default: max id + 1)",
                      ptype=int)
    num_items = Param(None, "explicit item vocabulary size (default: max id + 1)",
                      ptype=int)

    def set_indexer_model(self, indexer_model) -> "SAR":
        """Wire vocabulary sizes from a fitted RecommendationIndexerModel so
        items/users with no interactions still exist in the model (reference
        SARModel operates on the indexer's full id space,
        RecommendationIndexer.scala:16-130)."""
        self.set(num_users=indexer_model.n_users, num_items=indexer_model.n_items)
        return self

    def _fit(self, table: Table) -> "SARModel":
        u = np.asarray(table[self.get("user_col")], np.int64)
        it = np.asarray(table[self.get("item_col")], np.int64)
        if len(u) == 0 and not (self.get("num_users") and self.get("num_items")):
            raise ValueError(
                "cannot fit SAR on an empty table without explicit "
                "num_users/num_items"
            )
        max_u = int(u.max()) if len(u) else -1
        max_i = int(it.max()) if len(it) else -1
        n_users = self.get("num_users") or max_u + 1
        n_items = self.get("num_items") or max_i + 1
        if max_u >= n_users or max_i >= n_items:
            raise ValueError(
                f"interaction ids exceed declared vocab: max user {max_u} "
                f"(num_users={n_users}), max item {max_i} (num_items={n_items})"
            )

        # -- affinity weights (SAR.scala:82-117) ------------------------- #
        if self.get("rating_col") and self.get("rating_col") in table:
            w = np.asarray(table[self.get("rating_col")], np.float64)
        else:
            w = np.ones(len(u), np.float64)
        if self.get("time_col") and self.get("time_col") in table and len(u):
            t_min = _to_minutes(table[self.get("time_col")],
                                self.get("activity_time_format"))
            if self.get("start_time"):
                ref = datetime.strptime(
                    self.get("start_time"), self.get("start_time_format")
                ).timestamp() / 60.0
            else:
                ref = float(t_min.max())
            half_life_min = self.get("time_decay_coeff") * 24 * 60
            w = w * np.power(2.0, -(ref - t_min) / half_life_min)

        affinity = np.zeros((n_users, n_items), np.float64)
        np.add.at(affinity, (u, it), w)

        # -- item-item similarity (SAR.scala:119-205) -------------------- #
        occurrence = np.zeros((n_users, n_items), np.float32)
        occurrence[u, it] = 1.0  # distinct (user, item)
        occ_dev = jnp.asarray(occurrence)
        cooccur = np.asarray(
            jax.jit(lambda b: b.T @ b)(occ_dev), np.float64
        )  # (I, I) on the MXU — the reference's breeze SparseMatrix product
        occ = np.diag(cooccur).copy()

        fn = self.get("similarity_function")
        with np.errstate(divide="ignore", invalid="ignore"):
            if fn == "jaccard":
                denom = occ[:, None] + occ[None, :] - cooccur
                sim = np.where(denom > 0, cooccur / denom, 0.0)
            elif fn == "lift":
                denom = occ[:, None] * occ[None, :]
                sim = np.where(denom > 0, cooccur / denom, 0.0)
            elif fn in ("cooccurrence", "cooccur"):
                sim = cooccur
            else:
                raise ValueError(f"unknown similarity_function {fn!r}")
        sim = np.where(cooccur >= self.get("support_threshold"), sim, 0.0)

        model = SARModel(
            user_col=self.get("user_col"), item_col=self.get("item_col"),
        )
        model.user_affinity = affinity.astype(np.float32)
        model.item_similarity = sim.astype(np.float32)
        model.seen = occurrence.astype(bool)
        return model


@register_stage
class SARModel(Model):
    """Scoring: affinity (U×I) @ similarity (I×I), top-k via lax.top_k
    (reference SARModel.scala:95-130 BlockMatrix multiply + top-k udf)."""

    user_col = Param("user", "indexed user id column", ptype=str)
    item_col = Param("item", "indexed item id column", ptype=str)
    prediction_col = Param("prediction", "predicted affinity column", ptype=str)

    user_affinity: np.ndarray | None = None    # (U, I) float32
    item_similarity: np.ndarray | None = None  # (I, I) float32
    seen: np.ndarray | None = None             # (U, I) bool

    # device copies of the host arrays, uploaded once and reused across
    # calls; None until first use and after _load_state
    _device_cache: "dict[str, Any] | None" = None

    # rows per device block in recommend_for_all_users: bounds peak device
    # memory at block×I instead of U×I
    USER_BLOCK = 4096

    def _device_arrays(self) -> dict[str, Any]:
        if self._device_cache is None:
            self._device_cache = {
                "affinity": jnp.asarray(self.user_affinity),
                "similarity": jnp.asarray(self.item_similarity),
                "seen": (jnp.asarray(self.seen)
                         if self.seen is not None else None),
            }
        return self._device_cache

    def invalidate_device_cache(self) -> None:
        self._device_cache = None

    def _scores(self) -> jnp.ndarray:
        dev = self._device_arrays()
        return _affinity_scores(dev["affinity"], dev["similarity"])

    def _transform(self, table: Table) -> Table:
        """Per (user, item) row: predicted affinity score. Gathers only the
        requested users' affinity rows — one (n_requested × I) matmul, never
        the full U×I score matrix."""
        u = np.asarray(table[self.get("user_col")], np.int64)
        it = np.asarray(table[self.get("item_col")], np.int64)
        n_u, n_i = self.user_affinity.shape
        valid = (u >= 0) & (u < n_u) & (it >= 0) & (it < n_i)
        pred = np.zeros(len(u), np.float64)
        if valid.any():
            dev = self._device_arrays()
            users, pos = np.unique(u[valid], return_inverse=True)
            rows = np.asarray(_affinity_scores(
                dev["affinity"][jnp.asarray(users)], dev["similarity"]))
            pred[valid] = rows[pos, it[valid]]
        return table.with_column(self.get("prediction_col"), pred)

    def recommend_for_all_users(self, k: int, remove_seen: bool = True,
                                user_block: int | None = None) -> Table:
        """Reference: SARModel.recommendForAllUsers (SARModel.scala:95-130).
        Returns Table{user, recommendations, ratings} with top-k item ids.

        Scores `user_block` users at a time so peak device memory is
        block×I rather than U×I; matmul rows and top_k are row-independent,
        so the blocked result is byte-identical to the single big matmul."""
        dev = self._device_arrays()
        n_users, n_items = self.user_affinity.shape
        k = min(k, n_items)
        block = user_block or self.USER_BLOCK
        mask_seen = remove_seen and dev["seen"] is not None
        vals_parts, idx_parts = [], []
        for lo in range(0, n_users, block):
            hi = min(lo + block, n_users)
            aff = dev["affinity"][lo:hi]
            if mask_seen:
                v, i = _block_topk_unseen(
                    aff, dev["similarity"], dev["seen"][lo:hi], k)
            else:
                v, i = _block_topk(aff, dev["similarity"], k)
            vals_parts.append(np.asarray(v, np.float64))
            idx_parts.append(np.asarray(i, np.int64))
        vals = (np.concatenate(vals_parts) if vals_parts
                else np.zeros((0, k), np.float64))
        idx = (np.concatenate(idx_parts) if idx_parts
               else np.zeros((0, k), np.int64))
        # users with fewer than k unseen items: top_k still returns the
        # -inf (seen) entries — mark them invalid (id -1) instead of
        # leaking seen items back as 0-rated recommendations
        invalid = ~np.isfinite(vals)
        idx = np.where(invalid, -1, idx)
        vals = np.where(invalid, 0.0, vals)
        return Table({
            self.get("user_col"): np.arange(n_users, dtype=np.float64),
            "recommendations": idx,
            "ratings": vals,
        })

    def _save_state(self) -> dict[str, Any]:
        return {
            "user_affinity": self.user_affinity,
            "item_similarity": self.item_similarity,
            "seen": self.seen.astype(np.uint8) if self.seen is not None else None,
        }

    def _load_state(self, state: dict[str, Any]) -> None:
        self.user_affinity = np.asarray(state["user_affinity"], np.float32)
        self.item_similarity = np.asarray(state["item_similarity"], np.float32)
        seen = state.get("seen")
        self.seen = None if seen is None else np.asarray(seen, bool)
        self.invalidate_device_cache()
