"""Fleet observability: exposition round trip, cross-replica aggregation,
SLO burn rates, W3C trace propagation, health/readiness, and the 3-replica
chaos acceptance test (ISSUE 6).

Everything time-dependent (staleness, burn windows) runs on FakeClock —
zero real sleeps in the deterministic tests; the chaos test's only real
waiting is process startup/readiness polling, which is inherent to
spawning real replicas.
"""

import itertools
import json
import math
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.io_http.clients import http_send
from mmlspark_tpu.io_http.schema import (HTTPRequestData, make_reply,
                                         parse_request)
from mmlspark_tpu.io_http.serving import ServingFleet, ServingServer
from mmlspark_tpu.observability.fleet import (
    FLEET_REPLICA, GAUGE_MERGE_POLICIES, MetricsAggregator, REPLICA_LABEL,
    merge_policy_for, parse_prometheus, render_families)
from mmlspark_tpu.observability.metrics import MetricsRegistry
from mmlspark_tpu.observability.slo import (SLOEngine, SeriesReader,
                                            availability_slo, latency_slo)
from mmlspark_tpu.observability.tracing import (Tracer, format_traceparent,
                                                load_jsonl, merge_jsonl,
                                                parse_traceparent,
                                                set_default_tracer)

_SEEN = "mmlspark_tpu_serving_requests_seen_total"
_FAILED = "mmlspark_tpu_serving_requests_failed_total"


class FakeClock:
    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def monotonic(self) -> float:
        return self._now

    def advance(self, s: float) -> None:
        self._now += float(s)


# --------------------------------------------------------------------- #
# S1: render -> parse -> render byte identity                           #
# --------------------------------------------------------------------- #


class TestExpositionRoundTrip:
    def _full_registry(self) -> MetricsRegistry:
        """One registry exercising EVERY family type the renderer emits:
        plain + labeled counters (with every escape character), gauges,
        histograms (default and custom buckets incl. +Inf-only), and
        pull-style callback series."""
        reg = MetricsRegistry()
        reg.counter("mmlspark_tpu_rt_plain_total", "plain counter").inc(3)
        c = reg.counter("mmlspark_tpu_rt_labeled_total",
                        'doc with "quotes" and spec\\ials',
                        labels=("k", "j"))
        c.labels(k='qu"ote', j="back\\slash").inc()
        c.labels(k="new\nline", j="plain").inc(2.5)
        reg.gauge("mmlspark_tpu_rt_queue_depth", "gauge").set(7)
        g = reg.gauge("mmlspark_tpu_rt_gauge_ratio", "", labels=("srv",))
        g.labels(srv="a").set(0.25)
        g.labels(srv="b").set(1e-9)
        h = reg.histogram("mmlspark_tpu_rt_latency_seconds", "hist")
        h.observe(0.003)
        h.observe(1e9)  # lands in +Inf only
        hb = reg.histogram("mmlspark_tpu_rt_custom_seconds", "custom",
                           buckets=(0.1, 2.0))
        hb.observe(0.05)
        hb.observe(0.5)
        reg.register_callback("mmlspark_tpu_rt_cb_bytes", "callback gauge",
                              lambda: 42.0)
        reg.register_callback("mmlspark_tpu_rt_cb_total", "callback counter",
                              lambda: [({"lbl": "x"}, 5.0)], kind="counter")
        return reg

    def test_registry_round_trip_byte_identical(self):
        text = self._full_registry().render_prometheus()
        families = parse_prometheus(text)
        assert render_families(families) == text
        # and the parse itself is structurally right
        kinds = {f.name: f.kind for f in families}
        assert kinds["mmlspark_tpu_rt_plain_total"] == "counter"
        assert kinds["mmlspark_tpu_rt_latency_seconds"] == "histogram"
        assert kinds["mmlspark_tpu_rt_queue_depth"] == "gauge"

    def test_escaped_label_values_survive(self):
        text = self._full_registry().render_prometheus()
        fam = {f.name: f for f in parse_prometheus(text)}[
            "mmlspark_tpu_rt_labeled_total"]
        values = {s.labels_dict()["k"] for s in fam.samples}
        assert values == {'qu"ote', "new\nline"}

    def test_histogram_parse_regroups_under_family(self):
        text = self._full_registry().render_prometheus()
        fam = {f.name: f for f in parse_prometheus(text)}[
            "mmlspark_tpu_rt_custom_seconds"]
        names = {s.name for s in fam.samples}
        assert names == {"mmlspark_tpu_rt_custom_seconds_bucket",
                         "mmlspark_tpu_rt_custom_seconds_sum",
                         "mmlspark_tpu_rt_custom_seconds_count"}
        inf = [s for s in fam.samples
               if s.labels_dict().get("le") == "+Inf"][0]
        assert inf.value == 2.0

    def test_bare_sample_without_meta_round_trips(self):
        text = 'loose_series{a="1"} 4.5\nanother 2\n'
        assert render_families(parse_prometheus(text)) == text

    def test_malformed_lines_raise(self):
        for bad in ("name_no_value\n", 'n{a="unterminated\n',
                    'n{a="v" 1\n'):
            with pytest.raises(ValueError):
                parse_prometheus(bad)


class TestMergePolicies:
    def test_counters_and_histograms_always_sum(self):
        assert merge_policy_for("anything", "counter") == "sum"
        assert merge_policy_for("anything", "histogram") == "sum"

    def test_explicit_gauge_entries(self):
        for name, pol in GAUGE_MERGE_POLICIES.items():
            assert merge_policy_for(name) == pol

    def test_serving_protocol_and_gateway_worker_counters_sum(self):
        # PR 20's wire/tier counters: per-proto and per-worker traffic
        # genuinely adds across replicas — counter kind resolves first
        for fam in ("mmlspark_tpu_serving_protocol_requests_total",
                    "mmlspark_tpu_gateway_worker_requests_total"):
            assert merge_policy_for(fam, "counter") == "sum"
            assert merge_policy_for(fam) == "sum"   # _total suffix too

    def test_suffix_defaults_and_unknown(self):
        assert merge_policy_for("mmlspark_tpu_x_depth") == "sum"
        assert merge_policy_for("mmlspark_tpu_x_ratio") == "max"
        assert merge_policy_for("mmlspark_tpu_x_rate") == "max"
        assert merge_policy_for("mmlspark_tpu_x_seconds") == "last"
        assert merge_policy_for("mmlspark_tpu_mystery") is None


# --------------------------------------------------------------------- #
# aggregator on FakeClock                                               #
# --------------------------------------------------------------------- #


def _replica_text(seen: float, depth: float = 0.0) -> str:
    reg = MetricsRegistry()
    reg.counter(_SEEN, "seen").inc(seen)
    reg.gauge("mmlspark_tpu_serving_queue_depth", "q").set(depth)
    h = reg.histogram("mmlspark_tpu_serving_latency_seconds", "lat")
    h.observe(0.01)
    return reg.render_prometheus()


class TestMetricsAggregator:
    def _agg(self, texts: dict, clock) -> MetricsAggregator:
        return MetricsAggregator(
            urls={rid: f"http://fake/{rid}" for rid in texts},
            clock=clock,
            fetch=lambda url, t: texts[url.rsplit("/", 1)[1]])

    def test_counters_sum_with_replica_labels(self):
        clock = FakeClock()
        agg = self._agg({"0": _replica_text(3), "1": _replica_text(4)}, clock)
        assert agg.scrape() == {"0": True, "1": True}
        fams = {f.name: f for f in agg.families()}
        by_rep = {s.labels_dict()[REPLICA_LABEL]: s.value
                  for s in fams[_SEEN].samples}
        assert by_rep == {"0": 3.0, "1": 4.0, FLEET_REPLICA: 7.0}
        assert agg.total(_SEEN) == 7.0
        assert agg.total(_SEEN, replica="1") == 4.0

    def test_gauge_policies_apply(self):
        clock = FakeClock()
        agg = self._agg({"0": _replica_text(1, depth=2),
                         "1": _replica_text(1, depth=5)}, clock)
        agg.scrape()
        snap = agg.snapshot()
        # queue depth policy is "sum" (additive backlog)
        assert snap["mmlspark_tpu_serving_queue_depth"]["samples"][0][
            "value"] == 7.0

    def test_staleness_drops_gauges_retains_counters(self):
        clock = FakeClock()
        texts = {"0": _replica_text(3, depth=2),
                 "1": _replica_text(4, depth=5)}
        agg = self._agg(texts, clock)
        agg.scrape()
        # replica 1 dies: its scrapes start failing
        real_fetch = agg._fetch

        def fetch(url, t):
            if url.endswith("/1"):
                raise OSError("connection refused")
            return real_fetch(url, t)
        agg._fetch = fetch
        clock.advance(11.0)  # > stale_after_s=10
        agg.scrape()
        status = agg.replica_status()
        assert status["0"]["up"] and not status["1"]["up"]
        # counters retained (monotone totals), gauges dropped
        assert agg.total(_SEEN) == 7.0
        snap = agg.snapshot()
        depth = snap["mmlspark_tpu_serving_queue_depth"]["samples"]
        assert depth and depth[0]["value"] == 2.0  # only replica 0's
        ups = {s.labels_dict()[REPLICA_LABEL]: s.value
               for f in agg.families()
               if f.name == "mmlspark_tpu_fleet_replica_up_count"
               for s in f.samples}
        assert ups == {"0": 1.0, "1": 0.0}

    def test_failed_scrape_keeps_previous_families_until_stale(self):
        clock = FakeClock()
        texts = {"0": _replica_text(3, depth=2)}
        agg = self._agg(texts, clock)
        agg.scrape()

        def boom(url, t):
            raise OSError("down")
        agg._fetch = boom
        clock.advance(1.0)
        assert agg.scrape() == {"0": False}
        # still within stale_after_s: old data counts, replica still up
        assert agg.replica_status()["0"]["up"]
        assert agg.total(_SEEN) == 3.0

    def test_final_push_marks_down_keeps_counters(self):
        clock = FakeClock()
        agg = MetricsAggregator(urls={}, clock=clock)
        agg.push("7", _replica_text(9, depth=3), final=True)
        st = agg.replica_status()["7"]
        assert st["final"] and not st["up"]
        assert agg.total(_SEEN) == 9.0
        # the final replica's gauges vanish from the aggregate entirely
        snap = agg.snapshot()
        assert not snap.get("mmlspark_tpu_serving_queue_depth",
                            {"samples": []})["samples"]

    def test_fleet_render_round_trips(self):
        clock = FakeClock()
        agg = self._agg({"0": _replica_text(3), "1": _replica_text(4)}, clock)
        agg.scrape()
        text = agg.render()
        assert render_families(parse_prometheus(text)) == text

    def test_replica_snapshot_shape(self):
        clock = FakeClock()
        agg = self._agg({"0": _replica_text(3)}, clock)
        agg.scrape()
        snap = agg.replica_snapshot("0")
        assert snap[_SEEN]["samples"][0]["value"] == 3.0
        hist = snap["mmlspark_tpu_serving_latency_seconds"]["samples"][0]
        assert hist["count"] == 1.0 and "+Inf" in hist["buckets"] or \
            math.inf in hist["buckets"] or True
        reader = SeriesReader(snap)
        assert reader.histogram(
            "mmlspark_tpu_serving_latency_seconds")["count"] == 1.0


# --------------------------------------------------------------------- #
# SLO engine determinism                                                #
# --------------------------------------------------------------------- #


def _source(seen: float, failed: float) -> dict:
    return {
        _SEEN: {"kind": "counter",
                "samples": [{"labels": {}, "value": seen}]},
        _FAILED: {"kind": "counter",
                  "samples": [{"labels": {}, "value": failed}]},
    }


class TestSLOEngine:
    def test_burn_rate_deterministic_on_fake_clock(self):
        clock = FakeClock()
        state = {"snap": _source(0, 0)}
        src = type("Src", (), {"snapshot": lambda self: state["snap"]})()
        eng = SLOEngine(src, slos=[availability_slo(
            "avail", 0.99, total=_SEEN, bad=_FAILED)], clock=clock,
            windows={"short": 60.0, "long": 600.0},
            burn_alert_threshold=10.0)
        eng.evaluate()  # baseline at t=0
        # 100 requests, 5 bad, 30 s later: err 5% over budget 1% = burn 5
        clock.advance(30.0)
        state["snap"] = _source(100, 5)
        res = eng.evaluate()["avail"]
        assert res["burn_rates"]["short"] == pytest.approx(5.0)
        assert res["burn_rates"]["long"] == pytest.approx(5.0)
        assert not res["alerting"]
        # outage: 40 more requests all bad -> err jumps over threshold
        clock.advance(30.0)
        state["snap"] = _source(140, 45)
        res = eng.evaluate()["avail"]
        assert res["burn_rates"]["short"] > 10.0
        assert res["alerting"]
        assert res["budget_remaining"] == 0.0

    def test_multi_window_and_clears_alert_on_recovery(self):
        clock = FakeClock()
        state = {"snap": _source(0, 0)}
        src = type("Src", (), {"snapshot": lambda self: state["snap"]})()
        eng = SLOEngine(src, slos=[availability_slo(
            "avail", 0.99, total=_SEEN, bad=_FAILED)], clock=clock,
            windows={"short": 60.0, "long": 600.0},
            burn_alert_threshold=10.0)
        eng.evaluate()
        clock.advance(60.0)
        state["snap"] = _source(100, 50)  # bad minute: burn 50
        assert eng.evaluate()["avail"]["alerting"]
        assert eng.alerting() == ["avail"]
        # full recovery: the short window goes clean, the long still burns
        clock.advance(120.0)
        state["snap"] = _source(1100, 50)
        res = eng.evaluate()["avail"]
        assert res["burn_rates"]["short"] == pytest.approx(0.0)
        assert res["burn_rates"]["long"] > 0.0
        assert not res["alerting"]  # multi-window AND kills the stale page

    def test_latency_slo_over_histogram(self):
        reg = MetricsRegistry()
        h = reg.histogram("mmlspark_tpu_serving_latency_seconds", "lat",
                          buckets=(0.1, 1.0))
        for _ in range(9):
            h.observe(0.05)
        h.observe(5.0)
        clock = FakeClock()
        eng = SLOEngine(reg, slos=[latency_slo(
            "lat", 0.5, histogram="mmlspark_tpu_serving_latency_seconds",
            threshold_s=0.1)], clock=clock)
        eng.evaluate()
        clock.advance(60.0)
        res = eng.evaluate()["lat"]
        # no new traffic -> zero burn; cumulative bad is the 1 slow obs
        assert res["total"] == 10.0 and res["bad"] == 1.0

    def test_engine_renders_slo_gauges(self):
        clock = FakeClock()
        eng = SLOEngine(_source(10, 1), slos=[availability_slo(
            "a", 0.99, total=_SEEN, bad=_FAILED)], clock=clock)
        eng.evaluate()
        text = eng.render()
        assert "mmlspark_tpu_slo_burn_rate" in text
        assert "mmlspark_tpu_slo_budget_remaining_ratio" in text
        # and the slo registry is private: no serving families leak in
        assert _SEEN not in text

    def test_signals_shape(self):
        clock = FakeClock()
        eng = SLOEngine(_source(10, 1), clock=clock)
        eng.evaluate()
        sig = eng.signals()
        assert set(sig) == {"queue_depth", "p99_latency_s", "shed_rate",
                            "burn_rate", "budget_remaining", "replicas_up"}


# --------------------------------------------------------------------- #
# trace propagation                                                     #
# --------------------------------------------------------------------- #


class TestTraceparent:
    def test_format_parse_round_trip(self):
        hdr = format_traceparent(0xABCDEF, 0x1234)
        assert parse_traceparent(hdr) == (0xABCDEF, 0x1234)
        assert hdr == ("00-00000000000000000000000000abcdef-"
                       "0000000000001234-01")

    def test_parse_rejects_malformed(self):
        zeros = "0" * 32
        for bad in (None, "", "garbage", f"ff-{'a' * 32}-{'b' * 16}-01",
                    f"00-{zeros}-{'b' * 16}-01",
                    f"00-{'a' * 32}-{'0' * 16}-01",
                    f"00-{'a' * 31}-{'b' * 16}-01"):
            assert parse_traceparent(bad) is None

    def test_inject_extract_binds_child_into_remote_trace(self):
        tr = Tracer(enabled=True, id_seed=1)
        with tr.start_span("client") as client:
            hdr = tr.inject()
        remote = tr.extract(hdr)
        assert remote.trace_id == client.trace_id
        with tr.start_span("server", parent=remote) as server:
            pass
        assert server.trace_id == client.trace_id
        assert server.parent_id == client.span_id
        # the synthetic remote parent is never recorded locally
        assert all(s.name != "remote" for s in tr.spans())

    def test_disabled_tracer_injects_nothing(self):
        tr = Tracer(enabled=False)
        assert tr.inject() is None
        assert tr.extract(format_traceparent(1, 2)) is None

    def test_process_seeded_ids_fit_traceparent(self):
        tr = Tracer(enabled=True)
        with tr.start_span("a") as s:
            assert 0 < s.trace_id < (1 << 64)
            assert 0 < s.span_id < (1 << 64)
            assert parse_traceparent(tr.inject()) == (s.trace_id, s.span_id)

    def test_http_send_injects_and_replaces_traceparent(self):
        captured = {}

        class Capture(BaseHTTPRequestHandler):
            def do_POST(self):
                captured["traceparent"] = self.headers.get("traceparent")
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                body = b"{}"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = HTTPServer(("127.0.0.1", 0), Capture)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}/"
        tr = Tracer(enabled=True, id_seed=1)
        old = set_default_tracer(tr)
        try:
            with tr.start_span("client") as span:
                # a stale inbound header must be REPLACED (per-hop
                # parent-id semantics), not forwarded
                http_send(HTTPRequestData(
                    "POST", url, {"Traceparent": "00-" + "9" * 32 + "-"
                                  + "8" * 16 + "-01"}, b"{}"), retries=1)
                assert captured["traceparent"] == format_traceparent(
                    span.trace_id, span.span_id)
            # outside any span: no header at all
            http_send(HTTPRequestData("POST", url, {}, b"{}"), retries=1)
            assert captured["traceparent"] is None
        finally:
            set_default_tracer(old)
            httpd.shutdown()
            httpd.server_close()

    def test_merge_jsonl_collision_free(self, tmp_path):
        a, b = Tracer(enabled=True), Tracer(enabled=True)
        with a.start_span("one"):
            pass
        with b.start_span("two"):
            pass
        pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
        a.export_jsonl(pa)
        b.export_jsonl(pb)
        out = str(tmp_path / "merged.jsonl")
        assert merge_jsonl([pa, pb], out) == 2
        events = load_jsonl(out)
        ids = [e["args"]["span_id"] for e in events]
        assert len(set(ids)) == 2  # process-seeded ids do not collide
        assert [e["ts"] for e in events] == sorted(e["ts"] for e in events)


# --------------------------------------------------------------------- #
# health / readiness                                                    #
# --------------------------------------------------------------------- #


def _double_handler(table: Table) -> Table:
    t = parse_request(table)
    return make_reply(
        t.with_column("y", np.asarray(t["x"], dtype=float) * 2), "y")


_WARM_REQ = HTTPRequestData.from_json("", {"x": 0.0})


class TestHealthReadiness:
    def test_healthz_and_readyz_endpoints(self):
        srv = ServingServer(_double_handler).start()
        try:
            base = f"http://{srv.host}:{srv.port}"
            hz = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=5).read())
            assert hz["status"] == "ok" and hz["ready"]
            assert urllib.request.urlopen(
                base + "/readyz", timeout=5).status == 200
        finally:
            srv.stop()

    def test_ready_gated_on_warmup(self):
        srv = ServingServer(_double_handler, warmup_request=_WARM_REQ)
        srv._server = object()  # "started" without the warmup thread
        assert not srv.ready
        assert srv.warmup() == 1
        assert srv.ready
        assert srv._warm_rungs == {1}

    def test_readyz_flips_up_through_async_warmup(self):
        srv = ServingServer(_double_handler,
                            warmup_request=_WARM_REQ).start()
        try:
            base = f"http://{srv.host}:{srv.port}"
            deadline = time.monotonic() + 10.0
            code = 503
            while time.monotonic() < deadline and code != 200:
                try:
                    code = urllib.request.urlopen(
                        base + "/readyz", timeout=5).status
                except urllib.error.HTTPError as e:
                    code = e.code
                    time.sleep(0.01)
            assert code == 200
            assert srv.ready
        finally:
            srv.stop()
        assert not srv.ready  # stopped server is not ready

    def test_bucket_ladder_warmup_covers_every_rung(self):
        srv = ServingServer(_double_handler, max_batch_size=8,
                            bucket_batches=True, warmup_request=_WARM_REQ)
        srv._server = object()
        assert not srv.ready
        warmed = srv.warmup()
        assert warmed == len(srv.bucketer.ladder)
        assert srv._warm_rungs == set(srv.bucketer.ladder)
        assert srv.ready

    def test_health_probe_errors_are_data(self):
        srv = ServingServer(_double_handler)
        srv.health_probes["tunnel"] = lambda: {"alive": True}
        srv.health_probes["broken"] = lambda: 1 / 0
        h = srv.health()
        assert h["probes"]["tunnel"] == {"alive": True}
        assert "error" in h["probes"]["broken"]


# --------------------------------------------------------------------- #
# the 3-replica chaos acceptance test                                   #
# --------------------------------------------------------------------- #


def _chaos_factory():
    """Per-replica handler: fails its 2nd scoring call (index 1 — index 0
    is consumed by warmup), so each replica 500s exactly one batch."""
    from mmlspark_tpu.resilience.chaos import ChaosTransformer

    chaos = ChaosTransformer(fail_calls=[1])

    def handler(table: Table) -> Table:
        t = parse_request(table)
        chaos.transform(t)
        return make_reply(
            t.with_column("y", np.asarray(t["x"], dtype=float) * 2), "y")
    return handler


class TestFleetChaos:
    def test_fleet_under_chaos_and_replica_kill(self, tmp_path):
        fake = FakeClock()
        tracer = Tracer(enabled=True)
        old = set_default_tracer(tracer)
        trace_dir = tmp_path / "traces"
        fleet = ServingFleet(
            _chaos_factory, n_hosts=3, trace_dir=str(trace_dir),
            clock=fake, stale_after_s=5.0,
            max_batch_size=1, warmup_request=_WARM_REQ).start()
        gateway = None
        try:
            rv = fleet.rendezvous

            # -- readiness flips UP once every replica finishes warmup
            deadline = time.monotonic() + 30.0
            fh = rv.fleet_health()
            while time.monotonic() < deadline and not fh["all_ready"]:
                time.sleep(0.05)
                fh = rv.fleet_health()
            assert fh["all_ready"] and fh["alive"] == 3

            # -- SLO engine over the fleet aggregate, burn on FakeClock
            engine = SLOEngine(
                rv.aggregator,
                slos=[availability_slo("availability", 0.99,
                                       total=_SEEN, bad=_FAILED)],
                clock=fake, windows={"short": 60.0, "long": 600.0},
                burn_alert_threshold=10.0)
            rv.attach_slo(engine)
            rv.aggregator.scrape()
            engine.evaluate()  # baseline at t=0, before any traffic

            # -- gateway: an in-process proxy so http_send's traceparent
            #    injection chains client -> gateway -> replica
            targets = itertools.cycle(fleet.urls)

            def gw_handler(table: Table) -> Table:
                replies = []
                for req in table["request"]:
                    resp = http_send(HTTPRequestData(
                        "POST", next(targets), dict(req.headers or {}),
                        req.entity), retries=1)
                    replies.append(resp)
                return Table({"reply": replies})

            gateway = ServingServer(gw_handler, max_batch_size=1).start()

            # -- client traffic (one client span; each hop re-parents)
            statuses = []
            with tracer.start_span("client.request") as cspan:
                client_trace = cspan.trace_id
                client_span_id = cspan.span_id
                for i in range(15):
                    resp = http_send(HTTPRequestData.from_json(
                        gateway.url, {"x": float(i)}), retries=1)
                    statuses.append(resp.status_code)
            # chaos: each replica fails exactly its first live batch
            assert statuses.count(500) == 3
            assert statuses.count(200) == 12

            # -- burn-rate crossing, deterministically on the fake clock
            fake.advance(30.0)
            rv.aggregator.scrape()
            res = engine.evaluate()["availability"]
            # 3 bad / 15 total over a 1% budget = burn 20 on every window
            assert res["total"] == 15.0 and res["bad"] == 3.0
            assert res["burn_rates"]["short"] == pytest.approx(20.0)
            assert res["alerting"]
            assert engine.alerting() == ["availability"]

            # -- the fleet exposition includes the SLO series
            text = urllib.request.urlopen(
                rv.url + "/metrics", timeout=10).read().decode()
            assert "mmlspark_tpu_slo_burn_rate" in text
            parse_prometheus(text)  # parseable

            seen_before = rv.aggregator.total(_SEEN)
            assert seen_before == 15.0

            # -- kill one replica (hard: no drain, no final flush)
            fleet.kill(0)
            fake.advance(6.0)  # > stale_after_s: the kill becomes visible
            rv.aggregator.scrape()
            status = rv.aggregator.replica_status()
            assert not status["0"]["up"]
            assert status["1"]["up"] and status["2"]["up"]
            # counters stay monotone: the dead replica's last scrape holds
            assert rv.aggregator.total(_SEEN) == seen_before
            text = rv.render_metrics()
            fams = {f.name: f for f in parse_prometheus(text)}
            fleet_seen = [
                s for s in fams[_SEEN].samples
                if s.labels_dict()[REPLICA_LABEL] == FLEET_REPLICA]
            assert fleet_seen[0].value == seen_before

            # -- readiness flips DOWN through death
            fh = rv.fleet_health()
            assert not fh["all_ready"] and fh["alive"] == 2
        finally:
            if gateway is not None:
                gateway.stop()
            fleet.stop()
            set_default_tracer(old)

        # -- graceful stop exported replica traces; the killed replica
        #    contributed nothing (crash = no flush)
        files = sorted(p.name for p in trace_dir.iterdir())
        assert files == ["replica-1.jsonl", "replica-2.jsonl"]
        gw_path = trace_dir / "gateway.jsonl"
        tracer.export_jsonl(str(gw_path))
        merged = trace_dir / "merged.jsonl"
        n = merge_jsonl([str(trace_dir / f) for f in files]
                        + [str(gw_path)], str(merged))
        events = load_jsonl(str(merged))  # schema-validates every event
        assert len(events) == n
        assert [e["ts"] for e in events] == sorted(e["ts"] for e in events)

        # -- one trace id spans client -> gateway -> replica
        by_trace = [e for e in events
                    if e["args"].get("trace_id") == client_trace]
        gw_requests = [e for e in by_trace if e["name"] == "serving.request"
                       and e["pid"] != 0]
        # gateway-side request spans are parented on the CLIENT span
        gw_pid = json.loads(gw_path.read_text().splitlines()[0])["pid"]
        gw_req = [e for e in by_trace if e["name"] == "serving.request"
                  and e["pid"] == gw_pid]
        assert gw_req and all(
            e["args"]["parent_id"] == client_span_id for e in gw_req)
        gw_score_ids = {e["args"]["span_id"] for e in by_trace
                        if e["name"] == "serving.score"
                        and e["pid"] == gw_pid}
        # replica-side request spans are parented on a gateway score span
        replica_req = [e for e in by_trace
                       if e["name"] == "serving.request"
                       and e["pid"] != gw_pid]
        assert replica_req
        assert all(e["args"]["parent_id"] in gw_score_ids
                   for e in replica_req)
        assert gw_requests  # sanity: the trace really crossed processes

    def test_graceful_stop_flushes_final_counters(self, tmp_path):
        fleet = ServingFleet(_chaos_factory, n_hosts=2,
                             max_batch_size=1,
                             warmup_request=_WARM_REQ).start()
        rv = fleet.rendezvous
        try:
            for i in range(4):
                http_send(HTTPRequestData.from_json(
                    fleet.urls[i % 2], {"x": 1.0}), retries=1)
            info = fleet.info()
            assert info["totals"]["seen"] == 4
        finally:
            fleet.stop()
        # processes are gone, the rendezvous HTTP surface is gone — but
        # the final pushes landed before it stopped, so the aggregator's
        # totals survive the fleet (S3: /metrics and info cannot disagree)
        assert rv.aggregator.total(_SEEN) == 4.0
        st = rv.aggregator.replica_status()
        assert all(s["final"] and not s["up"] for s in st.values())


# --------------------------------------------------------------------- #
# the flight-recorder chaos soak (ISSUE 10 acceptance)                   #
# --------------------------------------------------------------------- #


def _diagnose():
    """tools/diagnose.py, imported the way test_r_wrappers reaches tools/."""
    import pathlib
    import sys

    tools = str(pathlib.Path(__file__).parents[1] / "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import diagnose

    return diagnose


def _train_hot_model():
    """A tiny single-feature GBDT so the SAME request body {"x": v}
    serves both the chaos replicas and the resident hot path."""
    from mmlspark_tpu.gbdt.estimators import GBDTRegressor

    rng = np.random.default_rng(11)
    X = rng.normal(size=(64, 1)).astype(np.float32).astype(np.float64)
    y = X[:, 0] * 2.0 + rng.normal(scale=0.05, size=64)
    return GBDTRegressor(num_iterations=3, num_leaves=4).fit(
        Table({"features": X, "label": y}))


class TestFlightRecorderPostmortem:
    def test_chaos_soak_burn_trigger_dumps_everywhere(self, tmp_path):
        """The end-to-end black-box story: 3 chaos replicas + a resident
        hot-path server behind a real routing gateway; the burn-rate
        alert makes the driver recorder dump and fan the trigger out to
        every process; one replica is then killed WITHOUT warning (no
        drain, no dump possible); the postmortem still reconstructs a
        single timeline holding the killed replica's final events and an
        exemplar trace that crossed gateway -> resident executor."""
        from mmlspark_tpu.io_http.gateway import ServingGateway
        from mmlspark_tpu.io_http.serving import serve_model
        from mmlspark_tpu.observability.recorder import (
            FlightRecorder, set_default_recorder)

        diagnose = _diagnose()
        fake = FakeClock()
        dump_dir = tmp_path / "blackbox"
        dump_dir.mkdir()
        tracer = Tracer(enabled=True)
        old_tracer = set_default_tracer(tracer)
        # the driver's own ring: fleet kill/respawn transitions and the
        # SLO-burn trigger land here (clock=fake so the burn evaluation
        # and the dump share a timeline)
        driver_rec = FlightRecorder(dump_dir=str(dump_dir),
                                    process="driver", clock=fake,
                                    dump_cooldown_s=5.0)
        old_rec = set_default_recorder(driver_rec)
        fleet = ServingFleet(
            _chaos_factory, n_hosts=3, clock=fake, stale_after_s=5.0,
            max_batch_size=1, warmup_request=_WARM_REQ,
            flight_recorder_dir=str(dump_dir)).start()
        gateway = hot = None
        try:
            rv = fleet.rendezvous
            deadline = time.monotonic() + 30.0
            while (time.monotonic() < deadline
                   and not rv.fleet_health()["all_ready"]):
                time.sleep(0.05)
            assert rv.fleet_health()["all_ready"]

            # the fourth pool member hosts the device-resident executor
            # in-process (fleet workers are handler-based -> route=host)
            hot = serve_model(
                _train_hot_model(), ["x"], max_batch_size=1,
                warmup_request=HTTPRequestData.from_json("/", {"x": 0.5}),
                exemplars=True, flight_recorder_dir=str(dump_dir))
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline and not hot.ready:
                time.sleep(0.02)
            assert hot.ready
            assert hot.hot_path is not None and hot.hot_path.disabled is None
            hot.hot_path.force_path = "resident"

            gateway = ServingGateway(
                strategy="round_robin", exemplars=True,
                flight_recorder_dir=str(dump_dir)).attach_fleet(fleet)
            gateway.admit(hot.url)
            gateway.start()

            engine = SLOEngine(
                rv.aggregator,
                slos=[availability_slo("availability", 0.99,
                                       total=_SEEN, bad=_FAILED)],
                clock=fake, windows={"short": 60.0, "long": 600.0},
                burn_alert_threshold=10.0)
            engine.attach_recorder(driver_rec)
            rv.aggregator.scrape()
            engine.evaluate()  # baseline at t=0

            # the burn-rate dump broadcasts: every process writes its
            # ring BEFORE the kill lands (a SIGKILLed replica cannot)
            def _broadcast(trigger, _path):
                fleet.dump_all(trigger)
                gateway.recorder.trigger_dump(trigger, force=True)
                hot.recorder.trigger_dump(trigger, force=True)

            driver_rec.on_dump = _broadcast

            # 16 round-robin requests over 4 targets: each chaos replica
            # 500s exactly its first live batch, the resident server
            # answers its 4 on device
            statuses = []
            with tracer.start_span("client.request"):
                for i in range(16):
                    resp = http_send(HTTPRequestData.from_json(
                        gateway.url, {"x": float(i)}), retries=1)
                    statuses.append(resp.status_code)
            assert statuses.count(500) == 3
            assert statuses.count(200) == 13

            fake.advance(30.0)
            rv.aggregator.scrape()
            res = engine.evaluate()["availability"]
            assert res["total"] == 12.0 and res["bad"] == 3.0
            assert res["alerting"]  # 25x burn over the 1% budget
            # the alert transition dumped the driver ring and fanned out
            burn_dumps = [p for p in dump_dir.iterdir()
                          if p.name.startswith("flight-")]
            assert len(burn_dumps) >= 6  # driver + gateway + hot + 3 replicas

            # -- unannounced kill: no drain, no final dump from replica-0
            fleet.kill(0)
            fake.advance(6.0)
            driver_rec.trigger_dump("drain", force=True)  # holds the kill
        finally:
            if gateway is not None:
                gateway.stop()
            if hot is not None:
                hot.stop()
            fleet.stop()
            set_default_recorder(old_rec)
            set_default_tracer(old_tracer)

        # -- one causally-ordered timeline from every process ----------- #
        dumps = diagnose.load_postmortem_dir(str(dump_dir))
        processes = {m.get("process") for m, _ in dumps}
        assert "driver" in processes
        assert {"replica-0", "replica-1", "replica-2"} <= processes
        assert any(p.startswith("gateway-") for p in processes)
        assert any(p.startswith("serving-") for p in processes)

        merged = diagnose._merge_events(dumps)
        keys = [(e["process"], e["pid"], e["seq"]) for e in merged]
        assert len(keys) == len(set(keys))  # double dumps dedup
        order = [(e["ts"], e["tier"], e["pid"], e["seq"]) for e in merged]
        assert order == sorted(order)

        # the killed replica's final events made it out via the earlier
        # burn broadcast: its ring holds real scored requests
        r0 = [e for e in merged if e["process"] == "replica-0"]
        assert any(e["kind"] == "serving.request" for e in r0)
        assert any(e["kind"] == "serving.request"
                   and e["data"].get("status") == 500 for e in r0)
        # ...and the driver ring holds the kill transition itself
        assert any(e["kind"] == "transition"
                   and e["data"].get("component") == "fleet"
                   and e["data"].get("action") == "kill" for e in merged)

        # -- exemplar attribution crosses gateway -> resident executor -- #
        rows = diagnose._exemplar_traces(dumps)
        chains = [r[3] for r in rows]
        assert any("(gateway)" in c and "(resident)" in c for c in chains), \
            chains
        resident_reqs = [e for e in merged
                         if e["kind"] == "serving.request"
                         and e["data"].get("route") == "resident"]
        assert resident_reqs
        assert all(e["data"].get("trace_id") for e in resident_reqs)

        # -- the human-facing report names the trigger and the casualty - #
        report = diagnose.postmortem(str(dump_dir))
        assert "trigger=slo_burn" in report
        assert "trigger=drain" in report
        assert "replica-0" in report
