"""Core runtime tests: Table, Params, Pipeline, serialization, mesh."""

import numpy as np
import pytest

from mmlspark_tpu.core import (
    Table,
    Param,
    Params,
    ServiceParam,
    Transformer,
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    pipeline_model,
    Timer,
    register_stage,
    save_stage,
    load_stage,
    registry,
    find_unused_column_name,
)


# -- Table ------------------------------------------------------------------
class TestTable:
    def test_construct_and_access(self):
        t = Table({"a": [1, 2, 3], "b": ["x", "y", "z"]})
        assert t.num_rows == 3
        assert t.columns == ["a", "b"]
        assert isinstance(t["a"], np.ndarray)
        assert isinstance(t["b"], list)

    def test_ragged_rejected(self):
        with pytest.raises(ValueError):
            Table({"a": [1, 2], "b": [1]})

    def test_vector_column(self):
        t = Table({"v": np.ones((4, 8))})
        assert t["v"].shape == (4, 8)
        assert t.num_rows == 4

    def test_functional_updates(self):
        t = Table({"a": [1, 2]})
        t2 = t.with_column("b", [3.0, 4.0])
        assert "b" not in t and "b" in t2
        t3 = t2.rename({"a": "c"})
        assert set(t3.columns) == {"c", "b"}
        t4 = t2.drop("a")
        assert t4.columns == ["b"]
        assert t2.select("b").columns == ["b"]

    def test_gather_filter_concat_split(self):
        t = Table({"a": np.arange(10), "s": [str(i) for i in range(10)]})
        g = t.gather([1, 3, 5])
        assert g["a"].tolist() == [1, 3, 5]
        assert g["s"] == ["1", "3", "5"]
        f = t.filter(lambda r: r["a"] % 2 == 0)
        assert f["a"].tolist() == [0, 2, 4, 6, 8]
        c = g.concat(f)
        assert c.num_rows == 8
        left, right = t.split(0.7, seed=1)
        assert left.num_rows == 7 and right.num_rows == 3
        assert sorted(left["a"].tolist() + right["a"].tolist()) == list(range(10))

    def test_from_rows_and_rows(self):
        rows = [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]
        t = Table.from_rows(rows)
        assert list(t.rows()) == rows

    def test_equals_tolerant(self):
        a = Table({"x": np.array([1.0, 2.0])})
        b = Table({"x": np.array([1.0, 2.0 + 1e-9])})
        c = Table({"x": np.array([1.0, 2.1])})
        assert a.equals(b)
        assert not a.equals(c)

    def test_meta(self):
        t = Table({"a": [1, 2]}).with_meta("a", {"category_values": ["p", "q"]})
        assert t.meta("a")["category_values"] == ["p", "q"]
        assert t.meta("missing_col") == {} if "missing_col" not in t else True

    def test_find_unused_column_name(self):
        t = Table({"x": [1], "x_1": [2]})
        assert find_unused_column_name("x", t) == "x_2"
        assert find_unused_column_name("y", t) == "y"


# -- Params -----------------------------------------------------------------
class _Demo(Params):
    alpha = Param(1.0, "alpha value", ptype=float, validator=lambda v: v >= 0)
    name = Param("d", "a name", ptype=str)
    svc = ServiceParam(None, "scalar-or-column")


class TestParams:
    def test_defaults_and_set(self):
        d = _Demo()
        assert d.get("alpha") == 1.0
        d.set(alpha=2.5)
        assert d.alpha == 2.5
        d.alpha = 3.0
        assert d.get("alpha") == 3.0

    def test_validation(self):
        d = _Demo()
        with pytest.raises(ValueError):
            d.set(alpha=-1.0)
        with pytest.raises(TypeError):
            d.set(name=42)
        with pytest.raises(KeyError):
            d.set(nope=1)

    def test_copy_isolated(self):
        d = _Demo(alpha=5.0)
        e = d.copy({"alpha": 6.0})
        assert d.alpha == 5.0 and e.alpha == 6.0

    def test_service_param_scalar_and_column(self):
        t = Table({"c": [10, 20, 30]})
        d = _Demo()
        assert d.resolve("svc", t) is None
        d.set(svc=7)
        assert d.resolve("svc", t) == [7, 7, 7]
        d.set_col(svc="c")
        assert d.resolve("svc", t) == [10, 20, 30]

    def test_explain(self):
        assert "alpha value" in _Demo().explain_params()


# -- Pipeline + serialization ----------------------------------------------
@register_stage
class _AddOne(Transformer):
    input_col = Param("x", "in", ptype=str)
    output_col = Param("y", "out", ptype=str)

    def _transform(self, table):
        return table.with_column(self.get("output_col"), table[self.get("input_col")] + 1)


@register_stage
class _MeanShift(Estimator):
    input_col = Param("x", "in", ptype=str)

    def _fit(self, table):
        m = _MeanShiftModel()
        m.set(input_col=self.get("input_col"))
        m.mean = float(np.mean(table[self.get("input_col")]))
        return m


@register_stage
class _MeanShiftModel(Model):
    input_col = Param("x", "in", ptype=str)
    mean: float = 0.0

    def _transform(self, table):
        c = self.get("input_col")
        return table.with_column(c, table[c] - self.mean)

    def _save_state(self):
        return {"mean": self.mean}

    def _load_state(self, state):
        self.mean = state["mean"]


class TestPipeline:
    def test_fit_transform(self):
        t = Table({"x": np.array([1.0, 2.0, 3.0])})
        pipe = Pipeline([_AddOne(), _MeanShift()])
        model = pipe.fit(t)
        assert isinstance(model, PipelineModel)
        out = model.transform(t)
        np.testing.assert_allclose(out["x"], [-1.0, 0.0, 1.0])
        assert out["y"].tolist() == [2.0, 3.0, 4.0]

    def test_pipeline_model_builder(self):
        pm = pipeline_model(_AddOne(), _AddOne(input_col="y", output_col="z"))
        out = pm.transform(Table({"x": np.array([0.0])}))
        assert out["z"].tolist() == [2.0]

    def test_timer(self):
        tm = Timer(_AddOne())
        out = tm.transform(Table({"x": np.array([1.0])}))
        assert out["y"].tolist() == [2.0]
        assert tm.last_elapsed is not None and tm.last_elapsed >= 0

    def test_save_load_roundtrip(self, tmp_path):
        t = Table({"x": np.array([1.0, 2.0, 3.0])})
        model = Pipeline([_AddOne(), _MeanShift()]).fit(t)
        p = str(tmp_path / "pm")
        save_stage(model, p)
        loaded = load_stage(p)
        assert loaded.transform(t).equals(model.transform(t))

    def test_save_load_unfitted_pipeline(self, tmp_path):
        pipe = Pipeline([_AddOne(output_col="q")])
        p = str(tmp_path / "pipe")
        pipe.save(p)
        loaded = load_stage(p)
        stages = loaded.get("stages")
        assert len(stages) == 1 and stages[0].get("output_col") == "q"

    def test_registry_contains_stages(self):
        names = {cls.__name__ for cls in registry().values()}
        assert {"Pipeline", "PipelineModel", "_AddOne"} <= names


# -- mesh -------------------------------------------------------------------
class TestMesh:
    def test_eight_virtual_devices(self):
        import jax

        assert jax.device_count() == 8

    def test_mesh_and_shard_rows(self, mesh8):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from mmlspark_tpu.parallel import DATA_AXIS, shard_rows

        x, n = shard_rows(np.arange(10, dtype=np.float32), mesh8)
        assert n == 10 and x.shape[0] == 16  # padded to multiple of 8

        @jax.jit
        def total(v):
            return jnp.sum(v)

        assert float(total(x)) == sum(range(10))

    def test_psum_over_mesh(self, mesh8):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        from mmlspark_tpu.parallel import DATA_AXIS, MODEL_AXIS

        x = np.ones((8, 4), np.float32)

        f = shard_map(
            lambda v: jax.lax.psum(jnp.sum(v), DATA_AXIS),
            mesh=mesh8,
            in_specs=P(DATA_AXIS, None),
            out_specs=P(),
        )
        assert float(f(x)) == 32.0


# -- review-driven regression tests ----------------------------------------
class TestReviewRegressions:
    def test_empty_gather_and_filter_chain(self):
        t = Table({"a": np.array([1.0, 2.0]), "s": ["x", "y"]})
        empty = t.filter(lambda r: False)
        assert empty.num_rows == 0
        assert empty.filter(lambda r: True).num_rows == 0
        assert t.gather([]).num_rows == 0

    def test_rename_collision_raises(self):
        t = Table({"a": [1], "b": [2]})
        with pytest.raises(ValueError):
            t.rename({"a": "b"})

    def test_numpy_scalar_state_roundtrip(self, tmp_path):
        m = _MeanShiftModel()
        m.mean = np.float64(3.5)  # natural np.mean result
        p = str(tmp_path / "m")
        save_stage(m, p)
        loaded = load_stage(p)
        assert isinstance(loaded.mean, float) and loaded.mean == 3.5

    def test_registry_qualified_names(self):
        from mmlspark_tpu.core import stage_class

        assert stage_class("Pipeline").__name__ == "Pipeline"
        assert stage_class(f"{Pipeline.__module__}.Pipeline") is stage_class("Pipeline")

    def test_with_column_drops_stale_meta(self):
        t = Table({"a": [1, 2]}).with_meta("a", {"category_values": ["p", "q"]})
        t2 = t.with_column("a", [3, 4])
        assert "category_values" not in t2.meta("a")
