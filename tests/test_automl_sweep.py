"""Distributed preemptible AutoML sweeps (ISSUE 16): HyperbandPruner
rung math on a private registry, the worker claim/heartbeat/status
protocol driven in-process, FindBestModel NaN handling, shared-bin
determinism, directed TargetPool sends, and the slow-tier chaos e2e —
a P=2 sweep with an unannounced SIGKILL mid-trial (and, separately, a
kill mid-sub-checkpoint fsync) must prune like, score like, and pick
the byte-identical winner of an undisturbed serial P=1 sweep, then
hot-swap that winner into a live gateway-fronted fleet under client
load with zero visible errors and byte-identical response bodies.

Pruner/protocol tests never spawn a process; the only real process work
is in the slow tier (real ServingFleet workers, real SIGKILL).
"""

import json
import math
import threading
import time

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import PipelineStage
from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.automl import FindBestModel
from mmlspark_tpu.automl.sweep import (
    HyperbandPruner,
    SweepModelFactory,
    SweepScheduler,
    SweepWorkerFactory,
    _score_gauge,
)
from mmlspark_tpu.gbdt import GBDTClassifier
from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.gbdt.shared_bins import (
    SharedBinContext,
    bin_counters,
    set_shared_bin_context,
)
from mmlspark_tpu.io_http.schema import HTTPRequestData
from mmlspark_tpu.observability.metrics import MetricsRegistry


def sweep_table(n=120, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4))
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    return Table({"features": x, "label": y})


def make_scheduler(ckpt, workers, chaos=None, **kw):
    est = GBDTClassifier(features_col="features", label_col="label",
                         num_iterations=8, num_leaves=4, seed=7)
    space = [{"learning_rate": lr, "num_leaves": nl}
             for lr in (0.05, 0.1, 0.2) for nl in (4, 8)]
    return SweepScheduler(
        [est], trials=[(0, p) for p in space],
        evaluation_metric="accuracy", label_col="label", num_folds=2,
        seed=0, checkpoint_dir=str(ckpt), workers=workers,
        pruner=HyperbandPruner(min_resource=4, max_resource=8, eta=2),
        rung_timeout_s=240.0, chaos=chaos, **kw)


# --------------------------------------------------------------------- #
# hyperband pruner (pure rung math, private registry, no processes)     #
# --------------------------------------------------------------------- #


class TestHyperbandPruner:
    def test_budget_geometry(self):
        assert HyperbandPruner(4, 8, eta=2).rung_budgets() == [4, 8]
        assert HyperbandPruner(2, 18, eta=3).rung_budgets() == [2, 6, 18]
        # final rung always trains at max_resource, even off-geometry
        assert HyperbandPruner(2, 7, eta=2).rung_budgets() == [2, 4, 7]
        assert HyperbandPruner(5, 5, eta=2).rung_budgets() == [5]

    def test_invalid_config_raises(self):
        with pytest.raises(ValueError):
            HyperbandPruner(10, 5)
        with pytest.raises(ValueError):
            HyperbandPruner(0, 5)
        with pytest.raises(ValueError):
            HyperbandPruner(1, 5, eta=1)

    def _seed(self, reg, rung, scores):
        g = _score_gauge(reg)
        for ti, v in scores.items():
            g.labels(trial=str(ti), rung=str(rung)).set(v)

    def test_keeps_top_ceil_over_eta(self):
        reg = MetricsRegistry()
        self._seed(reg, 0, {0: 0.9, 1: 0.5, 2: float("nan"), 3: 0.7})
        keep = HyperbandPruner(2, 8, eta=2).decide(
            0, [0, 1, 2, 3], maximize=True, registry=reg)
        assert keep == [0, 3]  # NaN pruned first, then worst

    def test_minimize_keeps_lowest(self):
        reg = MetricsRegistry()
        self._seed(reg, 1, {0: 0.9, 1: 0.5, 2: 0.7})
        keep = HyperbandPruner(2, 8, eta=3).decide(
            1, [0, 1, 2], maximize=False, registry=reg)
        assert keep == [1]

    def test_ties_break_by_trial_id(self):
        reg = MetricsRegistry()
        self._seed(reg, 0, {4: 0.5, 7: 0.5, 9: 0.5})
        keep = HyperbandPruner(2, 8, eta=2).decide(
            0, [4, 7, 9], maximize=True, registry=reg)
        assert keep == [4, 7]

    def test_barrier_violation_raises(self):
        reg = MetricsRegistry()
        self._seed(reg, 0, {0: 0.9})
        with pytest.raises(RuntimeError, match="not a barrier"):
            HyperbandPruner(2, 8, eta=2).decide(
                0, [0, 1], maximize=True, registry=reg)

    def test_all_nan_raises(self):
        reg = MetricsRegistry()
        self._seed(reg, 0, {0: float("nan"), 1: float("nan")})
        with pytest.raises(RuntimeError, match="NaN"):
            HyperbandPruner(2, 8, eta=2).decide(
                0, [0, 1], maximize=True, registry=reg)

    def test_rung_isolation(self):
        # rung 1 decisions never read rung 0 gauges
        reg = MetricsRegistry()
        self._seed(reg, 0, {0: 0.1, 1: 0.9})
        self._seed(reg, 1, {0: 0.9, 1: 0.1})
        keep = HyperbandPruner(2, 8, eta=2).decide(
            1, [0, 1], maximize=True, registry=reg)
        assert keep == [0]


# --------------------------------------------------------------------- #
# FindBestModel NaN handling (satellite 1)                              #
# --------------------------------------------------------------------- #


class _ConstModel(PipelineStage):
    """Scores every row with a constant; label == 1.23 rows make a
    perfect model, NaN makes an unusable one."""

    def __init__(self, value):
        self._v = float(value)
        self.calls = 0

    def transform(self, table):
        self.calls += 1
        return table.with_column(
            "prediction", np.full(len(table), self._v, np.float64))


class TestFindBestModelNaN:
    def _table(self):
        return Table({"x": np.zeros(8), "label": np.full(8, 1.23)})

    def test_nan_model_skipped_with_warning(self):
        good, bad = _ConstModel(1.23), _ConstModel(float("nan"))
        fb = FindBestModel(models=[bad, good],
                           evaluation_metric="mean_squared_error")
        with pytest.warns(UserWarning, match="NaN"):
            best = fb.fit(self._table())
        assert best.best_model is good

    def test_all_nan_raises(self):
        fb = FindBestModel(
            models=[_ConstModel(float("nan")), _ConstModel(float("nan"))],
            evaluation_metric="mean_squared_error")
        with pytest.raises(ValueError, match="NaN"):
            fb.fit(self._table())

    def test_unknown_metric_rejected_before_scoring(self):
        m = _ConstModel(1.0)
        with pytest.raises(ValueError, match="not rankable"):
            FindBestModel(models=[m], evaluation_metric="acuracy").fit(
                self._table())
        assert m.calls == 0  # a typo must not cost a full evaluation


# --------------------------------------------------------------------- #
# shared binned dataset                                                 #
# --------------------------------------------------------------------- #


class TestSharedBins:
    def test_row_gather_identity(self):
        # the invariant the whole cache rests on: binning is row-wise
        rng = np.random.default_rng(3)
        x = rng.normal(size=(60, 5))
        idx = np.asarray([3, 17, 42, 3])
        mapper = BinMapper(max_bin=16).fit(x)
        np.testing.assert_array_equal(
            mapper.transform(x[idx]), mapper.transform(x)[idx])

    def test_seed_once_lookup_hits(self):
        rng = np.random.default_rng(4)
        x = rng.normal(size=(50, 3))
        before = bin_counters()
        ctx = SharedBinContext()
        ctx.seed(x, max_bin=16)
        ctx.seed(x, max_bin=16)  # idempotent: no second build
        hit = ctx.lookup(x[10:30], max_bin=16, categorical_indexes=(),
                         bin_construct_sample_cnt=200_000)
        assert hit is not None
        np.testing.assert_array_equal(
            np.asarray(hit.device_bins()),
            hit.mapper.transform(x)[10:30])
        after = bin_counters()
        assert after["builds"] - before["builds"] == 1.0
        assert after["hits"] - before["hits"] == 1.0

    def test_config_mismatch_misses(self):
        rng = np.random.default_rng(5)
        x = rng.normal(size=(30, 3))
        ctx = SharedBinContext()
        ctx.seed(x, max_bin=16)
        # a trial sweeping max_bin must re-bin, not inherit boundaries
        assert ctx.lookup(x, max_bin=32, categorical_indexes=(),
                          bin_construct_sample_cnt=200_000) is None
        # foreign rows never match
        assert ctx.lookup(x + 1.0, max_bin=16, categorical_indexes=(),
                          bin_construct_sample_cnt=200_000) is None


# --------------------------------------------------------------------- #
# worker protocol (handler driven in-process, no fleet)                 #
# --------------------------------------------------------------------- #


def _reply(handler, body):
    out = handler(Table({"request": [HTTPRequestData.from_json("/", body)]}))
    r = out["reply"][0]
    return r.status_code, json.loads(r.entity.decode())


class TestWorkerProtocol:
    @pytest.fixture()
    def handler(self, tmp_path):
        sched = make_scheduler(tmp_path, workers=1)
        sched._write_spec(sweep_table())
        try:
            yield SweepWorkerFactory(str(tmp_path))()
        finally:
            set_shared_bin_context(None)

    def _await_done(self, handler, timeout=90.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            code, doc = _reply(handler, {"op": "heartbeat"})
            assert code == 200
            if doc["state"] in ("done", "failed"):
                return doc
            time.sleep(0.05)
        raise AssertionError("trial never finished")

    def test_unknown_op_is_an_error_reply_not_a_crash(self, handler):
        code, doc = _reply(handler, {"op": "explode"})
        assert code == 500 and "error" in doc
        # the worker survives to serve the next op
        code, doc = _reply(handler, {"op": "heartbeat"})
        assert code == 200 and doc["state"] == "idle"

    def test_claim_fit_report_and_idempotence(self, handler):
        before = bin_counters()
        code, doc = _reply(handler, {"op": "claim", "trial": 0, "rung": 0,
                                     "budget": 4})
        assert code == 200 and doc == {"ok": True}
        done = self._await_done(handler)
        assert done["state"] == "done"
        assert math.isfinite(done["metric"])

        # a re-sent claim after a driver hiccup must not fit twice
        code, doc = _reply(handler, {"op": "claim", "trial": 0, "rung": 0,
                                     "budget": 4})
        assert code == 200
        assert doc["done"] is True and doc["metric"] == done["metric"]

        # second trial: shared bins mean NO second BinMapper build
        _reply(handler, {"op": "claim", "trial": 1, "rung": 0, "budget": 4})
        self._await_done(handler)
        code, doc = _reply(handler, {"op": "status"})
        assert code == 200
        assert set(doc["done"]) == {"0:0", "1:0"}
        counters = doc["counters"]
        assert counters["builds"] - before["builds"] == 1.0
        assert counters["hits"] - before["hits"] == 4.0  # 2 trials x 2 folds

    def test_busy_worker_rejects_second_trial(self, handler):
        code, doc = _reply(handler, {"op": "claim", "trial": 2, "rung": 0,
                                     "budget": 8})
        assert doc == {"ok": True}
        code, doc = _reply(handler, {"op": "claim", "trial": 3, "rung": 0,
                                     "budget": 8})
        if "busy" in doc:  # fit can legitimately finish first on a fast box
            assert code == 200 and doc["trial"] == 2
        self._await_done(handler)


# --------------------------------------------------------------------- #
# the slow tier: real workers, real SIGKILL, live hot-swap              #
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def serial_sweep(tmp_path_factory):
    """The undisturbed P=1 ground truth every chaos run must match."""
    ckpt = tmp_path_factory.mktemp("sweep-serial")
    return make_scheduler(ckpt, workers=1).run(sweep_table())


@pytest.mark.slow
class TestSweepEndToEnd:
    def test_serial_sweep_prunes_and_picks(self, serial_sweep):
        res = serial_sweep
        assert res.pruned and sum(len(v) for v in res.pruned.values()) >= 1
        assert res.best_trial in res.survivors
        assert math.isfinite(res.best_metric)
        # bins built exactly once in the (single) worker
        assert [c["builds"] for c in res.worker_counters] == [1.0]

    def test_sigkill_mid_trial_matches_serial(self, serial_sweep, tmp_path):
        # the 3rd sub-checkpoint save SIGKILLs its worker with no
        # warning; the driver must respawn, re-queue, and converge on
        # the byte-identical winner
        sched = make_scheduler(tmp_path, workers=2,
                               chaos={"nth": 3, "mode": "before_save"})
        res = sched.run(sweep_table())
        assert (tmp_path / "_chaos_fired").exists()
        assert res.resumed_trials >= 1
        assert res.digest == serial_sweep.digest
        assert res.best_blob == serial_sweep.best_blob
        assert res.pruned == serial_sweep.pruned
        # every worker that trained built bins exactly once
        assert all(c["builds"] == 1.0 for c in res.worker_counters)

    def test_kill_mid_sub_checkpoint_matches_serial(self, serial_sweep,
                                                    tmp_path):
        # fsync dies mid-snapshot: the torn file must be fallen past on
        # resume, never loaded
        sched = make_scheduler(tmp_path, workers=2,
                               chaos={"nth": 3, "mode": "during_save"})
        res = sched.run(sweep_table())
        assert (tmp_path / "_chaos_fired").exists()
        assert res.digest == serial_sweep.digest
        assert res.best_blob == serial_sweep.best_blob

    def test_hot_swap_under_load_zero_errors(self, serial_sweep, tmp_path):
        from mmlspark_tpu.io_http.gateway import ServingGateway
        from mmlspark_tpu.io_http.serving import ServingFleet
        from mmlspark_tpu.io_http.clients import http_send

        res = serial_sweep
        modules = (type(res.best_model.best_model).__module__,)
        warm = HTTPRequestData.from_json("/", {"features": [0.0] * 4})
        fleet = ServingFleet(
            SweepModelFactory(res.best_blob, modules=modules),
            n_hosts=2, max_batch_size=1, warmup_request=warm).start()
        gw = ServingGateway(checkpoint_dir=str(tmp_path / "journal"),
                            strategy="round_robin")
        gw.attach_fleet(fleet)
        gw.start()

        rows = np.asarray(sweep_table()["features"])[:8]
        statuses, bodies, stop = [], [], threading.Event()

        def post(i):
            req = HTTPRequestData.from_json(
                gw.url, {"features": [float(v) for v in rows[i % 8]]})
            resp = http_send(req, retries=1)
            statuses.append(resp.status_code)
            bodies.append((i % 8, resp.entity))

        def loader():
            i = 0
            while not stop.is_set():
                post(i)
                i += 1

        try:
            for i in range(8):  # baseline bodies, pre-swap
                post(i)
            baseline = dict(bodies)
            t = threading.Thread(target=loader, daemon=True)
            t.start()
            # zero-downtime cutover of the sweep winner while clients
            # hammer the gateway
            swapped = res.hot_swap(fleet)
            assert swapped == 2
            time.sleep(0.5)
            stop.set()
            t.join(timeout=30)
            assert len(statuses) > 16
            assert all(s == 200 for s in statuses)
            # byte-identical responses across the cutover
            assert all(body == baseline[k] for k, body in bodies)
        finally:
            stop.set()
            gw.stop()
            fleet.stop()
