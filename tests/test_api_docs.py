"""docs/api.md must stay in sync with the stage registry (the reference
regenerates its wrapper/doc surface on every build, CodeGen.scala:44-97 —
here the equivalent staleness gate is a test)."""

import os
import sys


def test_api_reference_up_to_date():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import gen_api_docs

    path = os.path.join(os.path.dirname(__file__), "..", "docs", "api.md")
    with open(path) as fh:
        on_disk = fh.read()
    assert on_disk == gen_api_docs.generate(), (
        "docs/api.md is stale — run: python tools/gen_api_docs.py"
    )
