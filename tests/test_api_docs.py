"""docs/api.md must stay in sync with the stage registry (the reference
regenerates its wrapper/doc surface on every build, CodeGen.scala:44-97 —
here the equivalent staleness gate is a test).

Runs the generator in a CLEAN subprocess: inside the pytest process other
suites may have registered test-only stages (the fuzzing harness does),
which would make an in-process regeneration disagree with the committed
doc in a test-ordering-dependent way.
"""

import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).parent.parent


def test_api_reference_up_to_date():
    from tests.conftest import subprocess_env

    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "gen_api_docs.py"), "--check"],
        capture_output=True, text=True, timeout=300,
        cwd=str(REPO), env=subprocess_env(),
    )
    assert proc.returncode == 0, (
        f"docs/api.md is stale — run: python tools/gen_api_docs.py\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-1500:]}"
    )
