"""Deep-model subsystem tests.

Mirrors the reference suites for cntk-model (CNTKModelSuite: transform
shapes, batching, save/load), cntk-train (CNTKLearner fit), image-featurizer
(ImageFeaturizerSuite layer cutting) and downloader (DownloaderSuite
schema/hash) — run on the 8-virtual-device CPU mesh from conftest.
"""

import numpy as np
import pytest

from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.nn import (
    ARCHITECTURES,
    DeepModelTransformer,
    DNNLearner,
    ImageFeaturizer,
    ModelBundle,
    ModelDownloader,
    ModelSchema,
    retry_with_timeout,
)


def image_table(n=24, hw=8, c=3, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, hw, hw, c)).astype(np.float32)
    y = rng.integers(0, classes, size=n).astype(np.float64)
    # make the label recoverable: class shifts the mean of channel 0
    x[..., 0] += y[:, None, None] * 1.5
    return Table({"features": x, "label": y})


def vector_table(n=512, f=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    return Table({"features": x, "label": y})


class TestModelBundle:
    def test_init_and_forward_shapes(self):
        b = ModelBundle.init("mlp", (16,), num_outputs=3)
        t = DeepModelTransformer(input_col="features").set_model(b)
        tbl = Table({"features": np.zeros((10, 16), np.float32)})
        out = t.transform(tbl)
        assert np.asarray(out["output"]).shape == (10, 3)

    def test_save_load_roundtrip(self, tmp_path):
        b = ModelBundle.init("simple_cnn", (8, 8, 3), num_outputs=5)
        p = str(tmp_path / "m.model")
        b.save(p)
        b2 = ModelBundle.load(p)
        x = np.random.default_rng(0).normal(size=(4, 8, 8, 3)).astype(np.float32)
        t1 = DeepModelTransformer(input_col="f").set_model(b)
        t2 = DeepModelTransformer(input_col="f").set_model(b2)
        tbl = Table({"f": x})
        np.testing.assert_allclose(
            np.asarray(t1.transform(tbl)["output"]),
            np.asarray(t2.transform(tbl)["output"]),
            rtol=1e-5,
        )

    def test_layer_names(self):
        b = ModelBundle.init("resnet20_cifar", (16, 16, 3), num_outputs=10)
        names = b.layer_names()
        assert any("stage" in n for n in names)
        assert "pooled_features" in names


class TestDeepModelTransformer:
    def test_batching_matches_single_pass(self):
        # n not a multiple of mini_batch_size: padding must not leak
        b = ModelBundle.init("mlp", (12,), num_outputs=2)
        x = np.random.default_rng(1).normal(size=(37, 12)).astype(np.float32)
        tbl = Table({"features": x})
        small = DeepModelTransformer(input_col="features", mini_batch_size=8).set_model(b)
        big = DeepModelTransformer(input_col="features", mini_batch_size=64).set_model(b)
        np.testing.assert_allclose(
            np.asarray(small.transform(tbl)["output"]),
            np.asarray(big.transform(tbl)["output"]),
            rtol=1e-5,
        )

    def test_fused_dispatch_matches_per_batch_loop(self):
        # the single-dispatch scan path must equal the batch-by-batch path,
        # including tail padding and intermediate-layer fetches (the layer
        # path exercises capture_intermediates inside the fused lax.scan)
        b = ModelBundle.init("mlp", (12,), num_outputs=3)
        x = np.random.default_rng(5).normal(size=(53, 12)).astype(np.float32)
        tbl = Table({"features": x})
        layer = b.layer_names()[0]
        fetch = {"out": "logits", "prob": "probability", "feat": layer}
        fused = DeepModelTransformer(
            input_col="features", mini_batch_size=8, fetch_dict=fetch
        ).set_model(b).transform(tbl)
        looped = DeepModelTransformer(
            input_col="features", mini_batch_size=8, fetch_dict=fetch,
            fused_dispatch=False,
        ).set_model(b).transform(tbl)
        for c in fetch:
            np.testing.assert_allclose(
                np.asarray(fused[c]), np.asarray(looped[c]), rtol=1e-5
            )

    def test_fused_dispatch_budget_falls_back(self):
        # over-budget tables must stream batch-by-batch (and still be right)
        b = ModelBundle.init("mlp", (12,), num_outputs=2)
        x = np.random.default_rng(6).normal(size=(40, 12)).astype(np.float32)
        tbl = Table({"features": x})
        t = DeepModelTransformer(
            input_col="features", mini_batch_size=8, fused_dispatch_budget_mb=0
        ).set_model(b)
        ref = DeepModelTransformer(
            input_col="features", mini_batch_size=8, fused_dispatch=False
        ).set_model(b)
        np.testing.assert_allclose(
            np.asarray(t.transform(tbl)["output"]),
            np.asarray(ref.transform(tbl)["output"]), rtol=1e-5,
        )

    def test_probability_fetch(self):
        b = ModelBundle.init("mlp", (6,), num_outputs=4)
        t = DeepModelTransformer(
            input_col="features", fetch_dict={"prob": "probability"}
        ).set_model(b)
        out = t.transform(Table({"features": np.zeros((5, 6), np.float32)}))
        p = np.asarray(out["prob"])
        np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)

    def test_mesh_inference_matches(self, mesh8):
        from mmlspark_tpu.parallel.mesh import set_default_mesh

        b = ModelBundle.init("mlp", (10,), num_outputs=3)
        x = np.random.default_rng(2).normal(size=(64, 10)).astype(np.float32)
        tbl = Table({"features": x})
        plain = DeepModelTransformer(input_col="features").set_model(b).transform(tbl)
        set_default_mesh(mesh8)
        try:
            meshy = (
                DeepModelTransformer(input_col="features", use_mesh=True)
                .set_model(b)
                .transform(tbl)
            )
        finally:
            set_default_mesh(None)
        np.testing.assert_allclose(
            np.asarray(plain["output"]), np.asarray(meshy["output"]), rtol=1e-4
        )

    def test_save_load_stage(self, tmp_path):
        from mmlspark_tpu.core.pipeline import PipelineStage

        b = ModelBundle.init("mlp", (8,), num_outputs=2)
        t = DeepModelTransformer(input_col="features").set_model(b)
        p = str(tmp_path / "stage")
        t.save(p)
        t2 = PipelineStage.load(p)
        x = np.random.default_rng(3).normal(size=(6, 8)).astype(np.float32)
        tbl = Table({"features": x})
        np.testing.assert_allclose(
            np.asarray(t.transform(tbl)["output"]),
            np.asarray(t2.transform(tbl)["output"]),
            rtol=1e-5,
        )


class TestDNNLearner:
    def test_fit_mlp_learns(self):
        tbl = vector_table(n=512)
        model = DNNLearner(
            architecture="mlp",
            model_config={"features": (32,)},
            epochs=20,
            batch_size=64,
            learning_rate=0.01,
            use_mesh=False,
            bfloat16=False,
        ).fit(tbl)
        out = model.transform(tbl)
        acc = (out["prediction"] == tbl["label"]).mean()
        assert acc > 0.9

    def test_fit_on_mesh(self, mesh8):
        from mmlspark_tpu.parallel.mesh import set_default_mesh

        tbl = vector_table(n=512)
        set_default_mesh(mesh8)
        try:
            model = DNNLearner(
                architecture="mlp",
                model_config={"features": (32,)},
                epochs=10,
                batch_size=64,
                learning_rate=0.01,
                use_mesh=True,
                bfloat16=False,
            ).fit(tbl)
            out = model.transform(tbl)
        finally:
            set_default_mesh(None)
        assert (out["prediction"] == tbl["label"]).mean() > 0.85

    def test_fused_epochs_match_per_step_loop(self):
        # one-dispatch-per-epoch scan must train identically to the
        # batch-by-batch loop (same shuffle seed -> same batch sequence)
        rng = np.random.default_rng(9)
        x = rng.normal(size=(96, 10)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float64)
        tbl = Table({"features": x, "label": y})

        def fit(fused):
            from mmlspark_tpu.nn.trainer import DNNLearner

            m = DNNLearner(
                architecture="mlp", epochs=2, batch_size=32, seed=3,
                use_mesh=False, bfloat16=False, fused_epochs=fused,
            ).fit(tbl)
            return np.asarray(m.transform(tbl)["probability"])

        np.testing.assert_allclose(fit(True), fit(False), rtol=1e-4, atol=1e-5)

    def test_remat_trains_identically(self):
        """jax.checkpoint trades memory for recompute — the math must be
        unchanged: remat and no-remat fits produce matching models (BN
        model covers the mutable-stats remat path too)."""
        rng = np.random.default_rng(11)
        x = rng.normal(size=(64, 8, 8, 3)).astype(np.float32)
        y = (x[:, :, :, 0].mean(axis=(1, 2)) > 0).astype(np.float64)
        tbl = Table({"features": x, "label": y})

        def fit(remat):
            from mmlspark_tpu.nn.trainer import DNNLearner

            m = DNNLearner(
                architecture="resnet20_cifar", epochs=1, batch_size=32,
                seed=5, use_mesh=False, bfloat16=False, remat=remat,
            ).fit(tbl)
            return np.asarray(m.transform(tbl)["probability"])

        np.testing.assert_allclose(fit(True), fit(False), rtol=2e-4, atol=2e-5)

    def test_checkpoint_resume(self, tmp_path):
        tbl = vector_table(n=256)
        ck = str(tmp_path / "ckpts")
        est = DNNLearner(
            architecture="mlp", model_config={"features": (16,)},
            epochs=3, batch_size=64, use_mesh=False, bfloat16=False,
            checkpoint_dir=ck, seed=7,
        )
        est.fit(tbl)
        # resume: more epochs on the same dir starts from epoch 3
        est2 = DNNLearner(
            architecture="mlp", model_config={"features": (16,)},
            epochs=5, batch_size=64, use_mesh=False, bfloat16=False,
            checkpoint_dir=ck, seed=7,
        )
        model = est2.fit(tbl)
        out = model.transform(tbl)
        assert "prediction" in out.columns

    def test_transformer_sequence_task(self):
        # sequence family (absent in the reference): continuous inputs
        # through the Dense stem; label = sign of the first channel's mean
        from mmlspark_tpu.nn.trainer import DNNLearner

        rng = np.random.default_rng(7)
        x = rng.normal(size=(192, 12, 3)).astype(np.float32)
        y = (x[:, :, 0].mean(axis=1) > 0).astype(np.float64)
        m = DNNLearner(
            architecture="transformer", epochs=8, batch_size=64,
            model_config={"num_layers": 1, "d_model": 32, "num_heads": 2,
                          "d_ff": 64},
            use_mesh=False, bfloat16=False, learning_rate=3e-3,
        ).fit(Table({"features": x, "label": y}))
        acc = float((np.asarray(m.transform(Table({"features": x}))
                                ["prediction"]) == y).mean())
        assert acc > 0.8, acc

    def test_transformer_dropout_and_bf16_params(self):
        # dropout_rate > 0 requires the trainer to thread a dropout rng;
        # pos_embed must stay float32 under the default bf16 compute path
        import jax.numpy as jnp

        from mmlspark_tpu.nn.trainer import DNNLearner

        rng = np.random.default_rng(8)
        x = rng.normal(size=(96, 8, 2)).astype(np.float32)
        y = (x[:, :, 0].mean(axis=1) > 0).astype(np.float64)
        m = DNNLearner(
            architecture="transformer", epochs=2, batch_size=32,
            model_config={"num_layers": 1, "d_model": 16, "num_heads": 2,
                          "d_ff": 32, "dropout_rate": 0.1},
        ).fit(Table({"features": x, "label": y}))
        assert m.bundle.variables["params"]["pos_embed"].dtype == jnp.float32

    def test_transformer_token_inputs(self):
        # vocab_size > 0: integer token inputs embed; pooled features are
        # addressable for transfer learning like every other family
        b = ModelBundle.init(
            "transformer", (10,), num_outputs=3, seed=0,
            vocab_size=50, num_layers=1, d_model=16, num_heads=2, d_ff=32,
        )
        toks = np.random.default_rng(0).integers(0, 50, size=(6, 10))
        t = DeepModelTransformer(
            input_col="tokens",
            fetch_dict={"out": "logits", "feat": "pooled_features"},
        ).set_model(b)
        out = t.transform(Table({"tokens": toks}))
        assert np.asarray(out["out"]).shape == (6, 3)
        assert np.asarray(out["feat"]).shape == (6, 16)

    def test_bn_model_trains(self):
        tbl = image_table(n=64, hw=8, classes=4)
        model = DNNLearner(
            architecture="resnet",
            model_config={"stage_sizes": (1,), "num_filters": 8, "num_outputs": 4},
            epochs=15, batch_size=32, learning_rate=0.01,
            use_mesh=False, bfloat16=False,
        ).fit(tbl)
        out = model.transform(tbl)
        assert (out["prediction"] == tbl["label"]).mean() > 0.5

    def test_transfer_freeze(self):
        tbl = vector_table(n=128)
        est = DNNLearner(
            architecture="mlp", model_config={"features": (16,)},
            epochs=2, batch_size=32, use_mesh=False, bfloat16=False,
            trainable_prefixes=["head"],
        )
        init = ModelBundle.init("mlp", (16,), num_outputs=2, features=(16,))
        before_dense = np.array(init.variables["params"]["dense_0"]["kernel"])
        before_head = np.array(init.variables["params"]["head"]["kernel"])
        est.init_bundle = init
        model = est.fit(tbl)
        after_dense = np.asarray(model.bundle.variables["params"]["dense_0"]["kernel"])
        after_head = np.asarray(model.bundle.variables["params"]["head"]["kernel"])
        np.testing.assert_array_equal(before_dense, after_dense)
        assert not np.array_equal(before_head, after_head)


class TestImageFeaturizer:
    def test_cut_layers_features(self):
        b = ModelBundle.init("resnet20_cifar", (16, 16, 3), num_outputs=10)
        t = ImageFeaturizer(input_col="image").set_model(b)
        x = np.random.default_rng(0).normal(size=(6, 16, 16, 3)).astype(np.float32)
        out = t.transform(Table({"image": x}))
        feats = np.asarray(out["features_out"])
        assert feats.shape == (6, 64)  # pooled 16*2^2 channels

    def test_cut_zero_gives_logits(self):
        b = ModelBundle.init("resnet20_cifar", (16, 16, 3), num_outputs=10)
        t = ImageFeaturizer(input_col="image", cut_output_layers=0).set_model(b)
        x = np.zeros((4, 16, 16, 3), np.float32)
        out = t.transform(Table({"image": x}))
        assert np.asarray(out["features_out"]).shape == (4, 10)

    def test_resize_path(self):
        b = ModelBundle.init("resnet20_cifar", (16, 16, 3), num_outputs=10)
        t = ImageFeaturizer(input_col="image").set_model(b)
        x = np.zeros((2, 24, 24, 3), np.float32)  # wrong size -> resized
        out = t.transform(Table({"image": x}))
        assert np.asarray(out["features_out"]).shape[0] == 2


class TestZoo:
    def test_publish_download_load(self, tmp_path):
        repo = ModelDownloader(str(tmp_path / "repo"))
        b = ModelBundle.init("mlp", (4,), num_outputs=2)
        schema = repo.publish(b, "tiny-mlp")
        assert schema.sha256
        assert repo.get_model("tiny-mlp").architecture == "mlp"
        b2 = repo.load_bundle("tiny-mlp")
        assert b2.architecture == "mlp"

    def test_hash_mismatch_rejected(self, tmp_path):
        src = str(tmp_path / "src.model")
        ModelBundle.init("mlp", (4,), num_outputs=2).save(src)
        repo = ModelDownloader(str(tmp_path / "repo"))
        schema = ModelSchema(name="bad", uri=src, sha256="0" * 64)
        with pytest.raises(IOError):
            repo.download_model(schema)

    def test_retry_with_timeout(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ValueError("boom")
            return "ok"

        assert retry_with_timeout(flaky, retries=5) == "ok"
        assert len(calls) == 3

    def test_small_table_still_trains(self):
        # regression: batch_size > n used to produce zero training steps
        tbl = vector_table(n=50)
        model = DNNLearner(
            architecture="mlp", model_config={"features": (16,)},
            epochs=30, batch_size=128, learning_rate=0.02,
            use_mesh=False, bfloat16=False,
        ).fit(tbl)
        out = model.transform(tbl)
        assert (out["prediction"] == tbl["label"]).mean() > 0.8
