"""Elastic data-parallel training (ISSUE 17): partition-invariant shard
math in `parallel.dp`, the elastic worker protocol (world-epoch fencing,
preemption drain), driver digest parity — the SAME model bytes at any
world size and under kill/add chaos — the zombie-fencing checkpoint
refusal, autoscaler SLO wiring, metrics, and the diagnose table.

The fast tier drives the full driver protocol through in-process
handlers (`_LocalFleet`, the harness `tools/diagnose.py --training
--selftest` uses); the slow tier repeats the chaos schedule against
REAL `ServingFleet` worker processes, including a SIGKILL landing
inside the re-shard barrier itself.
"""

import hashlib
import json
import os
import signal
import time

import numpy as np
import pytest

from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.io_http.autoscale import FleetAutoscaler
from mmlspark_tpu.io_http.schema import HTTPRequestData
from mmlspark_tpu.observability.fleet import GAUGE_MERGE_POLICIES
from mmlspark_tpu.observability.metrics import (MetricsRegistry,
                                                set_default_registry)
from mmlspark_tpu.parallel import dp
from mmlspark_tpu.resilience.elastic import TrainingCheckpointer
from mmlspark_tpu.resilience.elastic_fleet import (WORLD_SIZE_GAUGE,
                                                   ElasticDNNFit,
                                                   ElasticGBDTFit,
                                                   ElasticWorkerFactory)
from mmlspark_tpu.resilience.policy import FakeClock


# --------------------------------------------------------------------- #
# harness: in-process fleet speaking the real worker protocol           #
# --------------------------------------------------------------------- #


class _LocalFleet:
    """Handler-per-URL stand-in for ServingFleet: the full driver
    protocol (configure/grad/hist/split/...) with zero processes."""

    def __init__(self, checkpoint_dir):
        self.checkpoint_dir = checkpoint_dir
        self.handlers = {}
        self._n = 0

    def add(self):
        url = f"http://local/{self._n:03d}"
        self._n += 1
        self.handlers[url] = ElasticWorkerFactory(
            self.checkpoint_dir, guard=False)()
        return url

    def remove_first(self):
        del self.handlers[sorted(self.handlers)[0]]

    urls = property(lambda self: list(self.handlers))
    n_live = property(lambda self: len(self.handlers))

    def watch(self, cb):
        pass

    def dump_all(self, trigger=""):
        return 0

    def stop(self):
        pass


def _post_fn(fleet):
    def post(url, body):
        handler = fleet.handlers.get(url)
        if handler is None:
            raise RuntimeError("dead member")
        out = handler(Table(
            {"request": [HTTPRequestData.from_json("/", body)]}))
        rep = out["reply"][0]
        doc = json.loads(bytes(rep.entity).decode("utf-8"))
        if rep.status_code != 200:
            raise RuntimeError(doc.get("error", "handler error"))
        return doc
    return post


def _raw_post(handler, body):
    """(status_code, doc) — for protocol tests that want the 500s too."""
    out = handler(Table(
        {"request": [HTTPRequestData.from_json("/", body)]}))
    rep = out["reply"][0]
    return rep.status_code, json.loads(bytes(rep.entity).decode("utf-8"))


def _gbdt_fit(d, x, y, n_workers, *, num_virtual=8, iters=5, hook=None,
              metrics=None, checkpoint_every_n=0, barrier_hook=None):
    fleet = _LocalFleet(d)
    fit = ElasticGBDTFit(
        d, objective="regression", num_iterations=iters, num_leaves=7,
        max_bin=15, min_data_in_leaf=1, seed=0, n_workers=n_workers,
        num_virtual=num_virtual, fleet=fleet, post=_post_fn(fleet),
        step_hook=hook, barrier_hook=barrier_hook, metrics=metrics,
        checkpoint_every_n=checkpoint_every_n)
    for _ in range(n_workers):
        fleet.add()
    booster = fit.fit(x, y)
    return fit, booster


def _dnn_fit(d, x, y, n_workers, *, num_virtual=8, epochs=2, hook=None):
    fleet = _LocalFleet(d)
    fit = ElasticDNNFit(
        d, architecture="mlp", model_config={"features": [8]},
        loss="softmax_ce", learning_rate=0.05, epochs=epochs,
        batch_size=8, seed=0, n_workers=n_workers,
        num_virtual=num_virtual, fleet=fleet, post=_post_fn(fleet),
        step_hook=hook)
    for _ in range(n_workers):
        fleet.add()
    bundle = fit.fit(x, y)
    return fit, bundle


def _reg_data(n=96, f=4, seed=11):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    y = x[:, 0] * 2.0 - x[:, 1] + 0.05 * rng.normal(size=n)
    return x, y


def _cls_data(n=48, f=4, seed=5):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.int64)
    return x, y


# --------------------------------------------------------------------- #
# dp: the partition-invariant shard math                                #
# --------------------------------------------------------------------- #


class TestShardMath:
    def test_virtual_shard_is_content_addressed(self):
        for rid in (0, 1, 17, 123456789):
            want = int.from_bytes(hashlib.blake2b(
                str(rid).encode(), digest_size=8).digest(), "big") % 32
            assert dp.virtual_shard_of(rid, 32) == want
        a = dp.shard_assignment(64, 16)
        assert a.dtype == np.int32 and a.shape == (64,)
        assert all(a[i] == dp.virtual_shard_of(i, 16) for i in range(64))

    @pytest.mark.parametrize("world", [1, 2, 3, 4, 5, 7])
    def test_shards_partition_exactly(self, world):
        owned = [dp.shards_of_member(r, world, 32) for r in range(world)]
        flat = [s for lst in owned for s in lst]
        assert sorted(flat) == list(range(32))      # each shard once
        for r, lst in enumerate(owned):
            for s in lst:
                assert dp.owner_of_shard(s, world) == r

    def test_rank_outside_world_raises(self):
        with pytest.raises(ValueError):
            dp.shards_of_member(3, 3, 32)
        with pytest.raises(ValueError):
            dp.owner_of_shard(0, 0)

    def test_fold_partials_ignores_insertion_order(self):
        rng = np.random.default_rng(0)
        parts = {s: rng.normal(size=5) for s in (9, 0, 3, 14)}
        a = dp.fold_partials(dict(sorted(parts.items())), 16)
        b = dp.fold_partials(dict(reversed(sorted(parts.items()))), 16)
        assert a.tobytes() == b.tobytes()
        with pytest.raises(ValueError):
            dp.fold_partials({}, 16)

    def test_global_batch_order_matches_trainer_stream(self):
        order = dp.global_batch_order(10, 4, 2, seed=7)
        assert order.shape == (4, 4) and order.dtype == np.int64
        rng = np.random.default_rng(7)
        want = []
        for _ in range(2):
            perm = rng.permutation(10)
            want += [perm[0:4], perm[4:8]]          # full batches only
        np.testing.assert_array_equal(order, np.stack(want))
        # P is not an argument: two draws are identical by construction
        np.testing.assert_array_equal(order, dp.global_batch_order(10, 4, 2, 7))

    def test_global_batch_order_small_n_clamps_batch(self):
        order = dp.global_batch_order(3, 8, 1, seed=0)
        assert order.shape == (1, 3)
        assert sorted(order[0].tolist()) == [0, 1, 2]

    def test_wire_codec_roundtrip(self):
        for a in (np.arange(12, dtype=np.float32).reshape(3, 4),
                  np.array([1, -2, 3], np.int64),
                  np.zeros((2, 0), np.float64)):
            b = dp.decode_array(dp.encode_array(a))
            assert b.dtype == a.dtype and b.shape == a.shape
            np.testing.assert_array_equal(a, b)

    def test_hist_partial_matches_naive_reference(self):
        rng = np.random.default_rng(3)
        n, f, nb = 40, 3, 6
        bins = rng.integers(0, nb, size=(n, f)).astype(np.int32)
        grad = rng.normal(size=n)
        hess = rng.uniform(0.1, 1.0, size=n)
        node = rng.integers(0, 3, size=n).astype(np.int32)
        got = dp.hist_partial(bins, grad, hess, node, [2, 0], nb)
        assert got.shape == (2, f, nb, 3)
        want = np.zeros_like(got)
        for slot, nd in enumerate([0, 2]):          # ascending node order
            for i in range(n):
                if node[i] != nd:
                    continue
                for j in range(f):
                    want[slot, j, bins[i, j], 0] += grad[i]
                    want[slot, j, bins[i, j], 1] += hess[i]
                    want[slot, j, bins[i, j], 2] += 1
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)

    def test_best_split_picks_first_max_and_honors_floors(self):
        hist = np.zeros((1, 3, 3))
        hist[0, 0] = [-4.0, 4.0, 4.0]               # bin 0: 4 rows, g=-4
        hist[0, 2] = [4.0, 4.0, 4.0]                # bin 2: 4 rows, g=+4
        parent = (0.0, 8.0, 8.0)
        sp = dp.best_split(hist, parent, min_data_in_leaf=1)
        assert sp is not None
        assert (sp["feature"], sp["bin"]) == (0, 0)  # tie -> first max
        assert sp["gain"] == pytest.approx(4.0)
        assert sp["left"] == (-4.0, 4.0, 4.0)
        assert sp["right"] == (4.0, 4.0, 4.0)
        assert dp.best_split(hist, parent, min_data_in_leaf=5) is None
        # the last bin's "left" is everything: never a split
        assert dp.best_split(hist[:, :1, :], parent) is None

    def test_tree_builder_roundtrip_through_walk(self):
        t = dp.TreeBuilder(5)
        left, right = t.alloc_pair()
        t.set_split(0, feature=1, threshold_bin=2, left=left, right=right,
                    gain=1.0)
        t.set_leaf(left, -0.5)
        t.set_leaf(right, 0.5)
        d = t.to_dict()
        bins = np.array([[0, 1], [0, 4]], np.int32)  # f1: 1<=2 left, 4 right
        np.testing.assert_allclose(
            dp.walk_tree_dict(d, bins), [-0.5, 0.5])


# --------------------------------------------------------------------- #
# satellite 1: zombie fencing in TrainingCheckpointer.load_latest       #
# --------------------------------------------------------------------- #


class TestZombieFence:
    def _store(self, tmp_path):
        ck = TrainingCheckpointer(str(tmp_path / "ck"))
        for epoch in (1, 2, 3):
            ck.save(f"epoch-{epoch}".encode(), tag=f"e{epoch}",
                    meta={"world_epoch": epoch, "kind": "gbdt"})
        return ck

    def test_unfenced_load_returns_newest(self, tmp_path):
        ck = self._store(tmp_path)
        payload, entry = ck.load_latest()
        assert payload == b"epoch-3"
        assert entry["meta"]["world_epoch"] == 3

    def test_newer_world_epoch_is_refused(self, tmp_path):
        ck = self._store(tmp_path)
        payload, entry = ck.load_latest(max_world_epoch=2)
        assert payload == b"epoch-2"                # fell back one entry
        assert entry["meta"]["world_epoch"] == 2

    def test_all_newer_means_no_snapshot(self, tmp_path):
        ck = self._store(tmp_path)
        assert ck.load_latest(max_world_epoch=0) is None

    def test_refusals_are_counted(self, tmp_path):
        ck = self._store(tmp_path)
        reg = MetricsRegistry()
        old = set_default_registry(reg)
        try:
            ck.load_latest(max_world_epoch=1)
        finally:
            set_default_registry(old)
        text = reg.render_prometheus()
        assert "mmlspark_tpu_checkpoint_refused_total 2" in text

    def test_snapshot_without_epoch_meta_is_not_fenced(self, tmp_path):
        ck = TrainingCheckpointer(str(tmp_path / "ck"))
        ck.save(b"legacy", tag="old")
        payload, _ = ck.load_latest(max_world_epoch=0)
        assert payload == b"legacy"


# --------------------------------------------------------------------- #
# worker protocol: fencing, errors, preemption drain                    #
# --------------------------------------------------------------------- #


class TestWorkerProtocol:
    def _spec_dir(self, tmp_path):
        """A checkpoint dir holding a real GBDT spec (written by a
        micro-fit) that fresh handlers can lazy-load."""
        d = str(tmp_path / "proto")
        x, y = _reg_data(n=40)
        _gbdt_fit(d, x, y, 1, iters=1, num_virtual=4)
        return d

    def test_configure_status_roundtrip(self, tmp_path):
        h = ElasticWorkerFactory(self._spec_dir(tmp_path), guard=False)()
        code, doc = _raw_post(h, {
            "op": "configure", "world_epoch": 7, "shards": [0, 1, 2, 3],
            "model": {"init_score": 0.0, "trees": []}})
        assert code == 200 and doc == {"ok": True, "world_epoch": 7}
        code, doc = _raw_post(h, {"op": "status"})
        assert code == 200
        assert doc["kind"] == "gbdt" and doc["world_epoch"] == 7
        assert doc["shards"] == [0, 1, 2, 3]

    def test_stale_epoch_is_fenced_not_computed(self, tmp_path):
        h = ElasticWorkerFactory(self._spec_dir(tmp_path), guard=False)()
        _raw_post(h, {"op": "configure", "world_epoch": 7,
                      "shards": [0, 1, 2, 3],
                      "model": {"init_score": 0.0, "trees": []}})
        code, doc = _raw_post(h, {"op": "hist", "world_epoch": 6,
                                  "nodes": [0], "step": 0})
        assert code == 200 and doc.get("stale") is True
        assert doc["world_epoch"] == 7              # the epoch it holds

    def test_unknown_op_is_a_500_reply_not_a_crash(self, tmp_path):
        h = ElasticWorkerFactory(self._spec_dir(tmp_path), guard=False)()
        _raw_post(h, {"op": "configure", "world_epoch": 7,
                      "shards": [0, 1, 2, 3],
                      "model": {"init_score": 0.0, "trees": []}})
        code, doc = _raw_post(h, {"op": "frobnicate", "world_epoch": 7})
        assert code == 500 and "unknown op" in doc["error"]
        # the handler survived: the next op still answers
        code, _ = _raw_post(h, {"op": "status"})
        assert code == 200

    def test_preemption_drain_finishes_reply_then_exits_75(self, tmp_path):
        """SIGTERM mid-serve: the in-flight reply flushes, then the
        worker schedules exit(RESUMABLE_EXIT_CODE) — drain, not drop."""
        exits = []

        class _Factory(ElasticWorkerFactory):
            _exit = staticmethod(exits.append)

        old_handler = signal.getsignal(signal.SIGTERM)
        try:
            h = _Factory(self._spec_dir(tmp_path), guard=True)()
            os.kill(os.getpid(), signal.SIGTERM)    # guard flips its Event
            code, doc = _raw_post(h, {"op": "status"})
            assert code == 200                      # reply still flushed
            deadline = time.monotonic() + 5.0
            while not exits and time.monotonic() < deadline:
                time.sleep(0.02)
            assert exits == [75]                    # EX_TEMPFAIL
        finally:
            signal.signal(signal.SIGTERM, old_handler)

    def test_missing_spec_is_an_error_reply(self, tmp_path):
        h = ElasticWorkerFactory(str(tmp_path / "nowhere"), guard=False)()
        code, doc = _raw_post(h, {"op": "configure", "world_epoch": 1,
                                  "shards": [0]})
        assert code == 500 and "error" in doc


# --------------------------------------------------------------------- #
# driver: digest parity at any world size, chaos, resume                #
# --------------------------------------------------------------------- #


class TestDigestParity:
    def test_gbdt_p1_vs_p3_byte_identical(self, tmp_path):
        x, y = _reg_data()
        fit1, b1 = _gbdt_fit(str(tmp_path / "p1"), x, y, 1)
        fit3, b3 = _gbdt_fit(str(tmp_path / "p3"), x, y, 3)
        assert fit1.model_digest() == fit3.model_digest()
        np.testing.assert_array_equal(b1.predict(x), b3.predict(x))
        # 5 boosting rounds must at least beat the constant predictor
        assert np.sqrt(np.mean((b1.predict(x) - y) ** 2)) < np.std(y)

    def test_dnn_p1_vs_p4_byte_identical(self, tmp_path):
        """The acceptance byte-compare: the batch-order stream and the
        gradient fold cannot depend on P."""
        x, y = _cls_data()
        fit1, _ = _dnn_fit(str(tmp_path / "p1"), x, y, 1)
        fit4, _ = _dnn_fit(str(tmp_path / "p4"), x, y, 4)
        assert fit1.params_digest() == fit4.params_digest()
        assert fit1.step == fit4.step > 0

    def test_gbdt_chaos_kill_and_add_digest_identical(self, tmp_path):
        x, y = _reg_data()
        fit1, _ = _gbdt_fit(str(tmp_path / "calm"), x, y, 1, iters=6)

        calls = {"n": 0}

        def hook(fit):
            calls["n"] += 1
            if calls["n"] == 2:
                fit.fleet.remove_first()            # death mid-fit
            elif calls["n"] == 4:
                fit.fleet.add()                     # join mid-fit

        fitc, _ = _gbdt_fit(str(tmp_path / "chaos"), x, y, 2, iters=6,
                            hook=hook)
        assert fitc.model_digest() == fit1.model_digest()
        causes = [r["cause"] for r in fitc.reshards]
        assert "death" in causes and "join" in causes

    def test_dnn_chaos_kill_and_add_digest_identical(self, tmp_path):
        x, y = _cls_data()
        fit1, _ = _dnn_fit(str(tmp_path / "calm"), x, y, 1)

        calls = {"n": 0}

        def hook(fit):
            calls["n"] += 1
            if calls["n"] == 3:
                fit.fleet.remove_first()
            elif calls["n"] == 6:
                fit.fleet.add()

        fitc, _ = _dnn_fit(str(tmp_path / "chaos"), x, y, 2, hook=hook)
        assert fitc.params_digest() == fit1.params_digest()
        causes = [r["cause"] for r in fitc.reshards]
        assert "death" in causes and "join" in causes

    def test_gbdt_resume_from_checkpoint_same_digest(self, tmp_path):
        d = str(tmp_path / "resume")
        x, y = _reg_data()
        first, _ = _gbdt_fit(d, x, y, 2, iters=6, checkpoint_every_n=3)
        second, _ = _gbdt_fit(d, x, y, 2, iters=6, checkpoint_every_n=3)
        assert second.reshards[0]["cause"] == "resume"
        assert second.model_digest() == first.model_digest()
        # the resumed incarnation fences zombies by outrunning the epoch
        assert second.world_epoch > first.world_epoch

    def test_ctor_validation(self, tmp_path):
        with pytest.raises(ValueError, match="checkpoint_dir"):
            ElasticGBDTFit("")
        with pytest.raises(ValueError, match="n_workers"):
            ElasticGBDTFit(str(tmp_path / "a"), n_workers=0)
        with pytest.raises(ValueError, match="num_virtual"):
            ElasticGBDTFit(str(tmp_path / "b"), n_workers=4, num_virtual=2)
        with pytest.raises(ValueError, match="objective"):
            ElasticGBDTFit(str(tmp_path / "c"), objective="poisson")

    def test_estimator_param_validation(self, tmp_path):
        from mmlspark_tpu.gbdt.estimators import GBDTRegressor
        from mmlspark_tpu.nn.trainer import DNNLearner
        from mmlspark_tpu.nn.models import ModelBundle

        x, y = _reg_data(n=32)
        t = Table({"features": x, "label": y})
        with pytest.raises(ValueError, match="bagging"):
            GBDTRegressor(elastic_workers=1, bagging_fraction=0.5,
                          checkpoint_dir=str(tmp_path / "g")).fit(t)
        with pytest.raises(ValueError, match="feature_fraction"):
            GBDTRegressor(elastic_workers=1, feature_fraction=0.5,
                          checkpoint_dir=str(tmp_path / "g2")).fit(t)
        learner = DNNLearner(elastic_workers=1,
                             checkpoint_dir=str(tmp_path / "d"))
        learner.init_bundle = ModelBundle.init("mlp", (4,), features=[4],
                                               num_outputs=2)
        with pytest.raises(ValueError, match="warm start"):
            learner.fit(Table({"features": x.astype(np.float32),
                               "label": (y > 0).astype(np.int64)}))


# --------------------------------------------------------------------- #
# autoscaler wiring + metrics + diagnose table                          #
# --------------------------------------------------------------------- #


class _StubFleet:
    def __init__(self, n=1):
        self.n = n

    n_live = property(lambda self: self.n)

    def dead_slots(self):
        return []

    def scale_to(self, n):
        self.n = n
        return []


def _training_sig(**over):
    sig = {"queue_depth": 0.0, "p99_latency_s": 0.0, "shed_rate": 0.0,
           "burn_rate": 0.0, "step_p99_latency_s": 0.0,
           "straggler_wait_s": 0.0}
    sig.update(over)
    return sig


class TestAutoscalerWiring:
    def _scaler(self, fleet, sig, **kw):
        kw.setdefault("hysteresis_ticks", 2)
        kw.setdefault("cooldown_s", 30.0)
        return FleetAutoscaler(fleet, lambda: dict(sig),
                               clock=FakeClock(), **kw)

    @pytest.mark.parametrize("key,value", [
        ("step_p99_latency_s", 2.0), ("straggler_wait_s", 0.9)])
    def test_training_slo_pressure_scales_up(self, key, value):
        fleet = _StubFleet(1)
        sig = _training_sig(**{key: value})
        scaler = self._scaler(fleet, sig, extra_up={
            "step_p99_latency_s": 1.0, "straggler_wait_s": 0.5})
        assert scaler.tick() == "up"
        assert fleet.n_live == 2

    def test_elevated_training_signal_blocks_scale_down(self):
        fleet = _StubFleet(3)
        sig = _training_sig(step_p99_latency_s=0.8)  # above 1.0 * 0.5
        scaler = self._scaler(fleet, sig, extra_up={
            "step_p99_latency_s": 1.0, "straggler_wait_s": 0.5})
        for _ in range(5):
            assert scaler.tick() == "none"          # never calm enough
        assert fleet.n_live == 3
        sig["step_p99_latency_s"] = 0.0             # truly calm now
        assert scaler.tick() == "none"
        assert scaler.tick() == "down"

    def test_fit_builds_wired_autoscaler(self, tmp_path):
        fit = ElasticGBDTFit(str(tmp_path / "a"), fleet=_StubFleet(2))
        scaler = fit.autoscaler(up_step_p99_s=2.0, up_straggler_s=0.25)
        assert scaler.fleet is fit.fleet
        assert scaler.extra_up == {"step_p99_latency_s": 2.0,
                                   "straggler_wait_s": 0.25}
        sig = fit.signals()
        for key in ("queue_depth", "p99_latency_s", "shed_rate",
                    "burn_rate", "step_p99_latency_s", "straggler_wait_s"):
            assert key in sig


class TestMetrics:
    def test_world_size_gauge_has_explicit_merge_policy(self):
        assert GAUGE_MERGE_POLICIES[WORLD_SIZE_GAUGE] == "last"

    def test_fit_emits_world_size_reshard_and_straggler(self, tmp_path):
        reg = MetricsRegistry()
        x, y = _reg_data(n=48)

        def hook(fit):
            if fit.step == 2 and fit.fleet.n_live > 1:
                fit.fleet.remove_first()

        fit, _ = _gbdt_fit(str(tmp_path / "m"), x, y, 2, iters=4,
                           hook=hook, metrics=reg)
        text = reg.render_prometheus()
        assert f"{WORLD_SIZE_GAUGE} 1" in text      # last world was P=1
        assert 'mmlspark_tpu_training_reshard_total{cause="join"} 1' in text
        assert 'mmlspark_tpu_training_reshard_total{cause="death"} 1' in text
        assert "mmlspark_tpu_training_straggler_wait_seconds" in text


class TestDiagnoseTable:
    def _diagnose(self):
        import pathlib
        import sys

        tools = str(pathlib.Path(__file__).parents[1] / "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        import diagnose

        return diagnose

    def test_renders_status_members_and_reshards(self, tmp_path):
        doc = {
            "kind": "dnn", "world_epoch": 4, "world_size": 2, "step": 9,
            "members": [
                {"rank": 0, "url": "http://a", "step": 9, "lag": 0,
                 "rtt_s": 0.002},
                {"rank": 1, "url": "http://b", "step": None, "lag": None,
                 "rtt_s": None},
            ],
            "last_reshard": {"cause": "join", "world_epoch": 4},
            "reshards": [{"cause": "join", "world_epoch": 4, "step": 8,
                          "world_size": 2, "barrier_retries": 0}],
            "straggler_wait_s": 0.001,
        }
        with open(tmp_path / "elastic_status.json", "w") as fh:
            json.dump(doc, fh)
        out = self._diagnose().diagnose_training(str(tmp_path))
        assert "elastic dnn fit" in out
        assert "world_epoch=4" in out and "P=2" in out and "step=9" in out
        assert "http://a" in out and "http://b" in out
        assert " - " in out                          # None lag renders "-"
        assert "re-shards" in out and " join " in out

    def test_missing_dir_and_missing_status(self, tmp_path):
        dg = self._diagnose()
        assert "no training checkpoint directory" in dg.diagnose_training(
            str(tmp_path / "nope"))
        assert "no elastic_status.json" in dg.diagnose_training(
            str(tmp_path))

    def test_live_status_from_real_fit(self, tmp_path):
        d = str(tmp_path / "live")
        x, y = _reg_data(n=48)
        _gbdt_fit(d, x, y, 2, iters=3)
        out = self._diagnose().diagnose_training(d)
        assert "elastic gbdt fit" in out and "step=3" in out
        assert "http://local/" in out


# --------------------------------------------------------------------- #
# slow tier: REAL worker processes, SIGKILL chaos, barrier kills        #
# --------------------------------------------------------------------- #


@pytest.mark.slow
class TestRealFleetChaos:
    """The ISSUE 17 chaos acceptance: kill AND add real workers every few
    steps for a DNN and a GBDT fit; the final model must be
    byte-identical to an undisturbed P=1 run — including when the
    SIGKILL lands inside the re-shard barrier itself."""

    def _chaos_hook(self, every=3):
        state = {"last": -1}

        def hook(fit):
            if fit.step and fit.step % every == 0 and \
                    fit.step != state["last"]:
                state["last"] = fit.step
                dead = fit.fleet.dead_slots()
                if dead:
                    fit.fleet.respawn(dead[0])      # add a real worker
                else:
                    fit.fleet.kill(0)               # SIGKILL a real worker
        return hook

    def test_gbdt_real_process_kill_add_digest_identical(self, tmp_path):
        x, y = _reg_data(n=256, f=6)
        base, _ = _gbdt_fit(str(tmp_path / "base"), x, y, 1, iters=8)

        fit = ElasticGBDTFit(
            str(tmp_path / "real"), objective="regression",
            num_iterations=8, num_leaves=7, max_bin=15,
            min_data_in_leaf=1, seed=0, n_workers=2, num_virtual=8,
            request_timeout_s=120.0, step_hook=self._chaos_hook())
        fit.fit(x, y)
        assert fit.model_digest() == base.model_digest()
        causes = [r["cause"] for r in fit.reshards]
        assert "death" in causes and "join" in causes

    def test_dnn_real_process_kill_add_digest_identical(self, tmp_path):
        x, y = _cls_data(n=48)
        base, _ = _dnn_fit(str(tmp_path / "base"), x, y, 1)

        fit = ElasticDNNFit(
            str(tmp_path / "real"), architecture="mlp",
            model_config={"features": [8]}, loss="softmax_ce",
            learning_rate=0.05, epochs=2, batch_size=8, seed=0,
            n_workers=2, num_virtual=8, request_timeout_s=120.0,
            step_hook=self._chaos_hook(every=4))
        fit.fit(x, y)
        assert fit.params_digest() == base.params_digest()
        causes = [r["cause"] for r in fit.reshards]
        assert "death" in causes and "join" in causes

    def test_sigkill_inside_reshard_barrier(self, tmp_path):
        """A worker dies WHILE the barrier is re-configuring the world:
        the barrier must converge against the shrunken membership and
        the model must still match the undisturbed run."""
        x, y = _reg_data(n=256, f=6)
        base, _ = _gbdt_fit(str(tmp_path / "base"), x, y, 1, iters=8)

        state = {"killed_step": False, "killed_barrier": False}

        def step_hook(fit):
            if fit.step == 2 and not state["killed_step"]:
                state["killed_step"] = True
                fit.fleet.kill(0)                   # death -> barrier

        def barrier_hook(fit):
            if state["killed_step"] and not state["killed_barrier"]:
                state["killed_barrier"] = True
                live = fit.fleet.live_slots()
                fit.fleet.kill(live[0])             # SIGKILL IN the barrier

        fit = ElasticGBDTFit(
            str(tmp_path / "real"), objective="regression",
            num_iterations=8, num_leaves=7, max_bin=15,
            min_data_in_leaf=1, seed=0, n_workers=3, num_virtual=8,
            request_timeout_s=120.0, step_hook=step_hook,
            barrier_hook=barrier_hook)
        fit.fit(x, y)
        assert state["killed_barrier"]
        assert fit.model_digest() == base.model_digest()
        # the barrier completed against the world the kills left behind
        sizes = [r["world_size"] for r in fit.reshards]
        assert 1 in sizes
