"""External-truth grounding for GBDT semantics (VERDICT r2 item 6).

Two anchors that do NOT reference this framework's own past outputs:

1. REAL DATA vs the reference's committed gate: the vendored Wisconsin
   Diagnostic Breast Cancer dataset (569 real rows; sklearn's bundled copy,
   written to tests/benchmarks/data/breast_cancer_wdbc.csv) trained with the
   reference suite's exact hyperparameters (numLeaves=5, numIterations=10,
   objective=binary — VerifyLightGBMClassifier.scala:232-240) must land
   within ±0.01 of the reference's committed train-AUC (breast-cancer gbdt
   0.99247, benchmarks_VerifyLightGBMClassifier.csv:22-25 — the reference
   commits that dataset at 0.1 but its tightest tier at 0.01; we gate at
   the tight tier), and the holdout AUC must stay within ±0.01 of this
   repo's committed value (train-only gates miss overfit regressions).
2. INDEPENDENT IMPLEMENTATION cross-check: sklearn's histogram GBDT —
   a from-scratch third-party implementation of the same algorithm family —
   must agree with this framework's AUC on identical data within a tight
   band.

Plus the format anchor: a hand-authored model file in LightGBM's OWN
native model.txt syntax loads via `Booster.from_lightgbm_text` and
reproduces hand-computed predictions — the loader is pinned to the
published format semantics (value <= threshold -> left, negative child ids
are leaves, sigmoid for binary), not to this repo's conventions.
"""

import os

import numpy as np
import pytest

# every case compiles a fresh fused boosting loop (different TrainOptions =
# different XLA program); minutes of compile wall-clock put the module in
# the slow tier alongside the other end-to-end gates
pytestmark = pytest.mark.slow

DATA = os.path.join(os.path.dirname(__file__), "benchmarks", "data",
                    "breast_cancer_wdbc.csv")

# the reference's committed gates for breast-cancer (train AUC),
# benchmarks_VerifyLightGBMClassifier.csv lines 22-25. The reference's CSV
# commits breast-cancer at precision 0.1 and its tightest datasets
# (BreastTissue etc., lines 2-5) at 0.01; this repo gates at the TIGHT
# tier — measured agreement is within ±0.004, and a ±0.1 window would
# pass a badly broken model (VERDICT r4 #6).
REFERENCE_GATES = {
    "gbdt": 0.9924667959194766,
    "rf": 0.9894725398177173,
    "dart": 0.9915381688379931,
    "goss": 0.9924667959194766,
}
PRECISION = 0.01


def _rf_kwargs(boosting):
    # the reference sets bagging for rf (VerifyLightGBMClassifier
    # .scala:228-231); rf without bagging is degenerate
    return ({"bagging_fraction": 0.9, "bagging_freq": 1}
            if boosting == "rf" else {})

# this repo's committed HOLDOUT AUC on the same config (seed-0 80/20 split;
# measured r5) — train-only gates cannot catch an overfit regression. Gated
# two-sided at the same ±0.01: drift in either direction means the
# algorithm changed and the committed value must be consciously re-derived.
HOLDOUT_GATES = {
    "gbdt": 0.98777,
    "rf": 0.97904,
    "dart": 0.97158,
    "goss": 0.98857,
}


def _auc(y, score):
    order = np.argsort(score, kind="stable")
    ranks = np.empty(len(y), np.float64)
    ranks[order] = np.arange(1, len(y) + 1)
    # tie-average ranks so AUC is exact for discrete scores
    for s in np.unique(score):
        m = score == s
        ranks[m] = ranks[m].mean()
    pos = y > 0.5
    n1, n0 = pos.sum(), (~pos).sum()
    return float((ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0))


@pytest.fixture(scope="module")
def wdbc():
    from mmlspark_tpu.core.table_io import read_csv

    t = read_csv(DATA)
    y = np.asarray(t["Label"], np.float64)
    feats = [c for c in t.columns if c != "Label"]
    x = np.stack([np.asarray(t[c], np.float64) for c in feats], axis=1)
    assert x.shape == (569, 30) and set(np.unique(y)) == {0.0, 1.0}
    return x, y


class TestReferenceGateOnRealData:
    @pytest.mark.parametrize("boosting", ["gbdt", "rf", "dart", "goss"])
    def test_train_auc_within_reference_window(self, wdbc, boosting):
        """The reference suite's exact config on REAL data must land within
        ±0.01 of the reference's committed AUC — same dataset family, same
        metric, same hyperparameters, gated at the reference CSV's tight
        precision tier (two-sided, like the reference's CI assertion: drift
        in either direction means the semantics changed)."""
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y = wdbc
        booster = Booster.train(x, y, TrainOptions(
            objective="binary", boosting_type=boosting,
            num_leaves=5, num_iterations=10, **_rf_kwargs(boosting),
        ))
        auc = _auc(y, np.asarray(booster.predict(x)))
        want = REFERENCE_GATES[boosting]
        assert abs(auc - want) < PRECISION, (
            f"{boosting}: train AUC {auc:.4f} outside the reference window "
            f"{want:.4f} ± {PRECISION}"
        )

    @pytest.mark.parametrize("boosting", ["gbdt", "rf", "dart", "goss"])
    def test_holdout_auc_within_committed_window(self, wdbc, boosting):
        """Holdout AUC on the fixed seed-0 80/20 split must stay within
        ±0.01 of the committed value — the overfit-catching counterpart of
        the train-AUC gate (VERDICT r4 #6)."""
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y = wdbc
        rng = np.random.default_rng(0)
        order = rng.permutation(len(y))
        cut = int(0.8 * len(y))
        tr, te = order[:cut], order[cut:]
        booster = Booster.train(x[tr], y[tr], TrainOptions(
            objective="binary", boosting_type=boosting,
            num_leaves=5, num_iterations=10, **_rf_kwargs(boosting),
        ))
        auc = _auc(y[te], np.asarray(booster.predict(x[te])))
        want = HOLDOUT_GATES[boosting]
        assert abs(auc - want) < PRECISION, (
            f"{boosting}: holdout AUC {auc:.4f} outside the committed "
            f"window {want:.4f} ± {PRECISION}"
        )

    def test_sklearn_cross_check(self, wdbc):
        """Independent-implementation agreement: sklearn's histogram GBDT
        with matched capacity lands within 0.02 AUC of this framework."""
        from sklearn.ensemble import HistGradientBoostingClassifier

        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y = wdbc
        ours = Booster.train(x, y, TrainOptions(
            objective="binary", num_leaves=5, num_iterations=10,
        ))
        ours_auc = _auc(y, np.asarray(ours.predict(x)))
        sk = HistGradientBoostingClassifier(
            max_iter=10, max_leaf_nodes=5, learning_rate=0.1,
            min_samples_leaf=20, early_stopping=False,
        ).fit(x, y)
        sk_auc = _auc(y, sk.predict_proba(x)[:, 1])
        assert abs(ours_auc - sk_auc) < 0.02, (ours_auc, sk_auc)
        assert ours_auc > 0.98


def _load_real_csv(name):
    from mmlspark_tpu.core.table_io import read_csv

    t = read_csv(os.path.join(os.path.dirname(__file__), "benchmarks",
                              "data", f"{name}.csv"))
    y = np.asarray(t["Label"], np.float64)
    feats = [c for c in t.columns if c != "Label"]
    x = np.stack([np.asarray(t[c], np.float64) for c in feats], axis=1)
    return x, y


class TestMoreRealDataAnchors:
    """Additional REAL datasets (VERDICT r3 item 6: one real dataset is a
    thin base for a GBDT claiming LightGBM parity). Iris, Wine, and Digits
    are genuine UCI-origin measurement data vendored from sklearn's
    bundled copies (zero-egress environment) — not generators. Each anchor
    follows the reference gate pattern
    (benchmarks_VerifyLightGBMClassifier.csv): fixed small config, the
    metric must clear an absolute bar, and sklearn's independent
    histogram-GBDT must agree within a tight band on identical data."""

    # (dataset, num_class, min holdout accuracy) — bars set below
    # well-known achievable accuracy for these datasets at this capacity,
    # mirroring the reference's precision windows; wine's 36-row holdout
    # moves ~2.8 points per misclassified row, so its bar carries a
    # two-row margin
    CASES = [("iris", 3, 0.90), ("wine", 3, 0.83), ("digits", 10, 0.90)]

    @pytest.mark.parametrize("name,k,bar", CASES)
    def test_holdout_accuracy_clears_reference_style_gate(self, name, k, bar):
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y = _load_real_csv(name)
        rng = np.random.default_rng(0)
        order = rng.permutation(len(y))
        cut = int(0.8 * len(y))
        tr, te = order[:cut], order[cut:]
        b = Booster.train(x[tr], y[tr], TrainOptions(
            objective="multiclass", num_class=k,
            num_leaves=15, num_iterations=30, min_data_in_leaf=5,
        ))
        pred = np.asarray(b.predict(x[te])).argmax(axis=1)
        acc = float((pred == y[te]).mean())
        assert acc >= bar, f"{name}: holdout acc {acc:.3f} below {bar}"

    @pytest.mark.parametrize("name,k", [(n, k) for n, k, _ in CASES])
    def test_sklearn_cross_check(self, name, k):
        from sklearn.ensemble import HistGradientBoostingClassifier

        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y = _load_real_csv(name)
        rng = np.random.default_rng(1)
        order = rng.permutation(len(y))
        cut = int(0.8 * len(y))
        tr, te = order[:cut], order[cut:]
        ours = Booster.train(x[tr], y[tr], TrainOptions(
            objective="multiclass", num_class=k,
            num_leaves=15, num_iterations=30, min_data_in_leaf=5,
        ))
        ours_acc = (np.asarray(ours.predict(x[te])).argmax(1) == y[te]).mean()
        sk = HistGradientBoostingClassifier(
            max_iter=30, max_leaf_nodes=15, learning_rate=0.1,
            min_samples_leaf=5, early_stopping=False,
        ).fit(x[tr], y[tr])
        sk_acc = (sk.predict(x[te]) == y[te]).mean()
        assert abs(ours_acc - sk_acc) < 0.06, (name, ours_acc, sk_acc)

    def test_boosting_modes_on_wine(self):
        """All four boosting modes learn real data (the reference gate
        table exercises gbdt/rf/dart/goss per dataset)."""
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y = _load_real_csv("wine")
        ybin = (y == 2.0).astype(np.float64)
        for boosting, bar in [("gbdt", 0.97), ("rf", 0.90),
                              ("dart", 0.95), ("goss", 0.95)]:
            kw = {"bagging_fraction": 0.9, "bagging_freq": 1} \
                if boosting == "rf" else {}
            b = Booster.train(x, ybin, TrainOptions(
                objective="binary", boosting_type=boosting,
                num_leaves=7, num_iterations=20, min_data_in_leaf=5, **kw,
            ))
            auc = _auc(ybin, np.asarray(b.predict(x)))
            assert auc > bar, f"{boosting}: train AUC {auc:.3f} <= {bar}"


class TestRealRegressionAnchor:
    """REAL regression data (the classification anchors' counterpart): the
    diabetes dataset — 442 genuine clinical records (age/sex/bmi/bp + six
    serum measurements -> disease progression), vendored from sklearn's
    bundled copy. Mirrors the reference's regressor gate pattern
    (benchmarks_VerifyLightGBMRegressor.csv: fixed config, metric within a
    window) plus an independent-implementation cross-check."""

    def _split(self, seed=0):
        x, y = _load_real_csv("diabetes")
        rng = np.random.default_rng(seed)
        order = rng.permutation(len(y))
        cut = int(0.8 * len(y))
        return x, y, order[:cut], order[cut:]

    def test_sklearn_cross_check(self):
        from sklearn.ensemble import HistGradientBoostingRegressor

        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y, tr, te = self._split(seed=1)
        ours = Booster.train(x[tr], y[tr], TrainOptions(
            objective="regression", num_leaves=15, num_iterations=50,
            min_data_in_leaf=5, learning_rate=0.1,
        ))
        ours_rmse = float(np.sqrt(np.mean(
            (np.asarray(ours.predict(x[te])) - y[te]) ** 2)))
        sk = HistGradientBoostingRegressor(
            max_iter=50, max_leaf_nodes=15, learning_rate=0.1,
            min_samples_leaf=5, early_stopping=False,
        ).fit(x[tr], y[tr])
        sk_rmse = float(np.sqrt(np.mean((sk.predict(x[te]) - y[te]) ** 2)))
        # same family, same capacity, identical data: RMSEs must land in
        # the same neighborhood (window sized like the reference's
        # per-metric precisions relative to the ~55-60 scale)
        assert abs(ours_rmse - sk_rmse) < 6.0, (ours_rmse, sk_rmse)

    # committed holdout RMSE per boosting type (seed-0 80/20 split,
    # num_leaves=15, num_iterations=50 — measured r5), gated at ±2.0 in
    # the style of the reference's regressor CSV windows
    # (benchmarks_VerifyLightGBMRegressor.csv: value ± per-metric precision)
    BOOSTING_RMSE_GATES = {
        "gbdt": 57.58,
        "rf": 58.07,
        "dart": 57.98,
        "goss": 61.04,
    }

    @pytest.mark.parametrize("boosting", ["gbdt", "rf", "dart", "goss"])
    def test_boosting_modes_holdout_rmse_within_window(self, boosting):
        """All four boosting types on REAL regression data, each gated
        against its committed holdout RMSE — the regression counterpart of
        the WDBC per-boosting-type windows (the reference's regressor gate
        table spans boosting types per dataset the same way). The gbdt case
        also carries the absolute anchors: label std is ~77 and published
        GBDT results on this dataset sit around RMSE 54-60, so the window
        sits far below the constant-predictor baseline."""
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y, tr, te = self._split()
        b = Booster.train(x[tr], y[tr], TrainOptions(
            objective="regression", boosting_type=boosting,
            num_leaves=15, num_iterations=50, min_data_in_leaf=5,
            learning_rate=0.1, **_rf_kwargs(boosting),
        ))
        rmse = float(np.sqrt(np.mean(
            (np.asarray(b.predict(x[te])) - y[te]) ** 2)))
        want = self.BOOSTING_RMSE_GATES[boosting]
        assert abs(rmse - want) < 2.0, (
            f"{boosting}: holdout RMSE {rmse:.2f} outside the committed "
            f"window {want:.2f} ± 2.0"
        )
        if boosting == "gbdt":
            const_rmse = float(
                np.sqrt(np.mean((y[tr].mean() - y[te]) ** 2)))
            assert rmse < 0.85 * const_rmse, (rmse, const_rmse)

    def test_robust_objectives_on_real_data(self):
        """l1/huber/quantile learn the real data too (the reference's
        regressor gates span objectives; quantile checks calibration)."""
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y, tr, te = self._split(seed=2)
        # huber is NOT renewed (matching LightGBM); on labels of scale
        # ~77 the LightGBM-faithful usage is alpha at the residual scale,
        # where huber ~ L2 and converges on the label scale
        for objective, kw in (("l1", {}), ("huber", {"alpha": 80.0})):
            b = Booster.train(x[tr], y[tr], TrainOptions(
                objective=objective, num_leaves=15, num_iterations=50,
                min_data_in_leaf=5, learning_rate=0.1, **kw,
            ))
            mae = float(np.mean(np.abs(np.asarray(b.predict(x[te])) - y[te])))
            const_mae = float(np.mean(np.abs(np.median(y[tr]) - y[te])))
            # l1 rides leaf renewal (RenewTreeOutput): measured ~50 vs the
            # constant's ~60 — the bar keeps a margin above sklearn's ~51
            assert mae < 0.93 * const_mae, (objective, mae, const_mae)
        bq = Booster.train(x[tr], y[tr], TrainOptions(
            objective="quantile", alpha=0.8, num_leaves=15,
            num_iterations=50, min_data_in_leaf=5, learning_rate=0.1,
        ))
        cover = float((y[te] <= np.asarray(bq.predict(x[te]))).mean())
        # with renewal the q0.8 holdout coverage is ~0.76; the window is a
        # calibration gate (unrenewed quantile fits collapse toward the
        # median and fail it)
        assert 0.68 <= cover <= 0.92, f"q0.8 coverage {cover:.3f}"


# A hand-authored model in LightGBM's native model.txt syntax. Semantics to
# reproduce by hand below: two trees, raw = leaf0(t0) + leaf(t1), prob =
# sigmoid(raw).
LIGHTGBM_MODEL_TXT = """tree
version=v3
num_class=1
num_tree_per_iteration=1
label_index=0
max_feature_idx=2
objective=binary sigmoid:1
feature_names=f0 f1 f2
feature_infos=[-5:5] [-5:5] [-5:5]

Tree=0
num_leaves=3
num_cat=0
split_feature=0 1
split_gain=10 5
threshold=1.5 -0.25
decision_type=2 2
left_child=1 -1
right_child=-3 -2
leaf_value=0.2 -0.1 0.4
leaf_weight=10 10 10
leaf_count=10 10 10
internal_value=0 0
internal_count=30 20
shrinkage=0.1


Tree=1
num_leaves=2
num_cat=0
split_feature=2
split_gain=3
threshold=0.5
decision_type=2
left_child=-1
right_child=-2
leaf_value=-0.05 0.15
leaf_weight=15 15
leaf_count=15 15
internal_value=0
internal_count=30
shrinkage=0.1


end of trees

feature importances:
f0=1
f1=1
f2=1
"""


class TestLightGBMNativeFormat:
    @pytest.fixture(scope="class")
    def booster(self):
        from mmlspark_tpu.gbdt.booster import Booster

        return Booster.from_lightgbm_text(LIGHTGBM_MODEL_TXT)

    def test_hand_computed_predictions(self, booster):
        """Tree 0: node0 splits f0<=1.5 (left->node1, right->leaf2);
        node1 splits f1<=-0.25 (left->leaf0, right->leaf1).
        Tree 1: f2<=0.5 -> leaf0 else leaf1. Probabilities are
        sigmoid(sum) — all four paths computed by hand."""
        rows = np.array([
            # f0,   f1,    f2     tree0 leaf        tree1 leaf
            [0.0, -1.0, 0.0],   # f0<=1.5,f1<=-.25 -> 0.2 ; f2<=.5 -> -0.05
            [0.0,  0.5, 1.0],   # f0<=1.5,f1>-.25  -> -0.1; f2>.5  -> 0.15
            [2.0,  9.9, 0.5],   # f0>1.5           -> 0.4 ; f2<=.5 -> -0.05
            [1.5, -0.25, 0.6],  # boundary: <= goes left   -> 0.2 + 0.15
        ])
        want_raw = np.array([0.2 - 0.05, -0.1 + 0.15, 0.4 - 0.05, 0.2 + 0.15])
        want_prob = 1.0 / (1.0 + np.exp(-want_raw))
        got = np.asarray(booster.predict(rows))
        np.testing.assert_allclose(got, want_prob, rtol=1e-6, atol=1e-7)
        raw = np.asarray(booster.predict_raw(rows))
        np.testing.assert_allclose(raw, want_raw, rtol=1e-6, atol=1e-7)

    def test_metadata(self, booster):
        assert booster.objective == "binary"
        assert booster.num_trees == 2
        assert booster.feature_names == ["f0", "f1", "f2"]

    def test_load_native_model_autodetects(self, booster, tmp_path):
        from mmlspark_tpu.gbdt.booster import Booster

        p = os.path.join(tmp_path, "model.txt")
        with open(p, "w") as fh:
            fh.write(LIGHTGBM_MODEL_TXT)
        loaded = Booster.load_native_model(p)
        x = np.random.default_rng(0).normal(size=(50, 3))
        np.testing.assert_array_equal(
            np.asarray(loaded.predict(x)), np.asarray(booster.predict(x))
        )

    def test_roundtrip_through_own_format(self, booster):
        """An imported LightGBM model survives this framework's own
        save/load with identical predictions."""
        from mmlspark_tpu.gbdt.booster import Booster

        x = np.random.default_rng(1).normal(size=(100, 3)) * 3
        again = Booster.from_text(booster.to_text())
        np.testing.assert_array_equal(
            np.asarray(again.predict(x)), np.asarray(booster.predict(x))
        )

    def test_export_roundtrip_through_lightgbm_format(self, wdbc):
        """A model trained HERE serializes to LightGBM's own model.txt
        (saveNativeModel parity: actual LightGBM could load it) and reloads
        through the format parser with identical predictions — export and
        import pin each other."""
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y = wdbc
        trained = Booster.train(x, y, TrainOptions(
            objective="binary", num_leaves=5, num_iterations=10,
        ))
        txt = trained.to_lightgbm_text()
        assert txt.startswith("tree\n") and "Tree=9" in txt
        again = Booster.from_lightgbm_text(txt)
        np.testing.assert_allclose(
            np.asarray(again.predict(x)), np.asarray(trained.predict(x)),
            rtol=1e-6, atol=1e-7,
        )
        # export synthesizes Column_j names when the model has none
        assert again.feature_names == [f"Column_{j}" for j in range(30)]

    def test_multiclass_export_roundtrip(self):
        """Multiclass models interleave one tree per class per round;
        num_class/num_tree_per_iteration and the softmax transform must
        survive the LightGBM-format roundtrip."""
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        rng = np.random.default_rng(3)
        x = rng.normal(size=(400, 5))
        y = (x[:, 0] > 0.5).astype(int) + (x[:, 1] > 0).astype(int)
        b = Booster.train(x, y.astype(np.float64), TrainOptions(
            objective="multiclass", num_class=3, num_leaves=7,
            num_iterations=4, min_data_in_leaf=5,
        ))
        txt = b.to_lightgbm_text()
        assert "num_class=3" in txt and "num_tree_per_iteration=3" in txt
        again = Booster.from_lightgbm_text(txt)
        np.testing.assert_allclose(
            np.asarray(again.predict(x)), np.asarray(b.predict(x)),
            rtol=1e-5, atol=1e-6,
        )

    def test_categorical_export_roundtrip(self):
        """A categorical (many-vs-many) model exports in LightGBM's own
        cat_boundaries/cat_threshold encoding and reloads with identical
        predictions — including unseen categories (route right)."""
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        rng = np.random.default_rng(0)
        cats = rng.integers(0, 7, 2000).astype(np.float64)
        y = np.isin(cats, [1, 2, 5]).astype(np.float64)
        x = np.column_stack([cats, rng.normal(size=2000)])
        b = Booster.train(x, y, TrainOptions(
            objective="binary", num_leaves=6, num_iterations=4,
            min_data_in_leaf=5, categorical_indexes=(0,),
        ))
        txt = b.to_lightgbm_text()
        assert "cat_boundaries=" in txt and "cat_threshold=" in txt
        again = Booster.from_lightgbm_text(txt)
        probe = np.vstack([x[:500], [[99.0, 0.0], [np.nan, 0.0]]])
        np.testing.assert_allclose(
            np.asarray(again.predict(probe)), np.asarray(b.predict(probe)),
            rtol=1e-6, atol=1e-7,
        )

    def test_categorical_export_rejects_noninteger_values(self):
        """LightGBM's on-file bitsets index by integer category value;
        fractional categories have no representation there."""
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        rng = np.random.default_rng(2)
        cats = rng.choice([0.5, 1.5, 2.5, 3.5], 1000)
        y = np.isin(cats, [0.5, 2.5]).astype(np.float64)
        x = np.column_stack([cats, rng.normal(size=1000)])
        b = Booster.train(x, y, TrainOptions(
            objective="binary", num_leaves=4, num_iterations=2,
            min_data_in_leaf=5, categorical_indexes=(0,),
        ))
        with pytest.raises(ValueError, match="non-integer"):
            b.to_lightgbm_text()

    def test_nan_right_node_rejected(self):
        from mmlspark_tpu.gbdt.booster import Booster

        # missing_type=NaN (8) + default_left clear -> routes NaN right
        bad = LIGHTGBM_MODEL_TXT.replace("decision_type=2 2",
                                         "decision_type=8 2")
        with pytest.raises(ValueError, match="missing"):
            Booster.from_lightgbm_text(bad)

    def test_malformed_categorical_rejected(self):
        """decision_type bit 0 without cat_boundaries/cat_threshold arrays
        is a corrupt file, not a loadable categorical model."""
        from mmlspark_tpu.gbdt.booster import Booster

        bad = LIGHTGBM_MODEL_TXT.replace("decision_type=2 2",
                                         "decision_type=3 2")
        with pytest.raises(ValueError, match="categorical"):
            Booster.from_lightgbm_text(bad)

    def test_not_a_model_rejected(self):
        from mmlspark_tpu.gbdt.booster import Booster

        with pytest.raises(ValueError, match="Tree="):
            Booster.from_lightgbm_text("hello\nworld\n")

    def test_nonunit_sigmoid_rejected(self):
        """LightGBM's binary transform is sigmoid(sigmoid_param * raw);
        loading sigmoid != 1 silently would scale every probability
        (ADVICE r3) — reject instead."""
        from mmlspark_tpu.gbdt.booster import Booster

        bad = LIGHTGBM_MODEL_TXT.replace("objective=binary sigmoid:1",
                                         "objective=binary sigmoid:2")
        with pytest.raises(ValueError, match="sigmoid"):
            Booster.from_lightgbm_text(bad)

    def test_inf_bins_by_comparison(self, booster):
        """±inf inputs follow LightGBM's `value <= threshold` routing
        (-inf left of every split, +inf right), NOT the NaN/missing path
        (ADVICE r3): f0=+inf fails f0<=1.5 -> leaf 0.4; f2=-inf passes
        f2<=0.5 -> leaf -0.05. NaN still takes the missing bin (left)."""
        inf = np.inf
        rows = np.array([
            [inf, 0.0, -inf],    # t0: f0>1.5 -> 0.4 ; t1: f2<=0.5 -> -0.05
            [-inf, -1.0, inf],   # t0: left,f1<=-.25 -> 0.2; t1: f2>.5 -> 0.15
        ])
        want_raw = np.array([0.4 - 0.05, 0.2 + 0.15])
        got = np.asarray(booster.predict_raw(rows))
        np.testing.assert_allclose(got, want_raw, rtol=1e-6, atol=1e-7)
        # NaN routes via the missing bin, which sorts left at every node
        nan_row = np.array([[np.nan, np.nan, np.nan]])
        np.testing.assert_allclose(
            np.asarray(booster.predict_raw(nan_row)),
            np.array([0.2 - 0.05]), rtol=1e-6, atol=1e-7,
        )


# Hand-authored model with one CATEGORICAL split in LightGBM's own on-file
# encoding (decision_type bit 0; threshold = index into cat_boundaries;
# cat_threshold packs left-routed category VALUES as uint32 bitset words).
# Word 18 = 2^1 + 2^4: categories {1, 4} go left.
LIGHTGBM_CAT_MODEL_TXT = """tree
version=v3
num_class=1
num_tree_per_iteration=1
label_index=0
max_feature_idx=1
objective=binary sigmoid:1
feature_names=c0 f1
feature_infos=none none

Tree=0
num_leaves=2
num_cat=1
split_feature=0
split_gain=7
threshold=0
decision_type=1
left_child=-1
right_child=-2
cat_boundaries=0 1
cat_threshold=18
leaf_value=0.6 -0.4
leaf_weight=10 10
leaf_count=10 10
internal_value=0
internal_count=20
shrinkage=0.1

end of trees
"""


class TestLightGBMCategoricalFormat:
    """The categorical on-file encoding is pinned to LightGBM's published
    semantics with a hand-decoded fixture (the numeric twin of
    TestLightGBMNativeFormat): bit v of the cat_threshold words set means
    raw category v routes LEFT; everything else — other categories, unseen
    values, NaN — routes RIGHT."""

    def test_hand_computed_categorical_predictions(self):
        from mmlspark_tpu.gbdt.booster import Booster

        b = Booster.from_lightgbm_text(LIGHTGBM_CAT_MODEL_TXT)
        rows = np.array([
            [1.0, 0.0],    # in {1,4}  -> left  -> 0.6
            [4.0, 9.9],    # in {1,4}  -> left  -> 0.6
            [0.0, 0.0],    # not in set -> right -> -0.4
            [2.0, 0.0],    # not in set -> right -> -0.4
            [40.0, 0.0],   # unseen     -> right -> -0.4
            [np.nan, 0.0], # missing    -> right -> -0.4
        ])
        want_raw = np.array([0.6, 0.6, -0.4, -0.4, -0.4, -0.4])
        np.testing.assert_allclose(
            np.asarray(b.predict_raw(rows)), want_raw, rtol=1e-6, atol=1e-7
        )
        want_prob = 1.0 / (1.0 + np.exp(-want_raw))
        np.testing.assert_allclose(
            np.asarray(b.predict(rows)), want_prob, rtol=1e-6, atol=1e-7
        )

    def test_roundtrips_preserve_categorical(self):
        from mmlspark_tpu.gbdt.booster import Booster

        b = Booster.from_lightgbm_text(LIGHTGBM_CAT_MODEL_TXT)
        probe = np.array([[1.0, 0.0], [3.0, 0.0], [4.0, 1.0], [7.0, 2.0]])
        again = Booster.from_text(b.to_text())
        np.testing.assert_array_equal(
            np.asarray(again.predict(probe)), np.asarray(b.predict(probe))
        )
        re_exported = Booster.from_lightgbm_text(b.to_lightgbm_text())
        np.testing.assert_allclose(
            np.asarray(re_exported.predict(probe)),
            np.asarray(b.predict(probe)), rtol=1e-6, atol=1e-7,
        )


class TestAgainstRealLightGBM:
    """Cross-checks against the actual lightgbm package (ADVICE r3: the
    'loadable by actual LightGBM' claim needs a test that runs wherever the
    package exists). Skipped in environments without lightgbm — the claim
    is then pinned only by the hand fixture above."""

    def test_export_loads_in_real_lightgbm(self, wdbc):
        lgb = pytest.importorskip("lightgbm")
        from mmlspark_tpu.gbdt.booster import Booster, TrainOptions

        x, y = wdbc
        trained = Booster.train(x, y, TrainOptions(
            objective="binary", num_leaves=5, num_iterations=10,
        ))
        real = lgb.Booster(model_str=trained.to_lightgbm_text())
        np.testing.assert_allclose(
            real.predict(x), np.asarray(trained.predict(x)),
            rtol=1e-5, atol=1e-6,
        )

    def test_real_lightgbm_model_loads_here(self, wdbc):
        lgb = pytest.importorskip("lightgbm")
        from mmlspark_tpu.gbdt.booster import Booster

        x, y = wdbc
        real = lgb.train(
            {"objective": "binary", "num_leaves": 5, "learning_rate": 0.1,
             "min_data_in_leaf": 20, "verbose": -1},
            lgb.Dataset(x, label=y), num_boost_round=10,
        )
        ours = Booster.from_lightgbm_text(real.model_to_string())
        np.testing.assert_allclose(
            np.asarray(ours.predict(x)), real.predict(x),
            rtol=1e-5, atol=1e-6,
        )

    def test_real_lightgbm_categorical_model_loads_here(self):
        lgb = pytest.importorskip("lightgbm")
        from mmlspark_tpu.gbdt.booster import Booster

        rng = np.random.default_rng(7)
        cats = rng.integers(0, 8, 2000).astype(np.float64)
        y = np.isin(cats, [0, 3, 6]).astype(np.float64)
        x = np.column_stack([cats, rng.normal(size=2000)])
        real = lgb.train(
            {"objective": "binary", "num_leaves": 6, "learning_rate": 0.3,
             "min_data_in_leaf": 5, "verbose": -1},
            lgb.Dataset(x, label=y, categorical_feature=[0]),
            num_boost_round=5,
        )
        ours = Booster.from_lightgbm_text(real.model_to_string())
        probe = np.vstack([x[:500], [[99.0, 0.0]]])
        np.testing.assert_allclose(
            np.asarray(ours.predict(probe)), real.predict(probe),
            rtol=1e-5, atol=1e-6,
        )
