"""Whole-pipeline fusion: plan partitioning, byte-identity, fallbacks.

The contract under test everywhere: `fuse()` changes WHERE stages execute
(one XLA program per maximal device-capable run, columns device-resident
between stages), never WHAT they produce. Fused and staged runs are
byte-identical across dtypes, ragged row counts ride the bucket ladder
without steady-state recompiles, non-fusable stages sandwiched
mid-pipeline fall back to the staged path unchanged, and serving /
streaming score through the fused path automatically.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core import (
    DeviceKernel,
    DeviceTable,
    FusedPipelineModel,
    fuse,
    pipeline_model,
    plan_fusion,
)
from mmlspark_tpu.core.dataplane import ShapeBucketer
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import PipelineModel, PipelineStage, Timer, Transformer
from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.core.serialize import register_stage
from mmlspark_tpu.nn.models import ModelBundle
from mmlspark_tpu.nn.runner import DeepModelTransformer
from mmlspark_tpu.ops.conversion import DataConversion
from mmlspark_tpu.ops.ensemble import EnsembleByKey
from mmlspark_tpu.ops.featurize import AssembleFeatures
from mmlspark_tpu.ops.missing import CleanMissingData


def _mlp(input_col="features", f=8, outputs=3, **kw):
    t = DeepModelTransformer(input_col=input_col, **kw)
    return t.set_model(ModelBundle.init("mlp", (f,), seed=0, num_outputs=outputs))


def _table(n=50, f=8, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    return Table({c: rng.normal(size=n).astype(dtype)
                  for c in "abcdefgh"[:f]})


@register_stage
class _DoubleOnHost(Transformer):
    """A deliberately non-fusable stage (no device_kernel)."""

    col = Param("x", "column", ptype=str)

    def _transform(self, table: Table) -> Table:
        return table.with_column(
            self.col_name(), np.asarray(table[self.col_name()]) * 2)

    def col_name(self):
        return self.get("col")


@register_stage
class _AddOneOnDevice(Transformer):
    col = Param("x", "column", ptype=str)

    def _transform(self, table: Table) -> Table:
        c = self.get("col")
        return table.with_column(
            c, np.asarray(table[c], np.float32) + np.float32(1))

    def device_kernel(self):
        c = self.get("col")
        return DeviceKernel(
            fn=lambda p, cols: {c: cols[c].astype("float32") + 1},
            input_cols=(c,), output_cols=(c,), out_dtypes={c: np.float32})


# --------------------------------------------------------------------- #
# planning
# --------------------------------------------------------------------- #


class TestPlanFusion:
    def test_maximal_runs_partition(self):
        plan = plan_fusion([_AddOneOnDevice(), _AddOneOnDevice(),
                            _DoubleOnHost(), _AddOneOnDevice()])
        assert [s.fused for s in plan.segments] == [True, False, True]
        assert [len(s.stages) for s in plan.segments] == [2, 1, 1]
        assert plan.n_fused_stages == 3 and plan.n_stages == 4
        assert plan.fusion_ratio == pytest.approx(0.75)

    def test_reasons_surface_for_host_stages(self):
        plan = plan_fusion([_DoubleOnHost(),
                            EnsembleByKey(keys=["k"], cols=["v"])])
        reasons = [sp.reason for s in plan.segments for sp in s.stages]
        assert "no device kernel declared" in reasons[0]
        assert "data-dependent output shape" in reasons[1]
        assert "HOST" in plan.describe()

    def test_nested_pipeline_models_flatten_into_runs(self):
        inner = pipeline_model(_AddOneOnDevice(), _AddOneOnDevice())
        plan = plan_fusion([_AddOneOnDevice(), inner])
        assert len(plan.segments) == 1 and plan.segments[0].fused
        assert len(plan.segments[0].stages) == 3

    def test_transfer_counts(self):
        plan = plan_fusion([_AddOneOnDevice(), _AddOneOnDevice(),
                            _DoubleOnHost(), _AddOneOnDevice()])
        fused, staged = plan.transfers_per_batch()
        assert fused == 4      # 2 fused segments x (1 in + 1 out)
        assert staged == 6     # 3 device stages x (1 in + 1 out)

    def test_broken_declaration_stays_on_host(self):
        class Broken(_AddOneOnDevice):
            def device_kernel(self):
                raise RuntimeError("boom")

        plan = plan_fusion([Broken()])
        assert not plan.segments[0].fused
        assert "device_kernel() failed" in plan.segments[0].stages[0].reason

    def test_fuse_is_idempotent_and_wraps_bare_transformers(self):
        fm = fuse(pipeline_model(_AddOneOnDevice()))
        assert fuse(fm) is fm
        single = fuse(_AddOneOnDevice())
        assert isinstance(single, FusedPipelineModel)
        with pytest.raises(TypeError):
            fuse(object())


# --------------------------------------------------------------------- #
# DeviceTable
# --------------------------------------------------------------------- #


class TestDeviceTable:
    def test_round_trip_and_with_columns(self):
        dt = DeviceTable.from_host({"x": np.arange(4.0, dtype=np.float32)})
        assert "x" in dt and dt.columns == ["x"] and len(dt) == 1
        dt2 = dt.with_columns({"y": dt["x"] * 2})
        host = dt2.to_host()
        assert host["y"].tolist() == [0.0, 2.0, 4.0, 6.0]
        # derivation never mutates the parent
        assert dt.columns == ["x"]


# --------------------------------------------------------------------- #
# byte identity, fused vs staged
# --------------------------------------------------------------------- #


class TestByteIdentity:
    def _assert_identical(self, staged: Table, fused: Table):
        assert staged.columns == fused.columns
        for c in staged.columns:
            s, f = staged[c], fused[c]
            if isinstance(s, np.ndarray):
                assert s.dtype == f.dtype, c
                assert s.tobytes() == f.tobytes(), c
            else:
                assert list(s) == list(f), c
            assert staged.meta(c) == fused.meta(c), c

    def test_f32_featurize_clean_model_postprocess_chain(self):
        t = _table(57)
        rng = np.random.default_rng(3)
        cat = rng.integers(0, 4, size=57).astype(np.float64)
        t = t.with_column("cat", cat, meta={"category_values": list("wxyz")})
        asm = AssembleFeatures(
            columns_to_featurize=[*"abcdefgh", "cat"]).fit(t)
        nanify = t["a"].copy()
        nanify[::9] = np.nan
        t = t.with_column("a", nanify)
        runner = _mlp(f=12)
        conv = DataConversion(cols=["out"], convert_to="float")
        # CleanMissingData fuses on the float32 features matrix between
        # assembly and the model
        clean = CleanMissingData(
            input_cols=["b"], output_cols=["b"], cleaning_mode="Mean",
        ).fit(Table({"b": t["b"].astype(np.float32)}))
        staged_model = pipeline_model(asm, runner, conv)
        fused_model = fuse(pipeline_model(asm, runner, conv),
                           mini_batch_size=16)
        runner.set(fetch_dict={"out": "logits"})
        staged = staged_model.transform(t)
        fused = fused_model.transform(t)
        assert fused_model.last_stats["segments"][0]["kind"] == "fused"
        self._assert_identical(staged, fused)
        del clean  # float32 clean path covered in test below

    def test_f32_clean_missing_fuses_and_matches(self):
        x = np.arange(40, dtype=np.float32)
        x[::7] = np.nan
        t = Table({"a": x})
        cm = CleanMissingData(input_cols=["a"], output_cols=["a_clean"],
                              cleaning_mode="Median").fit(t)
        fm = fuse(pipeline_model(cm, _AddOneOnDevice(col="a_clean")))
        staged = _AddOneOnDevice(col="a_clean").transform(cm.transform(t))
        fused = fm.transform(t)
        assert fm.last_stats["segments"][0]["kind"] == "fused"
        self._assert_identical(staged, fused)

    def test_f64_clean_missing_falls_back_and_matches(self):
        x = np.arange(40, dtype=np.float64)
        x[::7] = np.nan
        t = Table({"a": x})
        cm = CleanMissingData(input_cols=["a"], output_cols=["a_clean"],
                              cleaning_mode="Mean").fit(t)
        fm = fuse(pipeline_model(cm))
        fused = fm.transform(t)
        seg = fm.last_stats["segments"][0]
        assert seg["kind"] == "host_fallback" and "float64" in seg["reason"]
        self._assert_identical(cm.transform(t), fused)

    def test_bf16_runner_fused_matches_staged(self):
        t = _table(33)
        asm = AssembleFeatures(columns_to_featurize=list("abcdefgh")).fit(t)
        runner = _mlp(bfloat16=True)
        staged = pipeline_model(asm, runner).transform(t)
        fm = fuse(pipeline_model(asm, runner), mini_batch_size=8)
        fused = fm.transform(t)
        assert fm.last_stats["segments"][0]["kind"] == "fused"
        self._assert_identical(staged, fused)

    def test_int_conversion_fused_matches_staged(self):
        t = Table({"x": np.asarray([1.0, -2.5, 3.9, -0.1, 7.0], np.float32),
                   "y": np.asarray([0, 1, 2, 0, 5], np.int32)})
        for target in ("integer", "short", "byte", "boolean"):
            conv = DataConversion(cols=["x", "y"], convert_to=target)
            fm = fuse(pipeline_model(conv))
            fused = fm.transform(t)
            assert fm.last_stats["segments"][0]["kind"] == "fused", target
            self._assert_identical(conv.transform(t), fused)

    def test_conversion_f64_input_falls_back(self):
        t = Table({"x": np.asarray([1.0, 2.0])})  # float64
        conv = DataConversion(cols=["x"], convert_to="float")
        fm = fuse(pipeline_model(conv))
        fused = fm.transform(t)
        assert fm.last_stats["segments"][0]["kind"] == "host_fallback"
        self._assert_identical(conv.transform(t), fused)

    def test_gbdt_regression_fuses_and_matches(self):
        rng = np.random.default_rng(5)
        # float32-representable float64 features: the binning bit-identity
        # precondition the ready() check enforces
        X = rng.normal(size=(300, 6)).astype(np.float32).astype(np.float64)
        X[::11, 0] = np.nan
        y = 2 * np.nan_to_num(X[:, 0]) + np.sin(X[:, 1])
        t = Table({"features": X, "label": y})
        from mmlspark_tpu.gbdt.estimators import GBDTRegressor

        model = GBDTRegressor(features_col="features", label_col="label",
                              num_iterations=12, num_leaves=15).fit(t)
        fm = fuse(pipeline_model(model), mini_batch_size=128)
        assert fm.plan().segments[0].fused
        fused = fm.transform(t)
        assert fm.last_stats["segments"][0]["kind"] == "fused"
        self._assert_identical(model.transform(t), fused)

    def test_gbdt_classifier_declares_host_reason(self):
        rng = np.random.default_rng(6)
        X = rng.normal(size=(120, 4))
        y = (X[:, 0] > 0).astype(np.float64)
        t = Table({"features": X, "label": y})
        from mmlspark_tpu.gbdt.estimators import GBDTClassifier

        model = GBDTClassifier(features_col="features", label_col="label",
                               num_iterations=5).fit(t)
        plan = plan_fusion([model])
        assert not plan.segments[0].fused
        assert "float64" in plan.segments[0].stages[0].reason
        fm = fuse(pipeline_model(model))
        self._assert_identical(model.transform(t), fm.transform(t))

    def test_empty_table_runs_host_path(self):
        t = Table({"x": np.asarray([], np.float32)})
        fm = fuse(pipeline_model(_AddOneOnDevice()))
        out = fm.transform(t)
        assert out["x"].shape == (0,)
        assert fm.last_stats["segments"][0]["kind"] == "host_fallback"


# --------------------------------------------------------------------- #
# host sandwich / segmentation at runtime
# --------------------------------------------------------------------- #


class TestHostSandwich:
    def test_non_fusable_stage_mid_pipeline(self):
        t = Table({"x": np.arange(20, dtype=np.float32)})
        stages = [_AddOneOnDevice(), _AddOneOnDevice(), _DoubleOnHost(),
                  _AddOneOnDevice()]
        staged = pipeline_model(*stages).transform(t)
        fm = fuse(pipeline_model(*stages))
        fused = fm.transform(t)
        kinds = [s["kind"] for s in fm.last_stats["segments"]]
        assert kinds == ["fused", "host", "fused"]
        assert staged["x"].tobytes() == fused["x"].tobytes()

    def test_serialization_round_trip(self, tmp_path):
        t = Table({"x": np.arange(10, dtype=np.float32)})
        fm = fuse(pipeline_model(_AddOneOnDevice(), _DoubleOnHost(),
                                 _AddOneOnDevice()), mini_batch_size=4)
        expected = fm.transform(t)
        path = str(tmp_path / "fm")
        fm.save(path)
        loaded = PipelineStage.load(path)
        assert isinstance(loaded, FusedPipelineModel)
        assert loaded.get("mini_batch_size") == 4
        assert loaded.transform(t)["x"].tobytes() == expected["x"].tobytes()


# --------------------------------------------------------------------- #
# ragged tails through the bucket ladder
# --------------------------------------------------------------------- #


class TestRaggedLadder:
    def test_ragged_sizes_are_identical_and_stop_recompiling(self):
        runner = _mlp()
        asm_fit = _table(16)
        asm = AssembleFeatures(columns_to_featurize=list("abcdefgh")).fit(
            asm_fit)
        fm = fuse(pipeline_model(asm, runner), mini_batch_size=16)
        staged = pipeline_model(asm, runner)

        # warm the full ladder (every bucket compiles once)
        for n in ShapeBucketer(16).ladder:
            fm.transform(_table(n, seed=n))
        seg = fm._segments[0]
        warm = seg._exec_cache.stats()

        for i, n in enumerate((3, 7, 1, 29, 16, 2, 41, 5)):
            t = _table(n, seed=100 + i)
            s, f = staged.transform(t), fm.transform(t)
            for c in s.columns:
                assert s[c].tobytes() == f[c].tobytes(), (n, c)
        soaked = seg._exec_cache.stats()
        assert soaked["misses"] == warm["misses"]
        assert soaked["recompiles"] == warm["recompiles"]
        assert soaked["hits"] > warm["hits"]

    def test_buckets_off_pads_to_mini_batch(self):
        fm = fuse(pipeline_model(_AddOneOnDevice()), mini_batch_size=8,
                  shape_buckets=False)
        t = Table({"x": np.arange(13, dtype=np.float32)})
        out = fm.transform(t)
        assert out["x"].tolist() == [float(i + 1) for i in range(13)]

    def test_fully_fusable_chain_moves_two_transfers_per_batch(self):
        # model + postprocess over one input column, one output column:
        # each mini-batch costs exactly 1 upload (features) + 1 download
        # (the score) — the staged path would pay 4 (2 per device stage)
        rng = np.random.default_rng(12)
        t = Table({"features": rng.normal(size=(64, 8)).astype(np.float32)})
        fm = fuse(pipeline_model(
            _mlp(), DataConversion(cols=["output"], convert_to="float")),
            mini_batch_size=16)
        fm.transform(t)
        stats = fm.last_stats
        n_batches = 4
        assert stats["segments"][0]["kind"] == "fused"
        assert stats["uploads"] == n_batches
        assert stats["downloads"] == n_batches
        per_batch = (stats["uploads"] + stats["downloads"]) / n_batches
        assert per_batch <= 2
        _, staged = fm.plan().transfers_per_batch()
        assert staged == 4

    def test_prefetch_depth_zero_is_identical(self):
        t = _table(37)
        asm = AssembleFeatures(columns_to_featurize=list("abcdefgh")).fit(t)
        outs = []
        for depth in (0, 2):
            fm = fuse(pipeline_model(asm, _mlp()), mini_batch_size=8,
                      prefetch_depth=depth)
            outs.append(fm.transform(t)["output"].tobytes())
        assert outs[0] == outs[1]


# --------------------------------------------------------------------- #
# observability
# --------------------------------------------------------------------- #


class TestObservability:
    def test_fusion_ratio_gauge_and_spans(self):
        from mmlspark_tpu.observability.metrics import get_registry
        from mmlspark_tpu.observability.tracing import get_tracer

        fm = fuse(pipeline_model(_AddOneOnDevice(), _DoubleOnHost()),
                  fused_label="ratio-test")
        tracer = get_tracer()
        before = len(tracer.spans())
        fm.transform(Table({"x": np.arange(8, dtype=np.float32)}))
        names = [s.name for s in tracer.spans()[before:]]
        assert "pipeline.fused_segment" in names
        gauge = get_registry().gauge(
            "mmlspark_tpu_pipeline_fusion_ratio",
            labels=("pipeline", "mesh_shape")).labels(
                pipeline="ratio-test", mesh_shape="1")
        assert gauge.value == pytest.approx(0.5)

    def test_timer_reports_device_host_split_for_fused(self):
        fm = fuse(pipeline_model(_AddOneOnDevice(), _DoubleOnHost()))
        timer = Timer(fm)
        timer.transform(Table({"x": np.arange(8, dtype=np.float32)}))
        assert timer.last_segments is not None
        kinds = [s["kind"] for s in timer.last_segments]
        assert kinds == ["fused", "host"]
        fused_seg, host_seg = timer.last_segments
        assert fused_seg["seconds"] == pytest.approx(
            fused_seg["device_seconds"] + fused_seg["host_seconds"])
        assert host_seg["device_seconds"] == 0.0
        assert host_seg["host_seconds"] == host_seg["seconds"]

    def test_timer_plain_stage_has_no_segments(self):
        timer = Timer(_DoubleOnHost())
        timer.transform(Table({"x": np.arange(4.0)}))
        assert timer.last_segments is None


# --------------------------------------------------------------------- #
# serving + streaming integration
# --------------------------------------------------------------------- #


def _post(url: str, payload: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


class TestServingIntegration:
    def test_serve_model_auto_fuses_pipeline_models(self):
        from mmlspark_tpu.io_http.serving import serve_model

        model = pipeline_model(_mlp(f=2, outputs=2))
        fm = fuse(model, mini_batch_size=16)
        # warm every ladder bucket deterministically (HTTP batch sizes are
        # timing-dependent) with the same (n, 2) float64 features layout
        # the serving handler stacks
        for n in ShapeBucketer(16).ladder:
            fm.transform(Table({"features": np.ones((n, 2), np.float64)}))
        seg = fm._segments[0]
        warm = seg._exec_cache.stats()
        srv = serve_model(fm, input_cols=["a", "b"], output_col="output",
                          max_batch_size=16)
        try:
            def fire(n):
                errs = []

                def one(i):
                    try:
                        _post(srv.url, {"a": float(i), "b": 1.0})
                    except Exception as e:  # noqa: BLE001
                        errs.append(repr(e))

                ts = [threading.Thread(target=one, args=(i,))
                      for i in range(n)]
                for th in ts:
                    th.start()
                for th in ts:
                    th.join(timeout=30)
                assert not errs, errs

            for n in (1, 4, 8, 3, 7, 12, 16, 2, 9, 5):
                fire(n)
        finally:
            srv.stop()
        soaked = seg._exec_cache.stats()
        # the serving soak acceptance bar: zero steady-state recompiles of
        # the fused segment once the ladder is warm
        assert soaked["misses"] == warm["misses"]
        assert soaked["recompiles"] == warm["recompiles"]
        assert soaked["hits"] > warm["hits"]

    def test_serve_model_fuse_opt_out(self):
        from mmlspark_tpu.io_http import serving as serving_mod

        captured = {}
        orig = serving_mod.ServingServer

        class Capture(orig):
            def __init__(self, handler, **kw):
                captured["handler"] = handler
                super().__init__(handler, **kw)

            def start(self):
                return self

            def stop(self):
                pass

        serving_mod.ServingServer, restore = Capture, orig
        try:
            model = pipeline_model(_AddOneOnDevice())
            serving_mod.serve_model(model, input_cols=["x"],
                                    fuse_pipeline=False)
        finally:
            serving_mod.ServingServer = restore
        assert captured["handler"] is not None


class TestStreamingIntegration:
    def test_query_auto_fuses_and_matches_staged(self):
        from mmlspark_tpu.streaming import MemorySink, MemorySource
        from mmlspark_tpu.streaming.query import StreamingQuery

        model = pipeline_model(_AddOneOnDevice(), _AddOneOnDevice())
        src, sink = MemorySource(), MemorySink()
        q = StreamingQuery(src, model, sink)
        assert isinstance(q.transform, FusedPipelineModel)
        t = Table({"x": np.arange(6, dtype=np.float32)})
        src.add_rows(t)
        assert q.process_all_available() == 1
        staged = model.transform(t)
        assert sink.table()["x"].tobytes() == staged["x"].tobytes()

    def test_query_fuse_opt_out_keeps_model(self):
        from mmlspark_tpu.streaming import MemorySink, MemorySource
        from mmlspark_tpu.streaming.query import StreamingQuery

        model = pipeline_model(_AddOneOnDevice())
        q = StreamingQuery(MemorySource(), model, MemorySink(),
                           fuse_pipeline=False)
        assert q.transform is model


# --------------------------------------------------------------------- #
# ImageTransformer compile-cache quick win
# --------------------------------------------------------------------- #


class TestImageChainCache:
    def test_op_chain_compiles_once_across_transforms(self):
        from mmlspark_tpu.image.transformer import ImageTransformer

        rng = np.random.default_rng(7)
        t = Table({"image": rng.uniform(0, 255, size=(6, 10, 10, 3))})
        it = ImageTransformer(input_col="image", output_col="o") \
            .resize(8, 8).blur(3, 3)
        first = it.transform(t)
        assert it.compile_count == 1
        second = it.transform(t)
        assert it.compile_count == 1  # cached — no re-trace per call
        assert first["o"].tobytes() == second["o"].tobytes()
        # a new shape compiles once more, then is cached too
        t2 = Table({"image": rng.uniform(0, 255, size=(3, 12, 12, 3))})
        it.transform(t2)
        assert it.compile_count == 2
        it.transform(t2)
        assert it.compile_count == 2

    def test_image_chain_fused_matches_staged(self):
        from mmlspark_tpu.image.transformer import ImageTransformer

        rng = np.random.default_rng(8)
        t = Table({"image": rng.uniform(0, 255, size=(9, 10, 10, 3))})
        it = ImageTransformer(input_col="image", output_col="o") \
            .resize(8, 8).gray(keep_channels=True).threshold(90.0)
        staged = it.transform(t)
        fm = fuse(pipeline_model(it), mini_batch_size=4)
        fused = fm.transform(t)
        assert fm.last_stats["segments"][0]["kind"] == "fused"
        assert staged["o"].tobytes() == fused["o"].tobytes()
        assert staged.meta("o") == fused.meta("o")

    def test_ragged_image_column_falls_back(self):
        from mmlspark_tpu.image.transformer import ImageTransformer

        rng = np.random.default_rng(9)
        imgs = [rng.uniform(size=(10, 10, 3)), rng.uniform(size=(12, 12, 3))]
        t = Table({"image": imgs})
        it = ImageTransformer(input_col="image", output_col="o").resize(8, 8)
        fm = fuse(pipeline_model(it))
        fused = fm.transform(t)
        assert fm.last_stats["segments"][0]["kind"] == "host_fallback"
        staged = it.transform(t)
        assert staged["o"].tobytes() == fused["o"].tobytes()
