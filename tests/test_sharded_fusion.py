"""Sharded fused execution (core/fusion.py under a parallel/mesh.py mesh).

The contract layered on top of test_fusion.py's: a mesh changes WHERE a
fused segment's work lands (rows sharded over the data axis, params
replicated or kernel-placed), never WHAT it produces.  Fused-sharded,
fused-single-device, and staged runs are byte-identical — including
ragged tails riding mesh-divisible buckets and the tensor-parallel MLP
body on a 2-D data x model mesh.  Mesh shape is part of the executable
cache's family key (a chip-count change is a new family, never a
recompile of an old one), a fixed mesh shape soaks with zero steady-state
compiles, and no mesh / a 1-device mesh is the exact single-chip path.

Runs on the conftest-forced 8 host-platform CPU devices, the same
"multi-chip in one process" harness the reference simulates multi-node
with (partitions-in-one-JVM local[*] sessions).
"""

from __future__ import annotations

import numpy as np
import pytest

from mmlspark_tpu.core.dataplane import ExecutableCache, ShapeBucketer
from mmlspark_tpu.core.fusion import FusedPipelineModel, fuse
from mmlspark_tpu.core.pipeline import pipeline_model
from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.nn.models import ModelBundle
from mmlspark_tpu.nn.runner import DeepModelTransformer
from mmlspark_tpu.ops.conversion import DataConversion
from mmlspark_tpu.parallel.mesh import make_mesh


def _mlp(input_col="x", f=16, outputs=4, **kw):
    """Widths all divisible by 2 so the tensor-parallel body qualifies on
    a model axis of 2."""
    t = DeepModelTransformer(input_col=input_col, **kw)
    return t.set_model(ModelBundle.init(
        "mlp", (f,), seed=0, num_outputs=outputs, features=(16, 8)))


def _xtable(n, f=16, seed=3):
    rng = np.random.default_rng(seed)
    return Table({"x": rng.normal(size=(n, f)).astype(np.float32)})


def _stages(bs=32, **mlp_kw):
    return [_mlp(mini_batch_size=bs, **mlp_kw),
            DataConversion(cols=["output"], convert_to="float")]


# --------------------------------------------------------------------- #
# byte-identity
# --------------------------------------------------------------------- #


class TestShardedByteIdentity:
    def test_data_parallel_vs_single_vs_staged_ragged(self, mesh8):
        # 103 = 3 full 32-row chunks + a ragged 7-row tail: the tail pads
        # to a mesh-divisible bucket (multiple of 8) and the padding mask
        # must slice off identically on every shard layout
        table = _xtable(103)
        staged = pipeline_model(*_stages())
        fused1 = fuse(pipeline_model(*_stages()), mini_batch_size=32)
        fused8 = fuse(pipeline_model(*_stages()), mini_batch_size=32,
                      mesh=mesh8)
        out_s = np.asarray(staged.transform(table)["output"])
        out_1 = np.asarray(fused1.transform(table)["output"])
        out_8 = np.asarray(fused8.transform(table)["output"])
        assert out_1.tobytes() == out_s.tobytes()
        assert out_8.tobytes() == out_1.tobytes()
        assert fused8.last_stats["mesh_shape"] == "8x1"
        seg = fused8.last_stats["segments"][0]
        assert seg["kind"] == "fused"
        assert seg["mesh_shape"] == "8x1"
        # MLP variables replicate; DataConversion is parameterless
        assert seg["param_placements"] == ["replicated", "none"]

    def test_tensor_parallel_2d_mesh(self):
        import jax

        mesh = make_mesh(n_data=4, n_model=2, devices=jax.devices()[:8])
        t = _mlp(mini_batch_size=32,
                 fetch_dict={"out": "logits", "prob": "probability"})
        table = _xtable(70, seed=5)
        ref = t.transform(table)
        fused = fuse(_mlp(mini_batch_size=32,
                          fetch_dict={"out": "logits",
                                      "prob": "probability"}),
                     mini_batch_size=32, mesh=mesh)
        got = fused.transform(table)
        for c in ("out", "prob"):
            assert np.asarray(got[c]).tobytes() == \
                np.asarray(ref[c]).tobytes()
        seg = fused.last_stats["segments"][0]
        assert seg["mesh_shape"] == "4x2"
        # the kernel's mesh_fn swapped in the column-parallel body and
        # placed the dense params itself
        assert seg["param_placements"] == ["custom"]

    def test_gbdt_rows_sharded_params_replicated(self, mesh8, rng):
        import jax

        from mmlspark_tpu.gbdt.estimators import GBDTRegressor

        model = GBDTRegressor(
            features_col="features", label_col="label", num_iterations=4,
            num_leaves=7,
        ).fit(Table({"features": rng.normal(size=(64, 3)),
                     "label": rng.normal(size=64)}))
        # float32-representable features: the kernel's ready() check
        # refuses anything device binning would re-bucket
        score = Table({"features": rng.normal(
            size=(81, 3)).astype(np.float32).astype(np.float64)})
        ref = np.asarray(model.transform(score)["prediction"])
        fused = fuse(pipeline_model(model), mini_batch_size=32, mesh=mesh8)
        got = np.asarray(fused.transform(score)["prediction"])
        assert got.tobytes() == ref.tobytes()
        seg_stats = fused.last_stats["segments"][0]
        assert seg_stats["param_placements"] == ["custom"]
        # "custom" here must still mean fully replicated: the binning
        # table and tree SoAs live whole on every chip
        seg = fused._ensure_segments()[0]
        for leaf in jax.tree.leaves(seg._device_params):
            assert leaf.sharding.is_fully_replicated

    def test_shard_skew_gauge_recorded(self, mesh8):
        from mmlspark_tpu.observability.metrics import get_registry

        fused = fuse(pipeline_model(*_stages()), mini_batch_size=32,
                     mesh=mesh8, fused_label="skew-test")
        fused.transform(_xtable(64))
        seg = fused.last_stats["segments"][0]
        assert seg["shard_skew_ratio"] >= 1.0
        gauge = get_registry().gauge(
            "mmlspark_tpu_shard_skew_ratio",
            labels=("pipeline", "mesh_shape")).labels(
                pipeline="skew-test", mesh_shape="8x1")
        assert gauge.value >= 1.0


# --------------------------------------------------------------------- #
# cache-key isolation
# --------------------------------------------------------------------- #


class TestCacheKeys:
    def test_family_key_without_mesh_is_the_pr5_key(self):
        base = ("seg", ("x", "float32", (16,)))
        assert ExecutableCache.family_key(base) is base

    def test_family_key_differs_across_mesh_shapes(self):
        base = ("seg", ("x", "float32", (16,)))
        spec = (("mlp", "replicated"), ("x", "P(data)"))
        k8 = ExecutableCache.family_key(
            base, mesh_shape=(("data", 8), ("model", 1)), sharding_spec=spec)
        k4 = ExecutableCache.family_key(
            base, mesh_shape=(("data", 4), ("model", 1)), sharding_spec=spec)
        assert k8 != base and k4 != base and k8 != k4

    def test_segment_keys_carry_mesh_only_when_sharded(self, mesh8):
        import jax

        ins = {"x": np.zeros((32, 16), np.float32)}
        seg_none = fuse(pipeline_model(*_stages()),
                        mini_batch_size=32)._ensure_segments()[0]
        key_none = seg_none._family_key(ins)
        assert key_none[0] == id(seg_none)  # bare PR-5 base, no mesh part

        seg8 = fuse(pipeline_model(*_stages()), mini_batch_size=32,
                    mesh=mesh8)._ensure_segments()[0]
        mesh4 = make_mesh(n_data=4, devices=jax.devices()[:4])
        seg4 = fuse(pipeline_model(*_stages()), mini_batch_size=32,
                    mesh=mesh4)._ensure_segments()[0]
        k8, k4 = seg8._family_key(ins), seg4._family_key(ins)
        # (base, ("mesh", mesh_shape, sharding_spec)); the mesh parts must
        # differ across shapes even though the column contract is the same
        assert k8[1][0] == "mesh" and k4[1][0] == "mesh"
        assert k8[1][1:] != k4[1][1:]

    def test_bucket_ladder_is_mesh_divisible(self):
        for step in ShapeBucketer(32, multiple_of=8).ladder:
            assert step % 8 == 0


# --------------------------------------------------------------------- #
# steady state
# --------------------------------------------------------------------- #


class TestSteadyState:
    def test_zero_recompiles_at_fixed_mesh_shape(self, mesh8):
        fused = fuse(pipeline_model(*_stages()), mini_batch_size=32,
                     mesh=mesh8)
        # warm every bucket the 32-row ladder can mint: full chunks plus
        # ragged tails of 7 (-> 8) and 16 rows
        for n in (103, 80, 64):
            fused.transform(_xtable(n, seed=n))
        seg = fused._ensure_segments()[0]
        warm = seg._exec_cache.stats()
        for n in (103, 80, 64, 40, 96, 7):
            fused.transform(_xtable(n, seed=100 + n))
        after = seg._exec_cache.stats()
        assert after["misses"] == warm["misses"]
        assert after["recompiles"] == warm["recompiles"]
        assert after["hits"] > warm["hits"]


# --------------------------------------------------------------------- #
# fallback: no mesh / trivial mesh is the exact single-chip path
# --------------------------------------------------------------------- #


class TestFallback:
    def test_no_mesh_and_one_device_mesh_are_single_chip(self):
        import jax

        plain = fuse(pipeline_model(*_stages()), mini_batch_size=32)
        trivial = fuse(pipeline_model(*_stages()), mini_batch_size=32,
                       mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
        table = _xtable(20)
        out_p = np.asarray(plain.transform(table)["output"])
        out_t = np.asarray(trivial.transform(table)["output"])
        assert out_t.tobytes() == out_p.tobytes()
        for fm in (plain, trivial):
            seg = fm._ensure_segments()[0]
            assert fm._mesh is None and seg.mesh is None
            assert set(seg._param_placements) == {"single"}
            assert fm.last_stats["mesh_shape"] == "1"
            assert "param_placements" not in fm.last_stats["segments"][0]
            # bare PR-5 family key: no mesh component at all
            key = seg._family_key({"x": np.zeros((8, 16), np.float32)})
            assert key[0] == id(seg)

    def test_fuse_with_mesh_on_fused_model_reattaches(self, mesh8):
        fm = fuse(pipeline_model(*_stages()), mini_batch_size=32)
        assert fuse(fm) is fm
        assert fuse(fm, mesh=mesh8) is fm
        assert fm._effective_mesh() is mesh8
        fm.set_mesh(None)
        assert fm._effective_mesh() is None


# --------------------------------------------------------------------- #
# mesh threading: serving + streaming
# --------------------------------------------------------------------- #


class TestMeshThreading:
    def test_streaming_query_auto_fuses_under_mesh(self, mesh8):
        from mmlspark_tpu.streaming.query import StreamingQuery
        from mmlspark_tpu.streaming.sources import MemorySource

        q = StreamingQuery(source=MemorySource(),
                           transform=pipeline_model(*_stages()),
                           mesh=mesh8)
        assert isinstance(q.transform, FusedPipelineModel)
        assert q.transform._effective_mesh() is mesh8

    def test_serve_model_threads_mesh(self, mesh8):
        from mmlspark_tpu.io_http.serving import serve_model

        # an already-fused handler gets the mesh attached in place
        fm = fuse(pipeline_model(*_stages()), mini_batch_size=32)
        serve_model(fm, input_cols=["x"], mesh=mesh8)
        assert fm._effective_mesh() is mesh8
