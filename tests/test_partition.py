"""Distributed streaming tests: keyed shuffle, partition-parallel
stateful execution, streaming joins, state backends, per-partition
incremental checkpoints, and the fleet partition workers.

The load-bearing invariant everywhere: a `ParallelStreamingQuery` run at
any P is BYTE-identical to the P=1 `StreamingQuery` run over the same
batches — including across driver SIGKILL and partition-worker kill
(the slow tier), which is the exactly-once gate extended to P > 1.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import pipeline_model
from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.core.table_io import write_csv
from mmlspark_tpu.streaming import (
    CommitLog,
    DirectorySource,
    GroupedAggregator,
    KeyedShuffle,
    MemorySink,
    MemorySource,
    ParallelStreamingQuery,
    PartitionWorkerFactory,
    SpillingStateBackend,
    StreamingQuery,
    StreamStreamJoin,
    StreamTableJoin,
    WindowedAggregator,
    partition_of,
    split_by_partition,
    split_pipeline_at_shuffle,
    stable_hash,
)


def _assert_byte_identical(a: Table, b: Table) -> None:
    """Exact equality — not Table.equals' tolerant compare. Identical
    fold order must give bitwise-identical floats."""
    assert sorted(a.columns) == sorted(b.columns)
    assert a.num_rows == b.num_rows
    for c in a.columns:
        ca, cb = a[c], b[c]
        if isinstance(ca, np.ndarray) or isinstance(cb, np.ndarray):
            np.testing.assert_array_equal(np.asarray(ca), np.asarray(cb))
        else:
            assert list(ca) == list(cb)


def _key_for_partition(p: int, num_partitions: int, prefix: str = "k") -> str:
    for i in range(1000):
        k = f"{prefix}{i}"
        if partition_of(k, num_partitions) == p:
            return k
    raise AssertionError("no key found")


def _grouped_batches(seed: int = 3, n_batches: int = 5, rows: int = 40,
                     keys: int = 16) -> "list[Table]":
    rng = np.random.default_rng(seed)
    return [Table({"k": [f"k{int(i)}" for i in rng.integers(0, keys, rows)],
                   "v": rng.normal(size=rows)})
            for _ in range(n_batches)]


def _drive(q, src, batches) -> None:
    for b in batches:
        src.add_rows(b)
        q.process_all_available()


# --------------------------------------------------------------------------- #
# shuffle primitives


class TestShuffle:
    def test_stable_hash_is_process_stable(self):
        """Python's builtin hash is salted per process; routing must not
        be. A fresh interpreter computes the same digests."""
        from tests.conftest import subprocess_env

        local = [stable_hash("alpha"), stable_hash(7), stable_hash(2.5)]
        out = subprocess.run(
            [sys.executable, "-c",
             "from mmlspark_tpu.streaming import stable_hash\n"
             "print(stable_hash('alpha'), stable_hash(7), "
             "stable_hash(2.5))"],
            env=subprocess_env(), capture_output=True, text=True,
            timeout=120)
        assert out.returncode == 0, out.stderr[-2000:]
        assert [int(x) for x in out.stdout.split()] == local

    def test_split_is_disjoint_and_order_preserving(self):
        rng = np.random.default_rng(0)
        t = Table({"k": [f"k{int(i)}" for i in rng.integers(0, 9, 60)],
                   "v": np.arange(60.0)})
        parts = split_by_partition(t, "k", 4)
        assert sum(p.num_rows for p in parts) == 60
        for pid, part in enumerate(parts):
            for k in part["k"]:
                assert partition_of(k, 4) == pid       # disjoint keys
            # within-partition order == input order (the v column is the
            # input row index, so it must be strictly increasing)
            vs = list(part["v"])
            assert vs == sorted(vs)
        # per-key row sequence is exactly the key's input subsequence
        for key in set(t["k"]):
            pid = partition_of(key, 4)
            got = [v for k, v in zip(parts[pid]["k"], parts[pid]["v"])
                   if k == key]
            want = [v for k, v in zip(t["k"], t["v"]) if k == key]
            assert got == want

    def test_split_empty_and_p1(self):
        t = Table({"k": ["a"], "v": np.array([1.0])})
        assert split_by_partition(t, "k", 1) == [t]
        parts = split_by_partition(Table({"k": [], "v": np.zeros(0)}),
                                   "k", 3)
        assert len(parts) == 3
        assert all(p.num_rows == 0 for p in parts)
        assert all("v" in p.columns for p in parts)    # schema survives

    def test_keyed_shuffle_standalone_annotates(self):
        t = Table({"k": ["a", "b", "c"], "v": np.arange(3.0)})
        out = KeyedShuffle(key_col="k", num_partitions=3).transform(t)
        assert list(out["partition"]) == [partition_of(k, 3)
                                          for k in ("a", "b", "c")]
        assert list(out["k"]) == ["a", "b", "c"]


class TestSplitPipeline:
    def test_marker_splits_pre_and_chain(self):
        pre = StreamTableJoin(key_col="k", table_path="x.csv")
        sh = KeyedShuffle(key_col="k", num_partitions=2)
        agg = GroupedAggregator(group_col="k")
        p, s, c = split_pipeline_at_shuffle(pipeline_model(pre, sh, agg))
        assert p == [pre] and s is sh and c == [agg]

    def test_no_marker_is_all_chain(self):
        agg = GroupedAggregator(group_col="k")
        p, s, c = split_pipeline_at_shuffle(agg)
        assert p == [] and s is None and c == [agg]

    def test_two_shuffles_rejected(self):
        pm = pipeline_model(KeyedShuffle(key_col="k"),
                            KeyedShuffle(key_col="k"))
        with pytest.raises(ValueError, match="at most one"):
            split_pipeline_at_shuffle(pm)

    def test_plain_callable_rejected(self):
        with pytest.raises(TypeError, match="Transformer"):
            split_pipeline_at_shuffle(lambda t: t)

    def test_stateful_before_shuffle_rejected(self):
        pm = pipeline_model(GroupedAggregator(group_col="k"),
                            KeyedShuffle(key_col="k", num_partitions=2))
        with pytest.raises(ValueError, match="AFTER the KeyedShuffle"):
            ParallelStreamingQuery(MemorySource(), pm, MemorySink())

    def test_state_key_must_match_shuffle_key(self):
        pm = pipeline_model(KeyedShuffle(key_col="k", num_partitions=2),
                            GroupedAggregator(group_col="other"))
        with pytest.raises(ValueError, match="must match"):
            ParallelStreamingQuery(MemorySource(), pm, MemorySink())

    def test_key_col_required_without_marker(self):
        with pytest.raises(ValueError, match="key_col"):
            ParallelStreamingQuery(MemorySource(),
                                   GroupedAggregator(group_col="k"),
                                   MemorySink())


# --------------------------------------------------------------------------- #
# state backends


class TestStateBackends:
    def test_spill_equals_memory(self, tmp_path):
        mem = GroupedAggregator(group_col="k", value_col="v", agg="mean")
        spl = GroupedAggregator(group_col="k", value_col="v", agg="mean",
                                state_backend="spill",
                                spill_dir=str(tmp_path), spill_hot_keys=2)
        rng = np.random.default_rng(1)
        for _ in range(4):
            t = Table({"k": [f"k{int(i)}" for i in rng.integers(0, 12, 30)],
                       "v": rng.normal(size=30)})
            _assert_byte_identical(mem.transform(t), spl.transform(t))
        assert spl.spilled_bytes > 0          # 12 keys, 2 hot: cold file
        assert mem.spilled_bytes == 0

    def test_spill_state_doc_roundtrip(self, tmp_path):
        spl = GroupedAggregator(group_col="k", value_col="v", agg="sum",
                                state_backend="spill",
                                spill_dir=str(tmp_path / "a"),
                                spill_hot_keys=1)
        spl.transform(Table({"k": ["a", "b", "c"],
                             "v": np.array([1.0, 2.0, 3.0])}))
        doc = json.loads(json.dumps(spl.state_doc()))
        spl2 = GroupedAggregator(group_col="k", value_col="v", agg="sum",
                                 state_backend="spill",
                                 spill_dir=str(tmp_path / "b"),
                                 spill_hot_keys=1)
        spl2.load_state_doc(doc)
        nxt = Table({"k": ["a"], "v": np.array([10.0])})
        _assert_byte_identical(spl.transform(nxt), spl2.transform(nxt))

    def test_state_doc_is_arrival_order_invariant(self):
        """Sorted-key state docs: the same per-key history serializes to
        the same BYTES regardless of which order keys first appeared —
        the property the incremental-checkpoint diff depends on."""
        a = GroupedAggregator(group_col="k", value_col="v", agg="sum")
        b = GroupedAggregator(group_col="k", value_col="v", agg="sum")
        a.transform(Table({"k": ["x", "y"], "v": np.array([1.0, 2.0])}))
        b.transform(Table({"k": ["y", "x"], "v": np.array([2.0, 1.0])}))
        assert json.dumps(a.state_doc()) == json.dumps(b.state_doc())

    def test_spilling_backend_faults_cold_keys_back(self, tmp_path):
        b = SpillingStateBackend(str(tmp_path), hot_keys=1)
        b.acc("a")[0] += 1
        b.acc("b")[0] += 1
        b.end_batch()                          # evicts "a" to parquet
        assert b.spilled_bytes > 0 and len(b) == 2
        acc = b.acc("a")                       # fault back
        assert b.faults == 1 and acc[0] == 1


# --------------------------------------------------------------------------- #
# per-partition checkpoint files


class TestPartitionCheckpoints:
    def test_write_read_newest_at_or_before(self, tmp_path):
        log = CommitLog(str(tmp_path))
        log.write_partition_state(0, 0, {"n": 0})
        log.write_partition_state(0, 3, {"n": 3})
        log.write_partition_state(1, 1, {"n": 10})
        assert log.read_partition_state(0, 5) == {"n": 3}
        assert log.read_partition_state(0, 2) == {"n": 0}
        # incremental layout: partition 1 wrote nothing at bid 4, its
        # bid-1 snapshot IS its state as of bid 4
        assert log.read_partition_state(1, 4) == {"n": 10}
        assert log.read_partition_state(2, 5) is None
        log.close()

    def test_prune_keeps_each_partitions_newest(self, tmp_path):
        log = CommitLog(str(tmp_path))
        log.write_partition_state(0, 0, {"n": 0})
        log.write_partition_state(0, 4, {"n": 4})
        log.write_partition_state(1, 1, {"n": 10})    # old but current
        log.prune_state(keep_from=4)
        names = sorted(n for n in os.listdir(str(tmp_path))
                       if n.startswith("state-p"))
        assert names == ["state-p0000-000000004.json",
                         "state-p0001-000000001.json"]
        assert log.read_partition_state(1, 4) == {"n": 10}
        log.close()


# --------------------------------------------------------------------------- #
# streaming joins


class TestStreamStreamJoin:
    def test_pairs_within_window_across_batches(self):
        j = StreamStreamJoin(join_window_s=5.0)
        out1 = j.transform(Table({
            "key": ["a", "a"], "time": np.array([1.0, 3.0]),
            "side": ["left", "right"], "value": np.array([10.0, 20.0])}))
        # same batch: left buffered first, right row probes it
        assert list(out1["key"]) == ["a"]
        assert list(out1["left_value"]) == [10.0]
        assert list(out1["right_value"]) == [20.0]
        out2 = j.transform(Table({
            "key": ["a"], "time": np.array([7.0]),
            "side": ["left"], "value": np.array([30.0])}))
        # crosses batches: the buffered right row at t=3 matches |7-3|<=5
        assert list(out2["left_time"]) == [7.0]
        assert list(out2["right_time"]) == [3.0]
        out3 = j.transform(Table({
            "key": ["a"], "time": np.array([20.0]),
            "side": ["right"], "value": np.array([40.0])}))
        assert out3.num_rows == 0             # outside every window

    def test_no_match_across_keys(self):
        j = StreamStreamJoin(join_window_s=10.0)
        out = j.transform(Table({
            "key": ["a", "b"], "time": np.array([1.0, 1.0]),
            "side": ["left", "right"], "value": np.array([1.0, 2.0])}))
        assert out.num_rows == 0

    def test_watermark_drops_late_and_evicts_buffers(self):
        j = StreamStreamJoin(join_window_s=2.0, watermark_delay_s=1.0)
        j.transform(Table({
            "key": ["a"], "time": np.array([100.0]),
            "side": ["left"], "value": np.array([1.0])}))
        assert j.watermark() == 99.0
        out = j.transform(Table({
            "key": ["a"], "time": np.array([50.0]),
            "side": ["right"], "value": np.array([2.0])}))
        assert out.num_rows == 0 and j.late_rows_dropped == 1
        j.transform(Table({
            "key": ["b"], "time": np.array([200.0]),
            "side": ["left"], "value": np.array([3.0])}))
        assert j.buffered_rows == 2           # "a"@100 still within horizon
        # eviction uses the watermark as of batch START: the next batch
        # sees watermark 199, horizon 197, and drops the stale "a"@100
        j.transform(Table({"key": [], "time": np.zeros(0),
                           "side": [], "value": np.zeros(0)}))
        assert j.buffered_rows == 1           # only "b"@200 survives

    def test_state_doc_roundtrip_continues_identically(self):
        a = StreamStreamJoin(join_window_s=5.0)
        a.transform(Table({
            "key": ["a", "b"], "time": np.array([1.0, 2.0]),
            "side": ["left", "left"], "value": np.array([1.0, 2.0])}))
        b = StreamStreamJoin(join_window_s=5.0)
        b.load_state_doc(json.loads(json.dumps(a.state_doc())))
        nxt = Table({"key": ["a"], "time": np.array([4.0]),
                     "side": ["right"], "value": np.array([9.0])})
        _assert_byte_identical(a.transform(nxt), b.transform(nxt))


class TestStreamTableJoin:
    def _static(self, tmp_path) -> str:
        path = str(tmp_path / "dim.csv")
        write_csv(Table({"key": ["a", "b"],
                         "weight": np.array([1.5, 2.5])}), path)
        return path

    def test_left_fills_unmatched(self, tmp_path):
        j = StreamTableJoin(table_path=self._static(tmp_path))
        out = j.transform(Table({"key": ["a", "zz", "b"],
                                 "v": np.arange(3.0)}))
        assert list(out["key"]) == ["a", "zz", "b"]
        w = np.asarray(out["weight"])
        assert w[0] == 1.5 and np.isnan(w[1]) and w[2] == 2.5

    def test_inner_drops_unmatched(self, tmp_path):
        j = StreamTableJoin(table_path=self._static(tmp_path), how="inner")
        out = j.transform(Table({"key": ["zz", "a"], "v": np.arange(2.0)}))
        assert list(out["key"]) == ["a"]
        assert list(out["v"]) == [1.0]

    def test_duplicate_static_key_rejected(self, tmp_path):
        path = str(tmp_path / "dup.csv")
        write_csv(Table({"key": ["a", "a"], "w": np.zeros(2)}), path)
        j = StreamTableJoin(table_path=path)
        with pytest.raises(ValueError, match="duplicate"):
            j.transform(Table({"key": ["a"]}))

    def test_colliding_column_prefixed(self, tmp_path):
        path = str(tmp_path / "dim.csv")
        write_csv(Table({"key": ["a"], "v": np.array([9.0])}), path)
        out = StreamTableJoin(table_path=path).transform(
            Table({"key": ["a"], "v": np.array([1.0])}))
        assert list(out["v"]) == [1.0]
        assert list(out["right_v"]) == [9.0]


# --------------------------------------------------------------------------- #
# the parallel query, thread mode: byte identity with P=1


class TestParallelThreadMode:
    def _parallel(self, P: int, stage, src, sink, **kw):
        pm = pipeline_model(
            KeyedShuffle(key_col=stage.partition_key_col(),
                         num_partitions=P), stage)
        return ParallelStreamingQuery(src, pm, sink, workers="thread", **kw)

    def test_grouped_matches_p1_at_p2_and_p4(self):
        batches = _grouped_batches()
        oracle_src, oracle_sink = MemorySource(), MemorySink()
        oracle = StreamingQuery(
            oracle_src, GroupedAggregator(group_col="k", value_col="v",
                                          agg="sum"), oracle_sink)
        _drive(oracle, oracle_src, batches)
        oracle.stop()
        for P in (2, 4):
            src, sink = MemorySource(), MemorySink()
            q = self._parallel(P, GroupedAggregator(group_col="k",
                                                    value_col="v",
                                                    agg="sum"), src, sink)
            _drive(q, src, batches)
            q.stop()
            _assert_byte_identical(sink.table(), oracle_sink.table())
            assert q.last_progress["num_partitions"] == P
            assert q.last_progress["workers"] == "thread"

    def test_join_matches_p1_at_p4_with_late_rows(self):
        rng = np.random.default_rng(7)
        batches = []
        t = 0.0
        for _ in range(6):
            n = 24
            times = t + rng.uniform(0, 8, n)
            times[0] = max(0.0, t - 30.0)      # a late straggler
            batches.append(Table({
                "key": [f"k{int(i)}" for i in rng.integers(0, 6, n)],
                "time": times,
                "side": [["left", "right"][int(s)]
                         for s in rng.integers(0, 2, n)],
                "value": rng.normal(size=n)}))
            t += 8.0
        mk = lambda: StreamStreamJoin(join_window_s=4.0,  # noqa: E731
                                      watermark_delay_s=5.0)
        oracle_src, oracle_sink = MemorySource(), MemorySink()
        oracle = StreamingQuery(oracle_src, mk(), oracle_sink)
        _drive(oracle, oracle_src, batches)
        oracle.stop()
        src, sink = MemorySource(), MemorySink()
        q = self._parallel(4, mk(), src, sink)
        _drive(q, src, batches)
        q.stop()
        assert oracle_sink.table().num_rows > 0
        _assert_byte_identical(sink.table(), oracle_sink.table())

    def test_windowed_emission_needs_global_time_hints(self):
        """One partition's slice carries the max event time; the OTHER
        partition's window must still finalize. Byte identity with P=1
        proves the driver's global hint reached every partition."""
        ka = _key_for_partition(0, 2)
        kb = _key_for_partition(1, 2)
        batches = [
            Table({"g": [ka, kb], "t": np.array([5.0, 6.0]),
                   "v": np.array([1.0, 2.0])}),
            # only kb advances event time past the [0, 10) window end
            Table({"g": [kb], "t": np.array([25.0]),
                   "v": np.array([3.0])}),
        ]
        mk = lambda: WindowedAggregator(  # noqa: E731
            time_col="t", window_s=10.0, group_col="g", value_col="v",
            agg="sum", watermark_delay_s=0.0)
        oracle_src, oracle_sink = MemorySource(), MemorySink()
        oracle = StreamingQuery(oracle_src, mk(), oracle_sink)
        _drive(oracle, oracle_src, batches)
        oracle.stop()
        # the P=1 run emitted ka's bucket — if the hint machinery were
        # broken, ka's partition (which saw no row of batch 2) would not
        out = oracle_sink.table()
        assert ka in list(out["g"]) and kb in list(out["g"])
        src, sink = MemorySource(), MemorySink()
        q = ParallelStreamingQuery(
            src, pipeline_model(KeyedShuffle(key_col="g",
                                             num_partitions=2), mk()),
            sink, workers="thread")
        _drive(q, src, batches)
        q.stop()
        _assert_byte_identical(sink.table(), oracle_sink.table())

    def test_stateless_chain_restores_source_order(self, tmp_path):
        path = str(tmp_path / "dim.csv")
        write_csv(Table({"key": ["a", "b", "c"],
                         "weight": np.array([1.0, 2.0, 3.0])}), path)
        rng = np.random.default_rng(5)
        batches = [Table({"key": [f"{c}" for c in
                          rng.choice(list("abcdz"), 20)],
                          "v": rng.normal(size=20)}) for _ in range(3)]
        oracle_src, oracle_sink = MemorySource(), MemorySink()
        oracle = StreamingQuery(oracle_src,
                                StreamTableJoin(table_path=path),
                                oracle_sink)
        _drive(oracle, oracle_src, batches)
        oracle.stop()
        src, sink = MemorySource(), MemorySink()
        q = ParallelStreamingQuery(
            src, pipeline_model(KeyedShuffle(key_col="key",
                                             num_partitions=3),
                                StreamTableJoin(table_path=path)),
            sink, workers="thread")
        _drive(q, src, batches)
        q.stop()
        # row ORDER matters here: the hidden row tag must put the merged
        # output back in source order, and the tag must not leak
        _assert_byte_identical(sink.table(), oracle_sink.table())

    def test_incremental_checkpoints_and_prune(self, tmp_path):
        ka = _key_for_partition(0, 2)
        kb = _key_for_partition(1, 2)
        src, sink = MemorySource(), MemorySink()
        q = ParallelStreamingQuery(
            src, pipeline_model(
                KeyedShuffle(key_col="k", num_partitions=2),
                GroupedAggregator(group_col="k", agg="count")),
            sink, workers="thread", checkpoint_dir=str(tmp_path))
        src.add_rows(Table({"k": [ka, kb]}))
        q.process_all_available()
        assert q.last_progress["partition_states_written"] == 2
        src.add_rows(Table({"k": [ka]}))      # partition 1 untouched
        q.process_all_available()
        assert q.last_progress["partition_states_written"] == 1
        q.stop()
        names = sorted(n for n in os.listdir(str(tmp_path))
                       if n.startswith("state-p"))
        # prune kept partition 0's bid-1 snapshot and partition 1's
        # bid-0 one (its newest — incremental writes leave it old)
        assert names == ["state-p0000-000000001.json",
                         "state-p0001-000000000.json"]

    def test_restart_recovery_matches_p1_restart(self, tmp_path):
        d = str(tmp_path / "in")
        os.makedirs(d)
        rng = np.random.default_rng(9)

        def add_file(i):
            write_csv(Table({"k": [f"k{int(x)}" for x in
                                   rng.integers(0, 8, 10)],
                             "v": rng.normal(size=10)}),
                      os.path.join(d, f"f-{i:03d}.csv"))

        def run(ck, sink, parallel):
            agg = GroupedAggregator(group_col="k", value_col="v",
                                    agg="mean")
            src = DirectorySource(d, "*.csv", max_files_per_trigger=1)
            if parallel:
                q = ParallelStreamingQuery(
                    src, pipeline_model(
                        KeyedShuffle(key_col="k", num_partitions=2), agg),
                    sink, workers="thread", checkpoint_dir=ck)
            else:
                q = StreamingQuery(src, agg, sink, checkpoint_dir=ck)
            q.process_all_available()
            q.stop()

        for i in range(2):
            add_file(i)
        ck1, ck2 = str(tmp_path / "ck1"), str(tmp_path / "ck2")
        s1a, s2a = MemorySink(), MemorySink()
        run(ck1, s1a, parallel=False)
        run(ck2, s2a, parallel=True)
        for i in range(2, 4):
            add_file(i)
        # restart both from their checkpoints: fresh operator instances,
        # state recovered from (per-partition) snapshots
        s1b, s2b = MemorySink(), MemorySink()
        run(ck1, s1b, parallel=False)
        run(ck2, s2b, parallel=True)
        _assert_byte_identical(s2a.table(), s1a.table())
        _assert_byte_identical(s2b.table(), s1b.table())

    def test_sink_failure_rolls_back_every_partition(self, tmp_path):
        class FlakySink(MemorySink):
            def __init__(self):
                super().__init__()
                self.failures_left = 1

            def add_batch(self, batch_id, table):
                if self.failures_left > 0:
                    self.failures_left -= 1
                    raise OSError("sink hiccup")
                super().add_batch(batch_id, table)

        src, sink = MemorySource(), FlakySink()
        q = ParallelStreamingQuery(
            src, pipeline_model(
                KeyedShuffle(key_col="k", num_partitions=2),
                GroupedAggregator(group_col="k", agg="count")),
            sink, workers="thread", checkpoint_dir=str(tmp_path))
        src.add_rows(Table({"k": ["a", "a", "b"]}))
        with pytest.raises(OSError):
            q.process_next()
        assert q.process_next()               # retry of the same plan
        q.stop()
        out = sink.table()
        got = dict(zip(out["k"], out["aggregate"]))
        assert got == {"a": 2.0, "b": 1.0}    # no double-fold anywhere


# --------------------------------------------------------------------------- #
# the fleet worker protocol (in-process, no processes)


def _call(handler, body: dict):
    from mmlspark_tpu.io_http.schema import HTTPRequestData

    out = handler(Table({"request": [HTTPRequestData.from_json("/", body)]}))
    resp = out["reply"][0]
    return resp.status_code, json.loads(resp.entity)


class TestPartitionWorkerProtocol:
    def _handler(self):
        from mmlspark_tpu.core.serialize import stage_to_blob

        blob = stage_to_blob(pipeline_model(
            GroupedAggregator(group_col="k", agg="count")))
        return PartitionWorkerFactory(blob, "q")()

    def _apply(self, p, bid, keys):
        from mmlspark_tpu.streaming.partition import _encode_rows

        return {"op": "apply", "partition": p, "batch_id": bid,
                "rows": _encode_rows(Table({"k": keys})), "hints": {}}

    def test_apply_fold_and_idempotent_replay(self):
        h = self._handler()
        code, doc = _call(h, self._apply(0, 0, ["a", "a", "b"]))
        assert code == 200
        assert doc["rows"]["columns"]["aggregate"]["values"] == [2.0, 1.0]
        # a re-sent apply for the SAME batch returns the cached reply —
        # no second fold (counts would read 4/2 if it folded again)
        code2, doc2 = _call(h, self._apply(0, 0, ["a", "a", "b"]))
        assert (code2, doc2) == (200, doc)

    def test_fresh_partition_past_bid0_needs_state(self):
        h = self._handler()
        code, doc = _call(h, self._apply(1, 5, ["a"]))
        assert code == 200 and doc.get("need_state")
        code, doc = _call(h, {"op": "load_state", "partition": 1,
                              "batch_id": 4,
                              "state": {"ops": [{"groups":
                                                 {"a": [3, 3.0, 1.0,
                                                        1.0]}}]}})
        assert doc == {"ok": True}
        code, doc = _call(h, self._apply(1, 5, ["a"]))
        assert code == 200
        assert doc["rows"]["columns"]["aggregate"]["values"] == [4.0]

    def test_gap_in_batch_ids_needs_state(self):
        h = self._handler()
        _call(h, self._apply(0, 0, ["a"]))
        code, doc = _call(h, self._apply(0, 2, ["a"]))   # skipped bid 1
        assert code == 200 and doc.get("need_state") and doc["have"] == 0

    def test_status_and_unknown_op(self):
        h = self._handler()
        _call(h, self._apply(0, 0, ["a"]))
        code, doc = _call(h, {"op": "status"})
        assert code == 200
        assert doc["partitions"] == [0] and doc["last"] == {"0": 0}
        code, doc = _call(h, {"op": "bogus"})
        assert code == 500 and "error" in doc


class TestBinaryWireProtocol:
    """The shared binary row codec (io_http/wire.py) in the fleet apply
    op: same fold, same reply fields, and the output table is
    byte-identical to the JSON columnar encoding's."""

    def _handler(self):
        from mmlspark_tpu.core.serialize import stage_to_blob

        blob = stage_to_blob(pipeline_model(
            GroupedAggregator(group_col="k", value_col="v", agg="sum")))
        return PartitionWorkerFactory(blob, "q")()

    @staticmethod
    def _table(seed=7):
        rng = np.random.default_rng(seed)
        keys = np.array(list("abcd"))[rng.integers(0, 4, 32)]
        return Table({"k": keys.tolist(),
                      "v": rng.normal(size=32)})   # float64, full precision

    def _binary_apply(self, handler, table, p=0, bid=0):
        from mmlspark_tpu.io_http import wire
        from mmlspark_tpu.io_http.schema import HTTPRequestData

        ent = wire.encode_message(
            {"op": "apply", "partition": p, "batch_id": bid, "hints": {}},
            {c: table[c] for c in table.columns}, n_rows=table.num_rows)
        req = HTTPRequestData(
            "POST", "/", {"Content-Type": wire.WIRE_CONTENT_TYPE}, ent)
        out = handler(Table({"request": [req]}))
        return out["reply"][0]

    def test_binary_apply_byte_identical_to_json(self):
        from mmlspark_tpu.io_http import wire
        from mmlspark_tpu.streaming.partition import (_decode_rows,
                                                      _encode_rows)

        table = self._table()
        # JSON columnar path on one fresh worker
        hj = self._handler()
        code, doc = _call(hj, {"op": "apply", "partition": 0, "batch_id": 0,
                               "rows": _encode_rows(table), "hints": {}})
        assert code == 200
        json_out = _decode_rows(doc["rows"])
        # binary wire path on another fresh worker
        resp = self._binary_apply(self._handler(), table)
        assert resp.status_code == 200
        assert wire.is_wire_content_type(
            wire.content_type_of(resp.headers))
        meta, cols = wire.decode_message(resp.entity)
        assert meta["state"] == doc["state"]
        assert meta["watermark"] == doc["watermark"]
        assert sorted(cols) == sorted(json_out.columns)
        for c in json_out.columns:
            a, b = np.asarray(json_out[c]), np.asarray(cols[c])
            assert a.dtype == b.dtype and a.shape == b.shape, c
            assert a.tobytes() == b.tobytes(), c

    def test_binary_replay_idempotent_and_need_state_stays_json(self):
        from mmlspark_tpu.io_http import wire

        h = self._handler()
        table = self._table()
        r1 = self._binary_apply(h, table, bid=0)
        r2 = self._binary_apply(h, table, bid=0)    # replay: cached fold
        assert r1.entity == r2.entity
        # a gap answers need_state as plain JSON (control replies are
        # never framed), and the error path stays JSON too
        r3 = self._binary_apply(h, table, bid=5)
        assert not wire.is_wire_content_type(
            wire.content_type_of(r3.headers))
        assert json.loads(r3.entity).get("need_state")


# --------------------------------------------------------------------------- #
# PartitionSupervisor (stub fleet — real-fleet coverage is in the slow tier)


class _StubFleet:
    def __init__(self):
        self.dead: list[int] = []
        self.respawned: list[int] = []

    def dead_slots(self):
        return list(self.dead)

    def respawn(self, slot):
        self.dead.remove(slot)
        self.respawned.append(slot)
        return f"http://respawned-{slot}/"


class TestPartitionSupervisor:
    def test_respawns_dead_slots(self):
        from mmlspark_tpu.resilience import PartitionSupervisor

        fleet = _StubFleet()
        sup = PartitionSupervisor(fleet, poll_interval_s=0.01).start()
        try:
            fleet.dead.append(1)
            deadline = time.monotonic() + 5
            while not fleet.respawned and time.monotonic() < deadline:
                time.sleep(0.01)
            assert fleet.respawned == [1] and fleet.dead == []
            assert sup.respawns == 1 and sup.state == "running"
        finally:
            sup.stop()
        assert sup.state == "stopped"

    def test_escalates_when_budget_runs_dry(self):
        from mmlspark_tpu.resilience import (PartitionSupervisor,
                                             RestartPolicy)

        fleet = _StubFleet()
        failures = []
        sup = PartitionSupervisor(
            fleet, RestartPolicy(max_restarts=1, window_s=300.0),
            poll_interval_s=0.01,
            on_failure=lambda f, slot: failures.append(slot)).start()
        try:
            fleet.dead.append(0)
            deadline = time.monotonic() + 5
            while not fleet.respawned and time.monotonic() < deadline:
                time.sleep(0.01)
            fleet.dead.append(0)              # second death inside window
            deadline = time.monotonic() + 5
            while sup.state != "failed" and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sup.state == "failed"
            assert failures == [0]
            assert fleet.respawned == [0]     # budget spent on the first
        finally:
            sup.stop()


# --------------------------------------------------------------------------- #
# slow tier: fleet worker processes + kill/restart byte identity


def _oracle_grouped(batches):
    src, sink = MemorySource(), MemorySink()
    q = StreamingQuery(src, GroupedAggregator(group_col="k", value_col="v",
                                              agg="sum"), sink)
    _drive(q, src, batches)
    q.stop()
    return sink.table()


@pytest.mark.slow
class TestFleetMode:
    def test_fleet_matches_p1_and_survives_worker_kill(self, tmp_path):
        """P=2 fleet workers; one is killed while a batch streams. The
        driver heals (respawn -> need_state -> state re-push -> re-send)
        and the final output is byte-identical to the P=1 run."""
        batches = _grouped_batches(seed=21, n_batches=6, rows=400, keys=24)
        expected = _oracle_grouped(batches)
        src, sink = MemorySource(), MemorySink()
        q = ParallelStreamingQuery(
            src, pipeline_model(
                KeyedShuffle(key_col="k", num_partitions=2),
                GroupedAggregator(group_col="k", value_col="v",
                                  agg="sum")),
            sink, workers="fleet", checkpoint_dir=str(tmp_path / "ck"))
        try:
            _drive(q, src, batches[:2])       # workers spawned + warm
            assert q._fleet is not None and q._fleet.n_live == 2
            # kill BOTH workers while batch 2 is in flight (consistent-
            # hash routing might dodge a single corpse): every apply must
            # fail mid-batch, heal, answer need_state, and re-fold from
            # the committed state

            def _kill_all():
                for slot in range(2):
                    try:
                        q._fleet.kill(slot)
                    except Exception:  # noqa: BLE001 — already dead
                        pass

            src.add_rows(batches[2])
            killer = threading.Timer(0.05, _kill_all)
            killer.start()
            q.process_all_available()
            killer.join()
            _drive(q, src, batches[3:])
            assert q._fleet.dead_slots() == []     # healed
        finally:
            q.stop()
        _assert_byte_identical(sink.table(), expected)

    def test_binary_wire_fleet_run_byte_identical(self, tmp_path):
        """binary_wire=True ships slices/replies over the framed wire;
        the sunk output is still byte-identical to the P=1 JSON run."""
        batches = _grouped_batches(seed=11, n_batches=4, rows=200, keys=16)
        expected = _oracle_grouped(batches)
        src, sink = MemorySource(), MemorySink()
        q = ParallelStreamingQuery(
            src, pipeline_model(
                KeyedShuffle(key_col="k", num_partitions=2),
                GroupedAggregator(group_col="k", value_col="v",
                                  agg="sum")),
            sink, workers="fleet", binary_wire=True,
            checkpoint_dir=str(tmp_path / "ck"))
        try:
            _drive(q, src, batches)
        finally:
            q.stop()
        _assert_byte_identical(sink.table(), expected)

    def test_chaos_soak_repeated_kills_under_supervision(self, tmp_path):
        """P=4 partitions hashed onto 2 worker processes (multi-partition
        workers), a PartitionSupervisor patrolling between batches, and a
        worker killed every few batches — output stays byte-identical."""
        from mmlspark_tpu.resilience import (PartitionSupervisor,
                                             RestartPolicy)

        batches = _grouped_batches(seed=33, n_batches=9, rows=60, keys=32)
        expected = _oracle_grouped(batches)
        src, sink = MemorySource(), MemorySink()
        q = ParallelStreamingQuery(
            src, pipeline_model(
                KeyedShuffle(key_col="k", num_partitions=4),
                GroupedAggregator(group_col="k", value_col="v",
                                  agg="sum")),
            sink, workers="fleet", num_workers=2,
            checkpoint_dir=str(tmp_path / "ck"))
        sup = None
        kills = 0
        try:
            for i, b in enumerate(batches):
                src.add_rows(b)
                q.process_all_available()
                if sup is None:               # fleet exists after batch 0
                    sup = PartitionSupervisor(
                        q._fleet, RestartPolicy(max_restarts=100,
                                                window_s=300.0),
                        poll_interval_s=0.05).start()
                if i in (2, 5, 7):
                    q._fleet.kill(i % 2)
                    kills += 1
            deadline = time.monotonic() + 30
            while q._fleet.dead_slots() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert q._fleet.dead_slots() == []
        finally:
            if sup is not None:
                sup.stop()
            q.stop()
        assert kills == 3
        _assert_byte_identical(sink.table(), expected)
        # every kill was healed by SOMEONE — the supervisor between
        # batches or the driver's lazy heal inside an apply retry
        assert sup is not None and sup.state in ("running", "stopped")


_DRIVER = """\
import sys, time
import numpy as np
from mmlspark_tpu.core.pipeline import Transformer, pipeline_model
from mmlspark_tpu.streaming import (DirectorySource, GroupedAggregator,
    KeyedShuffle, ParallelStreamingQuery, ParquetSink)

d, out, ck, slow = sys.argv[1:5]

class SlowDown(Transformer):          # driver-side: widens the kill window
    def _transform(self, t):
        time.sleep(float(slow))
        return t

pm = pipeline_model(
    SlowDown(),
    KeyedShuffle(key_col="k", num_partitions=4),
    GroupedAggregator(group_col="k", value_col="v", agg="sum"))
src = DirectorySource(d, "*.csv", max_files_per_trigger=1)
q = ParallelStreamingQuery(src, pm, ParquetSink(out), checkpoint_dir=ck,
                           workers="thread")
q.process_all_available()
q.stop()
print("DONE", q.batches_processed, flush=True)
"""


@pytest.mark.slow
class TestDriverKillAtP4:
    def test_sigkill_mid_stream_byte_identical_to_p1(self, tmp_path):
        """SIGKILL the P=4 driver mid-batch, restart from the checkpoint:
        the parquet output equals the P=1 no-kill run byte for byte —
        per-partition recovery replays the in-flight batch exactly."""
        pytest.importorskip("pyarrow")
        from tests.conftest import subprocess_env

        d = str(tmp_path / "in")
        os.makedirs(d)
        rng = np.random.default_rng(41)
        for i in range(6):
            write_csv(Table({"k": [f"k{int(x)}" for x in
                                   rng.integers(0, 10, 20)],
                             "v": rng.normal(size=20)}),
                      os.path.join(d, f"f-{i:03d}.csv"))
        driver = os.path.join(str(tmp_path), "driver.py")
        with open(driver, "w") as fh:
            fh.write(_DRIVER)
        out = str(tmp_path / "out")
        ck = str(tmp_path / "ck")
        env = subprocess_env()
        env["JAX_PLATFORMS"] = "cpu"
        p1 = subprocess.Popen([sys.executable, driver, d, out, ck, "0.3"],
                              env=env, stdout=subprocess.PIPE)
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                parts = [n for n in os.listdir(out)
                         if n.startswith("part-")] \
                    if os.path.isdir(out) else []
                if len(parts) >= 2:
                    break
                if p1.poll() is not None:
                    break
                time.sleep(0.02)
            assert p1.poll() is None, "driver finished before the kill"
            p1.send_signal(signal.SIGKILL)
        finally:
            p1.wait(timeout=30)
        p2 = subprocess.run([sys.executable, driver, d, out, ck, "0"],
                            env=env, capture_output=True, text=True,
                            timeout=300)
        assert p2.returncode == 0, p2.stderr[-2000:]
        assert "DONE" in p2.stdout
        # P=1 oracle over the same files, no kill
        from mmlspark_tpu.streaming import ParquetSink

        oracle_out = str(tmp_path / "oracle")
        oracle_sink = ParquetSink(oracle_out)
        q = StreamingQuery(
            DirectorySource(d, "*.csv", max_files_per_trigger=1),
            GroupedAggregator(group_col="k", value_col="v", agg="sum"),
            oracle_sink, checkpoint_dir=str(tmp_path / "ock"))
        assert q.process_all_available() == 6
        q.stop()
        _assert_byte_identical(ParquetSink(out).table(),
                               oracle_sink.table())
