"""Text featurization tests (reference: TextFeaturizerSpec,
PageSplitterSpec, MultiNGramSpec)."""

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import PipelineStage
from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.text import (
    CountVectorizer,
    HashingTF,
    IDF,
    MultiNGram,
    NGram,
    PageSplitter,
    StopWordsRemover,
    TextFeaturizer,
    Tokenizer,
)


def docs():
    return Table({"text": [
        "The quick brown fox jumps over the lazy dog",
        "the quick brown cat sleeps",
        "dogs and cats are animals",
    ], "label": np.asarray([0.0, 1.0, 1.0])})


class TestBuildingBlocks:
    def test_tokenizer(self):
        out = Tokenizer().transform(docs())
        assert out["tokens"][0][:3] == ["the", "quick", "brown"]

    def test_stopwords(self):
        t = Tokenizer().transform(docs())
        out = StopWordsRemover(input_col="tokens").transform(t)
        assert "the" not in out["filtered"][0]
        assert "quick" in out["filtered"][0]

    def test_ngram(self):
        t = Tokenizer().transform(docs())
        out = NGram(input_col="tokens", n=2).transform(t)
        assert out["ngrams"][1][0] == "the quick"

    def test_hashing_tf_counts(self):
        t = Table({"tokens": [["a", "b", "a"], ["c"]]})
        out = HashingTF(num_features=32).transform(t)
        tf = np.asarray(out["tf"])
        assert tf.shape == (2, 32)
        assert tf[0].sum() == 3.0 and tf[0].max() == 2.0

    def test_count_vectorizer_vocab(self):
        t = Table({"tokens": [["a", "b"], ["a", "c"], ["a"]]})
        model = CountVectorizer(min_df=2).fit(t)
        assert model.vocabulary == ["a"]
        out = model.transform(t)
        assert np.asarray(out["tf"]).shape == (3, 1)

    def test_idf_downweights_common(self):
        t = Table({"tf": np.asarray([[1.0, 1.0], [1.0, 0.0], [1.0, 0.0]])})
        model = IDF().fit(t)
        out = model.transform(t)
        v = np.asarray(out["tfidf"])
        assert v[0, 1] > v[0, 0]  # rarer term weighted higher


class TestTextFeaturizer:
    def test_end_to_end_features(self):
        model = TextFeaturizer(num_features=256).fit(docs())
        out = model.transform(docs())
        feats = np.asarray(out["features"])
        assert feats.shape == (3, 256)
        assert (feats > 0).any()
        assert "__tokens" not in out.columns

    def test_classification_downstream(self):
        from mmlspark_tpu.gbdt import GBDTClassifier

        big = Table({
            "text": [f"repeat{'ed' * (i % 2)} token{i % 2}" for i in range(100)],
            "label": np.asarray([float(i % 2) for i in range(100)]),
        })
        model = TextFeaturizer(num_features=64).fit(big)
        featurized = model.transform(big)
        clf = GBDTClassifier(num_iterations=5, num_leaves=4).fit(featurized)
        out = clf.transform(featurized)
        assert (out["prediction"] == big["label"]).mean() > 0.9

    def test_save_load(self, tmp_path):
        model = TextFeaturizer(num_features=128).fit(docs())
        p = str(tmp_path / "tf")
        model.save(p)
        loaded = PipelineStage.load(p)
        np.testing.assert_allclose(
            np.asarray(model.transform(docs())["features"]),
            np.asarray(loaded.transform(docs())["features"]),
        )


class TestPageSplitter:
    def test_split_lengths(self):
        text = " ".join(["word"] * 500)  # 2499 chars
        t = Table({"text": [text]})
        out = PageSplitter(max_page_length=300, min_page_length=100).transform(t)
        pages = out["pages"][0]
        assert all(len(p) <= 300 for p in pages)
        assert "".join(p.replace(" ", "") for p in pages) == text.replace(" ", "")

    def test_short_text_one_page(self):
        out = PageSplitter().transform(Table({"text": ["short"]}))
        assert out["pages"][0] == ["short"]

    def test_explode(self):
        text = " ".join(["w"] * 200)
        out = PageSplitter(max_page_length=100, min_page_length=10,
                           explode=True).transform(Table({"text": [text], "id": [1.0]}))
        assert len(out) > 1
        assert all(v == 1.0 for v in out["id"])


class TestMultiNGram:
    def test_combines_lengths(self):
        t = Table({"tokens": [["a", "b", "c"]]})
        out = MultiNGram(lengths=[1, 2]).transform(t)
        assert out["ngrams"][0] == ["a", "b", "c", "a b", "b c"]
