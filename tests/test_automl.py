"""AutoML layer tests: TrainClassifier/Regressor, TuneHyperparameters,
FindBestModel, LIME (reference: VerifyTrainClassifier,
VerifyTuneHyperparameters, VerifyFindBestModel, ImageLIMESuite)."""

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import PipelineStage
from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.automl import (
    BestModel,
    ComputeModelStatistics,
    DiscreteHyperParam,
    FindBestModel,
    GridSpace,
    ImageLIME,
    RandomSpace,
    RangeHyperParam,
    SuperpixelTransformer,
    TrainClassifier,
    TrainRegressor,
    TuneHyperparameters,
    superpixels,
)
from mmlspark_tpu.gbdt import GBDTClassifier, GBDTRegressor


def mixed_table(n=400, seed=0):
    rng = np.random.default_rng(seed)
    num = rng.normal(size=n)
    cat = rng.choice(["a", "b", "c"], size=n)
    num2 = rng.normal(size=n)
    logits = num + (cat == "a") * 1.5 - (cat == "c") * 1.0
    y = np.where(logits + rng.normal(scale=0.5, size=n) > 0, "yes", "no")
    return Table({
        "num": num, "cat": list(cat), "num2": num2, "label": list(y),
    })


class TestTrainClassifier:
    def test_string_labels_and_mixed_features(self):
        t = mixed_table()
        model = TrainClassifier(
            model=GBDTClassifier(num_iterations=10, num_leaves=7),
            label_col="label",
        ).fit(t)
        out = model.transform(t)
        acc = np.mean(np.asarray(out["prediction"]) == np.asarray(t["label"]))
        assert acc > 0.8
        assert set(np.unique(out["prediction"])) <= {"yes", "no"}

    def test_save_load(self, tmp_path):
        t = mixed_table(n=200)
        model = TrainClassifier(
            model=GBDTClassifier(num_iterations=5, num_leaves=7),
            label_col="label",
        ).fit(t)
        p = str(tmp_path / "tc")
        model.save(p)
        loaded = PipelineStage.load(p)
        np.testing.assert_array_equal(
            np.asarray(model.transform(t)["prediction"]),
            np.asarray(loaded.transform(t)["prediction"]),
        )


class TestTrainRegressor:
    def test_basic(self):
        rng = np.random.default_rng(1)
        x1 = rng.normal(size=300)
        x2 = rng.normal(size=300)
        y = 2 * x1 - x2 + 0.05 * rng.normal(size=300)
        t = Table({"x1": x1, "x2": x2, "label": y})
        model = TrainRegressor(
            model=GBDTRegressor(num_iterations=20, num_leaves=15),
            label_col="label",
        ).fit(t)
        out = model.transform(t)
        pred = np.asarray(out["prediction"], np.float64)
        rmse = np.sqrt(np.mean((pred - y) ** 2))
        assert rmse < 1.0


class TestTuneHyperparameters:
    def test_grid_search(self):
        t = mixed_table(n=300)
        from mmlspark_tpu.automl import TrainClassifier as TC

        space = GridSpace({
            "num_leaves": DiscreteHyperParam([7, 15]),
            "num_iterations": DiscreteHyperParam([5]),
        })
        tuned = TuneHyperparameters(
            models=GBDTClassifier(),
            param_space=space,
            label_col="label_idx",
            num_folds=2,
            parallelism=2,
            evaluation_metric="accuracy",
        )
        # GBDT needs numeric features/labels: featurize by hand
        vals = np.asarray([{"yes": 1.0, "no": 0.0}[v] for v in t["label"]])
        tt = Table({
            "features": np.stack([np.asarray(t["num"]), np.asarray(t["num2"])], 1),
            "label_idx": vals,
        })
        tuned = tuned.copy({"models": GBDTClassifier(label_col="label_idx")})
        model = tuned.fit(tt)
        assert model.best_params["num_leaves"] in (7, 15)
        assert 0.5 < model.best_metric <= 1.0
        out = model.transform(tt)
        assert "prediction" in out.columns

    def test_random_space_draws(self):
        space = RandomSpace(
            {"a": RangeHyperParam(0.0, 1.0), "b": DiscreteHyperParam([1, 2])},
            num_runs=5, seed=3,
        )
        maps = list(space.param_maps())
        assert len(maps) == 5
        assert all(0.0 <= m["a"] <= 1.0 and m["b"] in (1, 2) for m in maps)


class TestFindBestModel:
    def test_picks_better_model(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(400, 5))
        y = (x[:, 0] > 0).astype(np.float64)
        t = Table({"features": x, "label": y})
        good = GBDTClassifier(num_iterations=20, num_leaves=15).fit(t)
        bad = GBDTClassifier(num_iterations=1, num_leaves=2, learning_rate=0.001).fit(t)
        best = FindBestModel(models=[bad, good], evaluation_metric="accuracy").fit(t)
        assert best.best_model is good
        fpr, tpr, _ = best.get_roc_curve()
        assert fpr[0] == 0.0 and tpr[-1] == 1.0


class TestLime:
    def test_superpixels_cover_image(self):
        img = np.random.default_rng(0).random((32, 32, 3)).astype(np.float32)
        labels, k = superpixels(img, cell_size=8)
        assert labels.shape == (32, 32)
        assert labels.max() < k

    def test_superpixel_transformer(self):
        imgs = np.random.default_rng(0).random((2, 16, 16, 3)).astype(np.float32)
        out = SuperpixelTransformer(cell_size=8).transform(Table({"image": imgs}))
        assert np.asarray(out["superpixels"]).shape == (2, 16, 16)

    def test_lime_finds_informative_region(self):
        # model responds ONLY to the top-left 8x8 patch mean
        class PatchModel(PipelineStage):
            def transform(self, table):
                x = np.asarray(table["image"], np.float64)
                score = x[:, :8, :8, :].mean(axis=(1, 2, 3)) / 255.0
                return table.with_column("probability", score)

        img = np.full((16, 16, 3), 200.0, np.float32)
        lime = ImageLIME(
            model=PatchModel(), cell_size=8, num_samples=64,
            prediction_col="probability", seed=1,
        )
        out = lime.transform(Table({"image": img[None]}))
        w = np.asarray(out["weights"][0])
        labels = np.asarray(out["superpixels"])[0]
        top_left_cluster = labels[2, 2]
        assert np.argmax(w) == top_left_cluster


class TestSubmeshTrials:
    """BASELINE config #5: hyperparameter trials placed on disjoint ICI
    submeshes (vs the reference's whole-cluster thread pool,
    TuneHyperparameters.scala:79-92)."""

    def test_split_mesh_disjoint(self):
        from mmlspark_tpu.parallel import make_mesh, split_mesh
        from mmlspark_tpu.parallel.mesh import DATA_AXIS

        mesh = make_mesh(n_data=8)
        subs = split_mesh(mesh, 4)
        assert len(subs) == 4
        seen = set()
        for sub in subs:
            assert sub.shape[DATA_AXIS] == 2
            devs = {d.id for d in sub.devices.ravel()}
            assert not (devs & seen)          # disjoint partitions
            seen |= devs
        assert len(seen) == 8
        with pytest.raises(ValueError):
            split_mesh(mesh, 3)

    def test_use_mesh_thread_local(self):
        import threading

        from mmlspark_tpu.parallel import make_mesh, use_mesh
        from mmlspark_tpu.parallel.mesh import get_mesh, split_mesh

        mesh = make_mesh(n_data=8)
        sub0, sub1 = split_mesh(mesh, 2)
        results = {}

        def worker(name, sub):
            with use_mesh(sub):
                results[name] = get_mesh()

        t0 = threading.Thread(target=worker, args=("a", sub0))
        t1 = threading.Thread(target=worker, args=("b", sub1))
        t0.start(); t1.start(); t0.join(); t1.join()
        assert results["a"] is sub0 and results["b"] is sub1
        assert get_mesh() is not sub0  # override never leaks out of its thread

    def test_trials_bind_disjoint_submeshes(self):
        """Each concurrent trial fits under a different 2-device submesh."""
        from mmlspark_tpu.core.pipeline import Estimator, Model
        from mmlspark_tpu.core.params import Param
        from mmlspark_tpu.parallel import make_mesh
        from mmlspark_tpu.parallel.mesh import get_mesh, set_default_mesh

        seen_meshes = []

        class ProbeModel(Model):
            def _transform(self, table):
                return table.with_column(
                    "prediction", np.asarray(table["label"], np.float64)
                )

        class MeshProbe(Estimator):
            seed = Param(0, "dummy", ptype=int)

            def _fit(self, table):
                seen_meshes.append(get_mesh())
                return ProbeModel()

        t = Table({"x": np.arange(64.0), "label": (np.arange(64.0) % 2)})
        set_default_mesh(make_mesh(n_data=8))
        try:
            TuneHyperparameters(
                models=MeshProbe(),
                param_space=GridSpace({"seed": DiscreteHyperParam([1, 2, 3, 4])}),
                num_folds=2, parallelism=4, evaluation_metric="accuracy",
                trial_submeshes=4, refit=False,
            ).fit(t)
        finally:
            set_default_mesh(None)
        # every TRIAL fit ran under a 2-device submesh; the final best-model
        # fit (appended last) correctly returns to the full 8-device mesh
        from mmlspark_tpu.parallel.mesh import DATA_AXIS

        assert len(seen_meshes) == 4 * 2 + 1   # trials x folds + final fit
        assert all(m.shape[DATA_AXIS] == 2 for m in seen_meshes[:-1])
        assert seen_meshes[-1].shape[DATA_AXIS] == 8

    def test_submesh_tuning_end_to_end(self):
        """A real GBDT grid on 4 disjoint submeshes produces a valid model."""
        from mmlspark_tpu.parallel import make_mesh
        from mmlspark_tpu.parallel.mesh import set_default_mesh

        rng = np.random.default_rng(5)
        x = rng.normal(size=(256, 5))
        y = (x[:, 0] > 0).astype(np.float64)
        t = Table({"features": x, "label": y})
        set_default_mesh(make_mesh(n_data=8))
        try:
            model = TuneHyperparameters(
                models=GBDTClassifier(use_mesh=True),
                param_space=GridSpace({"num_leaves": DiscreteHyperParam([3, 7]),
                                       "num_iterations": DiscreteHyperParam([4])}),
                num_folds=2, parallelism=2, evaluation_metric="accuracy",
                trial_submeshes=4,
            ).fit(t)
        finally:
            set_default_mesh(None)
        assert model.best_metric > 0.8
        out = model.transform(t)
        assert (np.asarray(out["prediction"], np.float64) == y).mean() > 0.9
