"""Telemetry timeline (ISSUE 19): the embedded metrics-history store,
its query engine, declarative alerting, regression watch, and every
surface the timeline wires into — flight-recorder keep-N, autoscaler
trend signals, SLO windowed burn, streaming per-partition history, and
the `diagnose.py --history` reconstruction.

Durability tests follow the checkpoint-store playbook: torn and
bit-flipped segments are quarantined (never raised), queries stay EXACT
across segment boundaries and process restarts, and a driver SIGKILL
mid-append leaves a directory `--history` reconstructs byte-stably.
All clock-driven tests run on FakeClock — zero real sleeps outside the
subprocess kill tests.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from mmlspark_tpu.observability.metrics import MetricsRegistry
from mmlspark_tpu.observability.recorder import (DUMP_PREFIX,
                                                 FlightRecorder)
from mmlspark_tpu.observability.timeline import (AlertEngine, AlertRule,
                                                 RegressionWatch,
                                                 TimelineRecorder,
                                                 TimelineStore)
from mmlspark_tpu.resilience.policy import FakeClock

_QUEUE = "mmlspark_tpu_serving_queue_depth"
_LATENCY = "mmlspark_tpu_serving_latency_seconds"
_SEEN = "mmlspark_tpu_serving_requests_seen_total"


# --------------------------------------------------------------------- #
# snapshot builders (registry-shaped dicts, no registry needed)         #
# --------------------------------------------------------------------- #


def _counter(v: float, labels=None) -> dict:
    return {"kind": "counter",
            "samples": [{"labels": dict(labels or {}), "value": v}]}


def _gauge(v: float, labels=None) -> dict:
    return {"kind": "gauge",
            "samples": [{"labels": dict(labels or {}), "value": v}]}


def _hist(count: float, total: float, buckets: dict, labels=None) -> dict:
    return {"kind": "histogram",
            "samples": [{"labels": dict(labels or {}), "count": count,
                         "sum": total, "buckets": dict(buckets)}]}


# --------------------------------------------------------------------- #
# store durability                                                      #
# --------------------------------------------------------------------- #


class TestStoreDurability:
    def test_append_rotate_prune_on_fake_clock(self, tmp_path):
        store = TimelineStore(str(tmp_path), keep=2, segment_samples=4)
        clk = FakeClock()
        for i in range(12):
            store.append(clk.monotonic(), {_SEEN: _counter(5.0 * i)})
            clk.advance(2.0)
        segs = store.segments()
        # 12 samples / 4 per segment = 3 sealed; keep=2 pruned the first
        assert [s["seq"] for s in segs] == [2, 3]
        assert all(s["intact"] and s["samples"] == 4 for s in segs)
        # the retained window is samples 4..11 (t = 8..22)
        ts = [t for t, _f in store.samples()]
        assert ts == [8.0, 10.0, 12.0, 14.0, 16.0, 18.0, 20.0, 22.0]

    def test_restart_continues_sequence_and_queries(self, tmp_path):
        clk = FakeClock()
        store = TimelineStore(str(tmp_path), segment_samples=4)
        for i in range(5):
            store.append(clk.monotonic(), {_SEEN: _counter(10.0 * i)})
            clk.advance(1.0)
        # a fresh process opens the same directory and keeps appending
        store2 = TimelineStore(str(tmp_path), segment_samples=4)
        for i in range(5, 9):
            store2.append(clk.monotonic(), {_SEEN: _counter(10.0 * i)})
            clk.advance(1.0)
        seqs = [s["seq"] for s in store2.segments()]
        assert seqs == sorted(set(seqs)), "restart reused a sequence"
        # counter increase over a window spanning the restart: samples at
        # t=2..7 hold 20..70 -> exact growth 50, rate 10/s
        assert store2.increase(_SEEN, 5.0, at=7.0) == pytest.approx(50.0)
        assert store2.rate(_SEEN, 5.0, at=7.0) == pytest.approx(10.0)

    def test_truncated_segment_quarantined(self, tmp_path):
        store = TimelineStore(str(tmp_path), segment_samples=3)
        for i in range(6):
            store.append(float(i), {_QUEUE: _gauge(float(i))})
        segs = store.segments()
        assert len(segs) == 2
        path = segs[0]["path"]
        raw = open(path, "rb").read()
        with open(path, "wb") as fh:          # torn write: tail lost
            fh.write(raw[:len(raw) - 7])
        ok, detail, doc = TimelineStore.verify_file(path)
        assert (ok, doc) == (False, None) and detail == "truncated"
        fresh = TimelineStore(str(tmp_path))
        inv = {s["seq"]: s["intact"] for s in fresh.segments()}
        assert inv == {1: False, 2: True}
        # reads fall back to the newest intact segment, never raise
        assert [t for t, _f in fresh.samples()] == [3.0, 4.0, 5.0]

    def test_bit_flip_fails_checksum_and_falls_back(self, tmp_path):
        store = TimelineStore(str(tmp_path), segment_samples=3)
        for i in range(6):
            store.append(float(i), {_SEEN: _counter(float(i))})
        path = store.segments()[1]["path"]
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0x40            # one flipped bit, mid-payload
        with open(path, "wb") as fh:
            fh.write(bytes(raw))
        ok, detail, _doc = TimelineStore.verify_file(path)
        assert not ok and detail == "checksum-mismatch"
        fresh = TimelineStore(str(tmp_path))
        assert [t for t, _f in fresh.samples()] == [0.0, 1.0, 2.0]
        assert fresh.last_value(_SEEN) == 2.0

    def test_verify_detail_taxonomy(self, tmp_path):
        p = str(tmp_path / "seg-00000001.bin")
        assert TimelineStore.verify_file(p)[1] == "missing"
        open(p, "wb").write(b"xy")
        assert TimelineStore.verify_file(p)[1] == "short-header"
        import hashlib
        import struct
        hdr = struct.Struct(">8s16sQ")
        payload = b"not json"
        digest = hashlib.blake2b(payload, digest_size=16).digest()
        open(p, "wb").write(hdr.pack(b"WRONGMAG", digest, len(payload)))
        assert TimelineStore.verify_file(p)[1] == "bad-magic"
        open(p, "wb").write(
            hdr.pack(b"MMLTLSEG", digest, len(payload)) + payload)
        assert TimelineStore.verify_file(p)[1] == "bad-payload"

    def test_queries_exact_across_segment_boundary(self, tmp_path):
        """The boundary is an encoding detail: windows spanning it give
        the same numbers a single flat log would."""
        store = TimelineStore(str(tmp_path), segment_samples=3)
        buckets = {"0.1": 0.0, "0.5": 0.0, "+Inf": 0.0}
        for i in range(8):                    # segments: [0,1,2][3,4,5][6,7]
            buckets = {"0.1": 100.0 * i, "0.5": 100.0 * i,
                       "+Inf": 100.0 * i}
            snap = {
                _SEEN: _counter(7.0 * i),
                _QUEUE: _gauge(2.0 * i),      # slope 1.0/s at 2s cadence
                _LATENCY: _hist(100.0 * i, 5.0 * i, buckets),
            }
            store.append(2.0 * i, snap)
        # window [6, 14] spans the 2nd boundary: counter 21 -> 49
        assert store.increase(_SEEN, 8.0, at=14.0) == pytest.approx(28.0)
        assert store.rate(_SEEN, 8.0, at=14.0) == pytest.approx(3.5)
        assert store.slope(_QUEUE, 8.0, at=14.0) == pytest.approx(1.0)
        assert store.avg_over(_QUEUE, 8.0, at=14.0) == pytest.approx(10.0)
        assert store.max_over(_QUEUE, 8.0, at=14.0) == pytest.approx(14.0)
        assert store.min_over(_QUEUE, 8.0, at=14.0) == pytest.approx(6.0)
        # histogram deltas across the boundary: all growth in the 0.1
        # bucket, so q=0.5 interpolates to half the first bound
        assert store.quantile_over(_LATENCY, 0.5, 8.0, at=14.0) == \
            pytest.approx(0.05)

    def test_label_matchers_select_series(self, tmp_path):
        store = TimelineStore(str(tmp_path))
        for i in range(4):
            snap = {_QUEUE: {"kind": "gauge", "samples": [
                {"labels": {"server": "a"}, "value": 10.0 * i},
                {"labels": {"server": "b"}, "value": 1.0 * i},
            ]}}
            store.append(float(i), snap)
        assert store.max_over(_QUEUE, 10.0, {"server": "b"}, at=3.0) == 3.0
        assert store.max_over(_QUEUE, 10.0, {"server": "a"}, at=3.0) == 30.0
        assert store.max_over(_QUEUE, 10.0, at=3.0) == 30.0  # all series
        both = store.series(_QUEUE)
        assert len(both) == 2

    def test_counter_reset_never_counts_negative(self, tmp_path):
        store = TimelineStore(str(tmp_path))
        for t, v in [(0.0, 100.0), (1.0, 120.0), (2.0, 5.0), (3.0, 25.0)]:
            store.append(t, {_SEEN: _counter(v)})   # replica restart at t=2
        # growth 20 before the reset + 20 after; the -115 drop is ignored
        assert store.increase(_SEEN, 3.0, at=3.0) == pytest.approx(40.0)

    def test_compaction_preserves_every_query(self, tmp_path):
        store = TimelineStore(str(tmp_path), segment_samples=3, keep=8)
        for i in range(9):
            store.append(2.0 * i, {_SEEN: _counter(4.0 * i),
                                   _QUEUE: _gauge(float(i % 5))})
        before = (store.increase(_SEEN, 10.0, at=16.0),
                  store.avg_over(_QUEUE, 10.0, at=16.0),
                  store.slope(_QUEUE, 6.0, at=16.0),
                  [t for t, _f in store.samples()])
        removed = store.compact()
        assert removed == 3
        files = [f for f in os.listdir(tmp_path) if f.startswith("seg-")]
        assert len(files) == 1
        after = (store.increase(_SEEN, 10.0, at=16.0),
                 store.avg_over(_QUEUE, 10.0, at=16.0),
                 store.slope(_QUEUE, 6.0, at=16.0),
                 [t for t, _f in store.samples()])
        assert before == after
        # a fresh open reads the merged segment the same way
        fresh = TimelineStore(str(tmp_path))
        assert [t for t, _f in fresh.samples()] == before[3]
        # appends after compaction start a new segment, queries still span
        store.append(18.0, {_SEEN: _counter(40.0), _QUEUE: _gauge(4.0)})
        # window [14, 18] spans merged segment + fresh one: 28 -> 32 -> 40
        assert store.increase(_SEEN, 4.0, at=18.0) == pytest.approx(12.0)

    def test_series_tombstone_on_disappearance(self, tmp_path):
        store = TimelineStore(str(tmp_path))
        store.append(0.0, {_QUEUE: _gauge(5.0), _SEEN: _counter(1.0)})
        store.append(1.0, {_SEEN: _counter(2.0)})   # gauge family gone
        flats = [f for _t, f in store.samples()]
        assert any(k.startswith(_QUEUE) for k in flats[0])
        assert not any(k.startswith(_QUEUE) for k in flats[1])

    def test_ctor_validation(self, tmp_path):
        with pytest.raises(ValueError):
            TimelineStore(str(tmp_path), keep=0)
        with pytest.raises(ValueError):
            TimelineStore(str(tmp_path), segment_samples=1)


_KILL_DRIVER = r"""
import os, sys
from mmlspark_tpu.observability.timeline import TimelineStore
store = TimelineStore(sys.argv[1], keep=4, segment_samples=5)
i = 0
while True:
    store.append(float(i), {
        "mmlspark_tpu_serving_requests_seen_total": {
            "kind": "counter",
            "samples": [{"labels": {}, "value": 3.0 * i}]}})
    if i == 20:
        open(os.path.join(sys.argv[1], "READY"), "w").write("1")
        sys.stdout.write("ready\n"); sys.stdout.flush()
    i += 1
"""


@pytest.mark.slow
class TestKillRestart:
    def test_sigkill_mid_append_leaves_readable_history(self, tmp_path):
        """SIGKILL a process that is appending as fast as it can; the
        survivor directory must read cleanly: every segment intact or
        quarantined (atomic_write means in practice intact), queries
        answer, and a new store resumes the sequence."""
        from tests.conftest import subprocess_env

        seg_dir = str(tmp_path / "segments")
        os.makedirs(seg_dir)
        proc = subprocess.Popen(
            [sys.executable, "-c", _KILL_DRIVER, seg_dir],
            env=subprocess_env(), stdout=subprocess.PIPE)
        try:
            deadline = time.monotonic() + 60.0
            while not os.path.exists(os.path.join(seg_dir, "READY")):
                assert proc.poll() is None, "driver died early"
                assert time.monotonic() < deadline, "driver never warmed"
                time.sleep(0.01)
            time.sleep(0.05)                  # let it run hot mid-write
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        store = TimelineStore(seg_dir)
        segs = store.segments()
        assert segs, "no segments survived"
        assert all(s["intact"] for s in segs), \
            "atomic_write let a torn segment through"
        ts = [t for t, _f in store.samples()]
        assert ts == sorted(ts) and len(ts) >= 5
        # the counter law (value = 3t) holds at the newest sample: the
        # file reflects a complete append, not a partial one
        last_t = ts[-1]
        assert store.last_value(_SEEN) == pytest.approx(3.0 * last_t)
        # a restarted writer continues without clobbering history
        store.append(last_t + 1.0, {_SEEN: _counter(3.0 * last_t + 3.0)})
        assert store.increase(_SEEN, 1.0, at=last_t + 1.0) == \
            pytest.approx(3.0)


# --------------------------------------------------------------------- #
# alert rules + engine                                                  #
# --------------------------------------------------------------------- #


class TestAlertRule:
    @pytest.mark.parametrize("expr", [
        "rate(mmlspark_tpu_serving_requests_seen_total[60s]) > 5",
        "increase(x_total[300s]) >= 10",
        'avg_over(q{server="a"}[30s]) < 0.5',
        "quantile(0.99, mmlspark_tpu_serving_latency_seconds[120s]) > 0.25",
        'mmlspark_tpu_serving_queue_depth{server="a"} > 3',
    ])
    def test_grammar_accepts(self, expr):
        AlertRule("r", expr)

    @pytest.mark.parametrize("expr", [
        "",                                     # empty
        "rate(x_total) > 5",                    # windowed func, no window
        "quantile(x[60s]) > 1",                 # quantile without q
        "avg_over(x[60s]) != 5",                # unsupported operator
        "rate(x[60s]) > 5 and rate(y[60s]) > 5",  # one comparison per rule
        "x{bad matcher}[60s] > 1",              # unquoted label value
    ])
    def test_grammar_rejects(self, expr):
        with pytest.raises(ValueError):
            AlertRule("r", expr)

    def test_rule_evaluates_against_store(self, tmp_path):
        store = TimelineStore(str(tmp_path))
        for i in range(5):
            store.append(float(i), {_SEEN: _counter(10.0 * i)})
        hit, value = AlertRule(
            "hot", f"rate({_SEEN}[4s]) > 5").breached(store, at=4.0)
        assert hit and value == pytest.approx(10.0)
        hit, _v = AlertRule(
            "cold", f"rate({_SEEN}[4s]) > 50").breached(store, at=4.0)
        assert not hit


class TestAlertEngine:
    def _store(self, tmp_path, values, cadence=2.0):
        store = TimelineStore(str(tmp_path))
        t = 0.0
        for v in values:
            store.append(t, {_QUEUE: _gauge(v)})
            t += cadence
        return store

    def test_pending_until_for_s_then_firing_then_recovery(self, tmp_path):
        clk = FakeClock()
        store = TimelineStore(str(tmp_path))
        rule = AlertRule("hot", f"avg_over({_QUEUE}[4s]) > 50",
                         for_s=4.0, severity="page")
        engine = AlertEngine(store, [rule], clock=clk)
        for i, v in enumerate([1.0, 1.0, 100.0, 100.0, 100.0, 100.0,
                               1.0, 1.0, 1.0]):
            t = 2.0 * i
            store.append(t, {_QUEUE: _gauge(v)})
            engine.evaluate(at=t)
        states = []
        engine2 = AlertEngine(store, [rule], clock=clk)
        for t in [0.0, 2.0, 4.0, 6.0, 8.0, 10.0, 12.0, 16.0]:
            states.append(engine2.evaluate(at=t)["hot"]["state"])
        # breach starts at t=4 (avg over [0,4] window with the spike),
        # fires once it has held for_s=4 continuously, clears on recovery
        assert states[0:2] == ["ok", "ok"]
        assert "pending" in states and "firing" in states
        assert states.index("firing") > states.index("pending")
        assert states[-1] == "ok"
        assert engine2.firing() == []

    def test_firing_edge_records_event_and_dumps_once(self, tmp_path):
        clk = FakeClock()
        store = TimelineStore(str(tmp_path / "segments"))
        fr = FlightRecorder(dump_dir=str(tmp_path / "dumps"), clock=clk,
                            registry=MetricsRegistry(), process="t")
        engine = AlertEngine(store, [AlertRule(
            "hot", f"{_QUEUE} > 50", for_s=0.0, severity="page",
            dump=True)], clock=clk, recorder=fr)
        for t, v in [(0.0, 1.0), (2.0, 99.0), (4.0, 99.0), (6.0, 1.0)]:
            store.append(t, {_QUEUE: _gauge(v)})
            engine.evaluate(at=t)
        alerts = [e for e in fr.events() if e["kind"] == "timeline.alert"]
        assert len(alerts) == 1               # edge-triggered, not level
        assert alerts[0]["data"]["rule"] == "hot"
        assert alerts[0]["data"]["severity"] == "page"
        dumps = os.listdir(tmp_path / "dumps")
        assert len(dumps) == 1 and dumps[0].startswith(DUMP_PREFIX)

    def test_alert_state_gauge_lands_in_next_sample(self, tmp_path):
        """The engine's state gauges live in the recorder overlay, so the
        durable history itself says what was firing (one sample late by
        design: evaluation follows the append)."""
        clk = FakeClock()
        reg = MetricsRegistry()
        g = reg.gauge(_QUEUE, "q")
        store = TimelineStore(str(tmp_path))
        engine = AlertEngine(store, [AlertRule(
            "hot", f"{_QUEUE} > 50", severity="page")], clock=clk)
        rec = TimelineRecorder(store, reg, clock=clk, alerts=engine)
        for v in [1.0, 99.0, 99.0, 99.0]:
            g.set(v)
            rec.sample()
            clk.sleep(2.0)
        series = store.series("mmlspark_tpu_timeline_alert_state_count")
        assert len(series) == 1
        (lbl_json, pts), = series.items()
        lbl = json.loads(lbl_json)
        assert lbl == {"rule": "hot", "severity": "page", "series": _QUEUE}
        # state computed at sample k lands in sample k+1 (eval follows
        # append): ok at t=0 -> recorded at t=2; firing at t=2 -> t=4
        assert [v for _t, v in pts] == [0.0, 2.0, 2.0]

    def test_bad_series_cannot_stop_evaluation(self, tmp_path):
        store = TimelineStore(str(tmp_path))
        store.append(0.0, {_QUEUE: _gauge(99.0)})
        engine = AlertEngine(store, [
            AlertRule("broken", "no_such_series_at_all[1s] > 0"),
            AlertRule("fine", f"{_QUEUE} > 50")], clock=FakeClock())
        res = engine.evaluate(at=0.0)
        assert res["fine"]["state"] == "firing"
        assert res["broken"]["state"] == "ok"


# --------------------------------------------------------------------- #
# regression watch                                                      #
# --------------------------------------------------------------------- #


def _latency_history(store, shift_at_s: float, until_s: float,
                     cadence: float = 2.0) -> None:
    """Cumulative serving-latency histogram: 10 fast requests (0.1
    bucket) per tick until `shift_at_s`, then 10 slow ones (1.0 bucket)
    — the p99 regression the watch must catch."""
    fast = slow = 0.0
    t = 0.0
    while t <= until_s:
        if t > 0:
            if t <= shift_at_s:
                fast += 10.0
            else:
                slow += 10.0
        buckets = {"0.1": fast, "1.0": fast + slow, "+Inf": fast + slow}
        store.append(t, {_LATENCY: _hist(fast + slow, 0.1 * fast + slow,
                                         buckets)})
        t += cadence


class TestRegressionWatch:
    def test_p99_drift_breaches_noise_band(self, tmp_path):
        store = TimelineStore(str(tmp_path), segment_samples=8)
        _latency_history(store, shift_at_s=30.0, until_s=40.0)
        watch = RegressionWatch(baseline_chunks=3, current_s=10.0,
                                min_baseline_points=3)
        rows = {r["series"]: r for r in watch.evaluate(store, at=40.0)}
        assert rows["serving_p99"]["breached"]
        assert rows["serving_p99"]["current"] > \
            rows["serving_p99"]["mean"] + rows["serving_p99"]["band"]

    def test_stable_history_stays_quiet(self, tmp_path):
        store = TimelineStore(str(tmp_path), segment_samples=8)
        _latency_history(store, shift_at_s=1e9, until_s=40.0)
        watch = RegressionWatch(baseline_chunks=3, current_s=10.0,
                                min_baseline_points=3)
        rows = watch.evaluate(store, at=40.0)
        assert rows and not any(r["breached"] for r in rows)

    def test_warming_store_is_silent(self, tmp_path):
        store = TimelineStore(str(tmp_path))
        _latency_history(store, shift_at_s=1e9, until_s=8.0)
        watch = RegressionWatch(baseline_chunks=3, current_s=10.0)
        assert watch.evaluate(store, at=8.0) == []
        assert RegressionWatch().evaluate(TimelineStore(
            str(tmp_path / "empty"))) == []

    def test_breach_surfaces_through_alert_engine(self, tmp_path):
        store = TimelineStore(str(tmp_path), segment_samples=8)
        _latency_history(store, shift_at_s=30.0, until_s=40.0)
        clk = FakeClock()
        fr = FlightRecorder(dump_dir=str(tmp_path / "dumps"), clock=clk,
                            registry=MetricsRegistry(), process="w")
        engine = AlertEngine(store, clock=clk, recorder=fr)
        engine.attach_watch(RegressionWatch(
            baseline_chunks=3, current_s=10.0, min_baseline_points=3))
        res = engine.evaluate(at=40.0)
        assert res["regression:serving_p99"]["state"] == "firing"
        kinds = [e["kind"] for e in fr.events()]
        assert "timeline.regression" in kinds


# --------------------------------------------------------------------- #
# TimelineRecorder                                                      #
# --------------------------------------------------------------------- #


class TestTimelineRecorder:
    def test_overlay_makes_segments_self_describing(self, tmp_path):
        clk = FakeClock()
        reg = MetricsRegistry()
        reg.gauge(_QUEUE, "q").set(3.0)
        rec = TimelineRecorder(str(tmp_path), reg, clock=clk,
                               segment_samples=4)
        for _ in range(6):
            rec.sample()
            clk.sleep(5.0)
        store = TimelineStore(str(tmp_path))
        assert store.last_value(
            "mmlspark_tpu_timeline_samples_total") == 6.0
        assert store.last_value(
            "mmlspark_tpu_timeline_segments_count") >= 1.0
        assert store.last_value(
            "mmlspark_tpu_timeline_last_sample_age_seconds") == 5.0
        assert store.last_value(_QUEUE) == 3.0

    def test_background_loop_samples_on_injected_clock(self, tmp_path):
        reg = MetricsRegistry()
        reg.gauge(_QUEUE, "q").set(1.0)
        rec = TimelineRecorder(str(tmp_path), reg, interval_s=0.01)
        rec.start()
        try:
            deadline = time.monotonic() + 30.0
            while rec.store.last_time() is None:
                assert time.monotonic() < deadline
                time.sleep(0.01)
        finally:
            rec.stop()
        assert TimelineStore(str(tmp_path)).last_value(_QUEUE) == 1.0

    def test_callable_source(self, tmp_path):
        rec = TimelineRecorder(str(tmp_path),
                               lambda: {_QUEUE: _gauge(7.0)},
                               clock=FakeClock())
        rec.sample()
        assert rec.store.last_value(_QUEUE) == 7.0


# --------------------------------------------------------------------- #
# flight-recorder keep-N (satellite: dump retention)                    #
# --------------------------------------------------------------------- #


class TestRecorderDumpRetention:
    def test_keep_n_prunes_oldest_and_counts(self, tmp_path):
        reg = MetricsRegistry()
        clk = FakeClock()
        fr = FlightRecorder(dump_dir=str(tmp_path), clock=clk,
                            registry=reg, process="p", keep=2)
        paths = []
        for i in range(5):
            fr.record("tick", i=i)
            paths.append(fr.dump("manual"))
            clk.advance(1.0)
        names = sorted(n for n in os.listdir(tmp_path)
                       if n.endswith(".jsonl"))
        assert len(names) == 2
        # the two newest dumps survived
        assert {os.path.join(str(tmp_path), n) for n in names} == \
            set(paths[-2:])
        snap = reg.snapshot()
        fam = snap["mmlspark_tpu_recorder_dumps_pruned_total"]
        assert fam["samples"][0]["value"] == 3.0

    def test_keep_none_retains_everything(self, tmp_path):
        fr = FlightRecorder(dump_dir=str(tmp_path),
                            registry=MetricsRegistry(), process="p")
        for _ in range(4):
            fr.dump("manual")
        assert len(os.listdir(tmp_path)) == 4

    def test_other_processes_dumps_untouched(self, tmp_path):
        other = str(tmp_path / f"{DUMP_PREFIX}other-1-000.jsonl")
        open(other, "w").write("{}\n")
        fr = FlightRecorder(dump_dir=str(tmp_path),
                            registry=MetricsRegistry(), process="mine",
                            keep=1)
        for _ in range(3):
            fr.dump("manual")
        assert os.path.exists(other)
        mine = [n for n in os.listdir(tmp_path) if "mine" in n]
        assert len(mine) == 1

    def test_keep_validated(self, tmp_path):
        with pytest.raises(ValueError):
            FlightRecorder(dump_dir=str(tmp_path), keep=0)


# --------------------------------------------------------------------- #
# autoscaler trend signals (timeline wiring)                            #
# --------------------------------------------------------------------- #


class _StubFleet:
    def __init__(self, n: int = 1):
        self.n = n

    @property
    def n_live(self) -> int:
        return self.n

    def dead_slots(self):
        return []

    def scale_to(self, n):
        self.n = n
        return []


def _calm_sig():
    return {"queue_depth": 0.0, "p99_latency_s": 0.0,
            "shed_rate": 0.0, "burn_rate": 0.0}


class TestAutoscalerTrend:
    def _rising_queue_store(self, tmp_path, slope=0.5, cadence=2.0,
                            n=31):
        store = TimelineStore(str(tmp_path))
        for i in range(n):
            t = cadence * i
            store.append(t, {_QUEUE: _gauge(slope * t)})
        return store

    def test_trend_signals_join_read_signals(self, tmp_path):
        from mmlspark_tpu.io_http.autoscale import FleetAutoscaler

        store = self._rising_queue_store(tmp_path)
        scaler = FleetAutoscaler(
            _StubFleet(1), _calm_sig, clock=FakeClock(),
            metrics=MetricsRegistry(), timeline=store,
            trend_window_s=60.0)
        sig = scaler.read_signals()
        assert sig["queue_depth_slope"] == pytest.approx(0.5)
        assert "p99_latency_slope" in sig

    def test_rising_slope_scales_up_before_absolute_threshold(
            self, tmp_path):
        """Queue at 30 is still under up_queue_depth=100, but the trend
        says it will not stay there — the slope threshold pages first."""
        from mmlspark_tpu.io_http.autoscale import FleetAutoscaler

        store = self._rising_queue_store(tmp_path)
        fleet = _StubFleet(1)
        scaler = FleetAutoscaler(
            fleet, _calm_sig, clock=FakeClock(),
            metrics=MetricsRegistry(), timeline=store,
            trend_window_s=60.0, up_queue_depth=100.0,
            up_queue_slope=0.2)
        assert scaler.tick() == "up"
        assert fleet.n_live == 2
        assert "queue_depth_slope" in scaler.state()["pressure"]

    def test_slope_blocks_scale_down_while_rising(self, tmp_path):
        from mmlspark_tpu.io_http.autoscale import FleetAutoscaler

        store = self._rising_queue_store(tmp_path)
        fleet = _StubFleet(3)
        clk = FakeClock()
        scaler = FleetAutoscaler(
            fleet, _calm_sig, clock=clk, metrics=MetricsRegistry(),
            timeline=store, trend_window_s=60.0, up_queue_depth=100.0,
            up_queue_slope=10.0,       # slope 0.5 is NOT pressure...
            hysteresis_ticks=2, cooldown_s=0.0)
        clk.advance(60.0)
        for _ in range(6):             # ...but 0.5 > 10*0.5-fraction? no:
            scaler.tick()              # 0.5 <= 5.0, so calm — downs happen
        assert fleet.n_live < 3
        # now a steep rise: slope above threshold*down_fraction blocks calm
        steep = TimelineStore(str(tmp_path / "steep"))
        for i in range(31):
            steep.append(2.0 * i, {_QUEUE: _gauge(12.0 * i)})
        fleet2 = _StubFleet(3)
        scaler2 = FleetAutoscaler(
            fleet2, _calm_sig, clock=FakeClock(),
            metrics=MetricsRegistry(), timeline=steep,
            trend_window_s=60.0, up_queue_depth=1e9,
            up_queue_slope=10.0, hysteresis_ticks=2, cooldown_s=0.0)
        scaler2.clock.advance(60.0)
        acts = [scaler2.tick() for _ in range(6)]
        assert "down" not in acts

    def test_no_timeline_means_no_trend_keys(self):
        from mmlspark_tpu.io_http.autoscale import FleetAutoscaler

        scaler = FleetAutoscaler(_StubFleet(1), _calm_sig,
                                 clock=FakeClock(),
                                 metrics=MetricsRegistry())
        sig = scaler.read_signals()
        assert "queue_depth_slope" not in sig
        assert scaler.tick() in ("none", "down")

    def test_recorder_accepted_where_store_expected(self, tmp_path):
        """Wiring convenience: passing the TimelineRecorder (what the
        fleet holds) unwraps to its store."""
        from mmlspark_tpu.io_http.autoscale import FleetAutoscaler

        rec = TimelineRecorder(str(tmp_path),
                               lambda: {_QUEUE: _gauge(0.0)},
                               clock=FakeClock())
        scaler = FleetAutoscaler(_StubFleet(1), _calm_sig,
                                 clock=FakeClock(),
                                 metrics=MetricsRegistry(), timeline=rec)
        assert scaler.timeline is rec.store


# --------------------------------------------------------------------- #
# SLO windowed burn (satellite: one-tick spikes are noise)              #
# --------------------------------------------------------------------- #


class TestWindowedBurnSignal:
    def _engine_and_state(self):
        from mmlspark_tpu.observability.slo import (SLOEngine,
                                                    availability_slo)

        clock = FakeClock()
        state = {"snap": {
            _SEEN: _counter(0.0),
            "mmlspark_tpu_serving_requests_failed_total": _counter(0.0)}}
        src = type("Src", (), {"snapshot": lambda self: state["snap"]})()
        eng = SLOEngine(src, slos=[availability_slo(
            "avail", 0.99, total=_SEEN,
            bad="mmlspark_tpu_serving_requests_failed_total")],
            clock=clock, windows={"short": 60.0, "long": 600.0})
        return eng, state, clock

    def test_one_tick_spike_does_not_reach_scaleup_threshold(self):
        eng, state, clock = self._engine_and_state()
        seen = bad = 0.0
        # five quiet evaluations at 10s cadence
        for _ in range(5):
            seen += 100.0
            state["snap"][_SEEN] = _counter(seen)
            eng.evaluate()
            clock.advance(10.0)
        # one hot evaluation: half the new traffic fails
        seen += 100.0
        bad += 50.0
        state["snap"][_SEEN] = _counter(seen)
        state["snap"]["mmlspark_tpu_serving_requests_failed_total"] = \
            _counter(bad)
        res = eng.evaluate()["avail"]
        spike = max(res["burn_rates"].values())
        assert spike > 8.0                    # the raw gauge DID spike
        sig = eng.signals()
        # ...but the autoscaler signal is the short-window average over
        # six evaluations, five of them zero-burn
        assert sig["burn_rate"] == pytest.approx(spike / 6.0)
        assert sig["burn_rate"] < spike / 2.0

        from mmlspark_tpu.io_http.autoscale import FleetAutoscaler

        fleet = _StubFleet(1)
        scaler = FleetAutoscaler(fleet, eng, clock=clock,
                                 metrics=MetricsRegistry(),
                                 up_burn_rate=spike / 2.0)
        assert scaler.tick() != "up"
        assert fleet.n_live == 1

    def test_sustained_burn_still_pages(self):
        eng, state, clock = self._engine_and_state()
        seen = bad = 0.0
        for _ in range(7):                    # every evaluation is hot
            seen += 100.0
            bad += 50.0
            state["snap"][_SEEN] = _counter(seen)
            state["snap"]["mmlspark_tpu_serving_requests_failed_total"] \
                = _counter(bad)
            eng.evaluate()
            clock.advance(10.0)
        sig = eng.signals()
        assert sig["burn_rate"] > 8.0         # the average converged up


# --------------------------------------------------------------------- #
# streaming per-partition history (timeline wiring)                     #
# --------------------------------------------------------------------- #


class TestStreamingTimeline:
    def test_parallel_query_records_partition_series(self, tmp_path):
        from mmlspark_tpu.core.pipeline import pipeline_model
        from mmlspark_tpu.core.schema import Table
        from mmlspark_tpu.streaming import (GroupedAggregator,
                                            KeyedShuffle, MemorySink,
                                            MemorySource,
                                            ParallelStreamingQuery)

        rng = np.random.default_rng(3)
        src, sink = MemorySource(), MemorySink()
        q = ParallelStreamingQuery(
            src, pipeline_model(KeyedShuffle(key_col="k",
                                             num_partitions=2),
                                GroupedAggregator(group_col="k",
                                                  value_col="v",
                                                  agg="sum")),
            sink, workers="thread", name="tlq-partitions",
            timeline_dir=str(tmp_path / "history"))
        n_batches = 3
        for _ in range(n_batches):
            src.add_rows(Table({
                "k": [f"k{int(i)}" for i in rng.integers(0, 6, 30)],
                "v": rng.normal(size=30)}))
            q.process_all_available()
        q.stop()
        store = TimelineStore(str(tmp_path / "history"))
        # one sample per committed batch (the commit IS the cadence)
        assert store.last_value(
            "mmlspark_tpu_timeline_samples_total") == float(n_batches)
        # the gauge family lives on the shared registry, so other
        # queries' labelsets may ride along in the snapshot — count
        # only THIS query's partitions
        def _mine(series):
            return {k: v for k, v in series.items()
                    if json.loads(k or "{}").get("query") == q.name}

        lag = _mine(
            store.series("mmlspark_tpu_streaming_partition_lag_seconds"))
        assert len(lag) == 2                  # one labelset per partition
        for pts in lag.values():
            assert len(pts) == n_batches
        depth = _mine(store.series(
            "mmlspark_tpu_streaming_partition_queue_depth"))
        assert len(depth) == 2


# --------------------------------------------------------------------- #
# gateway wiring (opt-in timeline_dir)                                  #
# --------------------------------------------------------------------- #


class TestGatewayTimeline:
    def test_gateway_records_history_and_shutdown_edge(self, tmp_path):
        import urllib.request

        from mmlspark_tpu.io_http.gateway import ServingGateway
        from tests.test_gateway import _EchoServer

        srv = _EchoServer("a")
        gw = ServingGateway(urls=[srv.url],
                            timeline_dir=str(tmp_path / "history"),
                            timeline_interval_s=3600.0).start()
        try:
            body = json.dumps({"x": 1.0}).encode()
            req = urllib.request.Request(
                gw.url, data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=30).read()
        finally:
            gw.stop()
            srv.stop()
        store = TimelineStore(str(tmp_path / "history"))
        # the start-of-loop sample plus the shutdown-edge sample
        assert store.last_value(
            "mmlspark_tpu_timeline_samples_total") >= 2.0
        # the shutdown-edge sample caught the forwarded request
        names = set(store.kinds())
        assert any(n.startswith("mmlspark_tpu_gateway_") for n in names)

    def test_fleet_rejects_timeline_without_rendezvous(self, tmp_path):
        from mmlspark_tpu.io_http.serving import ServingFleet

        with pytest.raises(ValueError, match="timeline"):
            ServingFleet(lambda: None, n_hosts=1, rendezvous=False,
                         timeline_dir=str(tmp_path))


# --------------------------------------------------------------------- #
# the chaos incident (ISSUE 19 acceptance)                              #
# --------------------------------------------------------------------- #


_CHAOS_DRIVER = r"""
import os, sys
from mmlspark_tpu.observability.metrics import MetricsRegistry
from mmlspark_tpu.observability.recorder import FlightRecorder
from mmlspark_tpu.observability.timeline import (AlertEngine, AlertRule,
                                                 TimelineRecorder,
                                                 TimelineStore)
from mmlspark_tpu.resilience.policy import FakeClock

root = sys.argv[1]
seg_dir = os.path.join(root, "segments")
clk = FakeClock()
reg = MetricsRegistry()
g = reg.gauge("mmlspark_tpu_serving_queue_depth", "q")
store = TimelineStore(seg_dir, keep=8, segment_samples=6)
fr = FlightRecorder(dump_dir=os.path.join(root, "dumps"), clock=clk,
                    registry=reg, process="driver")
engine = AlertEngine(store, [AlertRule(
    "queue_hot", "avg_over(mmlspark_tpu_serving_queue_depth[6s]) > 50",
    for_s=4.0, severity="page", dump=True)], clock=clk, recorder=fr)
rec = TimelineRecorder(store, reg, clock=clk, alerts=engine)
i = 0
while True:
    # the seeded fault: queue pinned hot from sample 8 onward
    g.set(3.0 if i < 8 else 100.0)
    rec.sample()
    clk.sleep(2.0)
    i += 1
    if i == 16:
        # incident recorded (alert fired, dump written); tell the
        # parent we are mid-flight so the SIGKILL lands on a live loop
        open(os.path.join(root, "READY"), "w").write("1")
"""


@pytest.mark.slow
class TestChaosIncident:
    def test_sigkilled_driver_leaves_reconstructable_incident(
            self, tmp_path):
        """The PR's acceptance story end to end: a seeded fault drives a
        rule through for_s into firing on FakeClock, the firing edge
        dumps the black box, the driver is SIGKILLed without warning —
        and `diagnose.py --history` rebuilds the incident from the
        segment directory alone, byte-stably across two renders."""
        from tests.conftest import subprocess_env
        from tests.test_fleet_observability import _diagnose

        root = str(tmp_path)
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHAOS_DRIVER, root],
            env=subprocess_env())
        try:
            deadline = time.monotonic() + 120.0
            while not os.path.exists(os.path.join(root, "READY")):
                assert proc.poll() is None, "chaos driver died early"
                assert time.monotonic() < deadline, "driver never ready"
                time.sleep(0.01)
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        seg_dir = os.path.join(root, "segments")
        # the black box dumped exactly once, on the firing edge
        dumps = [n for n in os.listdir(os.path.join(root, "dumps"))
                 if n.endswith(".jsonl")]
        assert len(dumps) == 1
        # the history alone names the incident: rule, series, edge, dump
        diagnose = _diagnose()
        report = diagnose.diagnose_history(seg_dir)
        assert "queue_hot" in report
        assert "mmlspark_tpu_serving_queue_depth" in report
        assert "firing" in report and "<-- edge" in report
        assert "dumps triggered at: +" in report
        # byte-stable: rendering is a pure function of the segment bytes
        assert diagnose.diagnose_history(seg_dir) == report
        # and the recorded alert-state series reaches state 2 (firing)
        store = TimelineStore(seg_dir)
        states = store.series("mmlspark_tpu_timeline_alert_state_count")
        assert any(v == 2.0 for pts in states.values() for _t, v in pts)
