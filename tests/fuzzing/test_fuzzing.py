"""Registry-wide fuzzing: coverage enforcement + experiment/serialization fuzz.

Reference: FuzzingTest.scala:27-100 ("verify all stages have a fuzzer"),
Fuzzing.scala:78-175. Adding a `@register_stage` class without a TestObject
(or an explicit exemption) turns this suite red.
"""

from __future__ import annotations

import importlib
import json
import pkgutil
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import mmlspark_tpu
from mmlspark_tpu.core.serialize import own_stages, registry

from .harness import experiment_fuzz, serialization_fuzz
from .test_objects import COVERED_BY_ESTIMATOR, EXEMPT, build_all


def _import_all_submodules() -> None:
    """Populate the registry the way JarLoadingUtils reflection does."""
    for pkg_name in ["core", "ops", "gbdt", "nn", "image", "text", "automl",
                     "recommendation", "io_http", "parallel", "streaming",
                     "resilience", "utils"]:
        pkg = importlib.import_module(f"mmlspark_tpu.{pkg_name}")
        for mod in pkgutil.iter_modules(pkg.__path__):
            importlib.import_module(f"mmlspark_tpu.{pkg_name}.{mod.name}")


_import_all_submodules()
# own_stages(): the coverage walk must enumerate the package's own
# stages only — under one-process multi-file runs the global registry
# also carries OTHER test modules' fixture stages (tests/test_core.py),
# which legitimately have no TestObjects
_ALL_STAGES = sorted(own_stages())


@pytest.fixture(scope="session")
def fuzz_ctx(tmp_path_factory):
    """Echo server + tmp dir shared by all TestObject builders."""

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            out = json.dumps({"echo": payload}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(out)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    ctx = {
        "url": f"http://127.0.0.1:{srv.server_address[1]}",
        "tmpdir": tmp_path_factory.mktemp("fuzz"),
    }
    yield ctx
    srv.shutdown()
    srv.server_close()


@pytest.fixture(scope="session")
def test_objects(fuzz_ctx):
    return build_all(fuzz_ctx)


def test_every_registered_stage_has_a_fuzzer(test_objects):
    """The FuzzingTest coverage gate: registry ⊆ fuzzed ∪ models ∪ exempt."""
    missing = []
    for name in _ALL_STAGES:
        if name in test_objects or name in COVERED_BY_ESTIMATOR or name in EXEMPT:
            continue
        missing.append(name)
    assert not missing, (
        "registered stages without a fuzzer (add a TestObject in "
        f"tests/fuzzing/test_objects.py or an explicit exemption): {missing}"
    )


def test_no_stale_fuzzer_entries(test_objects):
    """Every declared fuzzer/covering/exemption refers to a real stage."""
    known = set(registry())
    stale = [n for n in list(test_objects) + list(COVERED_BY_ESTIMATOR) + list(EXEMPT)
             if n not in known]
    assert not stale, f"fuzzer entries for unregistered stages: {stale}"
    # and every covering estimator itself has a TestObject
    uncovered = [est for est in COVERED_BY_ESTIMATOR.values() if est not in test_objects]
    assert not uncovered, f"covering estimators without their own fuzzer: {uncovered}"


@pytest.mark.parametrize("stage_name", _ALL_STAGES)
def test_experiment_fuzzing(stage_name, test_objects):
    """ExperimentFuzzing (Fuzzing.scala:78-106): fit/transform runs end to end,
    and the fitted model class matches the declared coverage map."""
    if stage_name in COVERED_BY_ESTIMATOR:
        pytest.skip(f"covered via {COVERED_BY_ESTIMATOR[stage_name]}")
    if stage_name in EXEMPT:
        pytest.skip(f"exempt: {EXEMPT[stage_name]}")
    for to in test_objects[stage_name]:
        experiment_fuzz(to)


def test_flight_recorder_dump_mid_fuzz_is_loadable(tmp_path):
    """A ring dumped MID-FUZZ (the wrapped stage explodes on a fuzz
    input) must always round-trip through the postmortem parser's
    schema-validating load — a recorder that writes a dump the
    postmortem cannot read is worse than no recorder at all."""
    import os

    import numpy as np

    from mmlspark_tpu.core.schema import Table
    from mmlspark_tpu.observability import (FlightRecorderTransformer,
                                            load_dump)
    from mmlspark_tpu.ops.stages import DropColumns
    from mmlspark_tpu.resilience import ChaosTransformer

    stage = FlightRecorderTransformer(
        inner=DropColumns(cols=["b"]), stage_name="fuzz_crash",
        flight_recorder_dir=str(tmp_path), ring_capacity=32,
        tick_interval_s=0.0)
    ab = Table({"a": np.arange(4.0), "b": np.arange(4.0)})
    stage.transform(ab)  # a healthy pass fills the ring first
    stage.set(inner=ChaosTransformer(fail_calls=[0]))
    with pytest.raises(Exception):
        stage.transform(ab)
    dumps = sorted(f for f in os.listdir(tmp_path)
                   if f.startswith("flight-") and f.endswith(".jsonl"))
    assert dumps, "the exception trigger wrote no dump"
    for name in dumps:
        meta, events = load_dump(os.path.join(tmp_path, name))
        assert meta["trigger"] == "exception"
        assert any(e["kind"] == "stage.exception" for e in events)
        assert any(e["kind"] == "stage.transform" for e in events)


@pytest.mark.parametrize("stage_name", _ALL_STAGES)
def test_serialization_fuzzing(stage_name, test_objects, tmp_path):
    """SerializationFuzzing (Fuzzing.scala:108-175): save/load roundtrips of
    stage and fitted model transform identically."""
    if stage_name in COVERED_BY_ESTIMATOR:
        pytest.skip(f"covered via {COVERED_BY_ESTIMATOR[stage_name]}")
    if stage_name in EXEMPT:
        pytest.skip(f"exempt: {EXEMPT[stage_name]}")
    for i, to in enumerate(test_objects[stage_name]):
        if to.skip_serialization:
            pytest.skip(to.skip_serialization)
        serialization_fuzz(to, str(tmp_path / str(i)))
