"""TestObject builders for every registered stage.

One entry per registered stage class (keyed by qualified name). Model
classes produced only by `fit` are declared in COVERED_BY_ESTIMATOR — the
experiment fuzz asserts the estimator really produces that class, so the
coverage claim is checked, not just declared (FuzzingTest.scala:27-100).
"""

from __future__ import annotations

import json
from typing import Callable

import numpy as np

from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.io_http.schema import HTTPRequestData, HTTPResponseData

from .harness import TestObject

# ---------------------------------------------------------------------------
# shared fixture tables


def _vec_table(n=120, f=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f)).astype(np.float64)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    return Table({"features": x, "label": y})


def _reg_table(n=120, f=4, seed=1):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f)).astype(np.float64)
    y = 2.0 * x[:, 0] - x[:, 1] + 0.05 * rng.normal(size=n)
    return Table({"features": x, "label": y})


def _image_table(n=4, hw=8, c=3, seed=0):
    rng = np.random.default_rng(seed)
    return Table({"image": rng.uniform(0, 255, size=(n, hw, hw, c)).astype(np.float32)})


def _interactions(seed=3):
    rng = np.random.default_rng(seed)
    rows = []
    for u in range(6):
        for i in rng.choice(8, size=4, replace=False):
            rows.append((float(u), float(i), 1.0))
    arr = np.asarray(rows, np.float64)
    return Table({"user": arr[:, 0], "item": arr[:, 1], "rating": arr[:, 2]})


def _docs():
    return Table({"text": [
        "the quick brown fox jumps", "pack my box with five dozen jugs",
        "the lazy dog sleeps", "five quick foxes", "dogs and foxes play",
        "the box is packed",
    ]})


def _scored_binary():
    return Table({
        "label": np.array([0.0, 0.0, 1.0, 1.0]),
        "scored_labels": np.array([0.0, 1.0, 1.0, 1.0]),
        "scores": np.array([0.1, 0.6, 0.7, 0.9]),
    })


def _json_response(payload) -> HTTPResponseData:
    return HTTPResponseData(
        200, "OK", {"Content-Type": "application/json"}, json.dumps(payload).encode()
    )


def _mlp_bundle(f=8, outputs=2):
    from mmlspark_tpu.nn import ModelBundle

    return ModelBundle.init("mlp", (f,), num_outputs=outputs)


# ---------------------------------------------------------------------------
# builders — ctx carries the live echo-server url (ctx["url"]) and a tmp dir


def _core_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.core.fusion import FusedPipelineModel
    from mmlspark_tpu.core.pipeline import Pipeline, Timer
    from mmlspark_tpu.nn import DeepModelTransformer
    from mmlspark_tpu.ops.conversion import DataConversion
    from mmlspark_tpu.ops.indexer import ValueIndexer
    from mmlspark_tpu.ops.stages import DropColumns

    cat = Table({"c": ["a", "b", "a", "c"], "x": np.arange(4.0)})
    f_table = Table({
        "features": np.random.default_rng(1).normal(size=(12, 8)).astype(np.float32)
    })
    return {
        "mmlspark_tpu.core.pipeline.Pipeline": [TestObject(
            Pipeline([ValueIndexer(input_col="c", output_col="i")]),
            fit_table=cat,
            model_class="mmlspark_tpu.core.pipeline.PipelineModel",
        )],
        "mmlspark_tpu.core.pipeline.Timer": [TestObject(
            Timer(DropColumns(cols=["x"])),
            transform_table=cat,
        )],
        "mmlspark_tpu.core.fusion.FusedPipelineModel": [TestObject(
            # fully fusable model+postprocess run with the fusion knobs
            # exercised: bucketed ragged tail (12 rows, bs 8 -> 8 + 4)
            FusedPipelineModel(
                [DeepModelTransformer(input_col="features").set_model(
                    _mlp_bundle(8, 3)),
                 DataConversion(cols=["output"], convert_to="float")],
                mini_batch_size=8, prefetch_depth=1, shape_buckets=True,
                readback_lag=0, fused_label="fuzz",
            ),
            transform_table=f_table,
        ), TestObject(
            # host-fallback path: a string-column stage that declares no
            # device kernel keeps the per-stage semantics unchanged
            FusedPipelineModel([DropColumns(cols=["x"])],
                               shape_buckets=False),
            transform_table=cat,
        )],
    }


def _ops_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.ops.adapter import MultiColumnAdapter
    from mmlspark_tpu.ops.conversion import DataConversion
    from mmlspark_tpu.ops.ensemble import EnsembleByKey
    from mmlspark_tpu.ops.featurize import AssembleFeatures, Featurize
    from mmlspark_tpu.ops.indexer import IndexToValue, ValueIndexer
    from mmlspark_tpu.ops.minibatch import (
        DynamicMiniBatchTransformer,
        FixedMiniBatchTransformer,
        FlattenBatch,
        TimeIntervalMiniBatchTransformer,
    )
    from mmlspark_tpu.ops.missing import CleanMissingData
    from mmlspark_tpu.ops.sample import PartitionSample
    from mmlspark_tpu.ops.stages import (
        Cacher,
        CheckpointData,
        ClassBalancer,
        DropColumns,
        Explode,
        Lambda,
        RenameColumn,
        Repartition,
        SelectColumns,
        TextPreprocessor,
        UDFTransformer,
    )
    from mmlspark_tpu.ops.summarize import SummarizeData

    ab = Table({"a": np.arange(6.0), "b": np.arange(6.0) * 2, "c": list("xyzxyz")})
    nanx = Table({"x": np.array([1.0, np.nan, 3.0]), "y": np.array([1.0, 2.0, 3.0])})
    cat = Table({"c": ["a", "b", "a", "c"]})
    indexed = Table({"i": np.array([0.0, 1.0, 0.0])},
                    meta={"i": {"category_values": ["a", "b"]}})
    batched = FixedMiniBatchTransformer(batch_size=2).transform(
        Table({"v": np.arange(5.0)}))
    ck_path = str(ctx["tmpdir"] / "ckpt_snapshot.npz")
    return {
        "mmlspark_tpu.ops.stages.DropColumns": [TestObject(
            DropColumns(cols=["a"]), transform_table=ab,
            validation=ab.drop("a"),
        )],
        "mmlspark_tpu.ops.stages.SelectColumns": [TestObject(
            SelectColumns(cols=["b", "a"]), transform_table=ab,
        )],
        "mmlspark_tpu.ops.stages.RenameColumn": [TestObject(
            RenameColumn(input_col="a", output_col="z"), transform_table=ab,
        )],
        "mmlspark_tpu.ops.stages.Repartition": [TestObject(
            Repartition(n=2), transform_table=ab,
        )],
        "mmlspark_tpu.ops.stages.Explode": [TestObject(
            Explode(input_col="vs"),
            transform_table=Table({"vs": [[1, 2], [3]], "k": ["p", "q"]}),
        )],
        "mmlspark_tpu.ops.stages.Lambda": [TestObject(
            Lambda(lambda tb: tb.with_column("y", np.asarray(tb["a"]) * 10)),
            transform_table=ab,
            skip_serialization="holds an arbitrary Python callable (reference "
                               "Lambda serializes a Scala closure — not portable)",
        )],
        "mmlspark_tpu.ops.stages.UDFTransformer": [TestObject(
            UDFTransformer(input_col="a", output_col="a2", udf=lambda v: v + 1),
            transform_table=ab,
            skip_serialization="holds an arbitrary Python callable",
        )],
        "mmlspark_tpu.ops.stages.Cacher": [TestObject(
            Cacher(), transform_table=ab,
        )],
        "mmlspark_tpu.ops.stages.CheckpointData": [TestObject(
            CheckpointData(to_disk=True, path=ck_path), transform_table=ab,
        )],
        "mmlspark_tpu.ops.stages.TextPreprocessor": [TestObject(
            TextPreprocessor(input_col="c", output_col="c2", map={"x": "X"}),
            transform_table=ab,
        )],
        "mmlspark_tpu.ops.stages.ClassBalancer": [TestObject(
            ClassBalancer(input_col="c"),
            fit_table=ab,
            model_class="mmlspark_tpu.ops.stages.ClassBalancerModel",
        )],
        "mmlspark_tpu.ops.indexer.ValueIndexer": [TestObject(
            ValueIndexer(input_col="c", output_col="i"),
            fit_table=cat,
            model_class="mmlspark_tpu.ops.indexer.ValueIndexerModel",
        )],
        "mmlspark_tpu.ops.indexer.IndexToValue": [TestObject(
            IndexToValue(input_col="i", output_col="c2"), transform_table=indexed,
        )],
        "mmlspark_tpu.ops.missing.CleanMissingData": [TestObject(
            CleanMissingData(input_cols=["x"], output_cols=["x"]),
            fit_table=nanx,
            model_class="mmlspark_tpu.ops.missing.CleanMissingDataModel",
        )],
        "mmlspark_tpu.ops.conversion.DataConversion": [TestObject(
            DataConversion(cols=["a"], convert_to="integer"), transform_table=ab,
        )],
        "mmlspark_tpu.ops.summarize.SummarizeData": [TestObject(
            SummarizeData(), transform_table=ab.drop("c"),
        )],
        "mmlspark_tpu.ops.sample.PartitionSample": [TestObject(
            PartitionSample(mode="RandomSample", percent=0.5, seed=1),
            transform_table=ab,
        )],
        "mmlspark_tpu.ops.ensemble.EnsembleByKey": [TestObject(
            EnsembleByKey(keys=["c"], cols=["a"]), transform_table=ab,
        )],
        "mmlspark_tpu.ops.adapter.MultiColumnAdapter": [TestObject(
            MultiColumnAdapter(
                base_stage=ValueIndexer(),
                input_cols=["c"], output_cols=["ci"],
            ),
            fit_table=ab,
            model_class="mmlspark_tpu.ops.adapter.MultiColumnAdapterModel",
        )],
        "mmlspark_tpu.ops.featurize.AssembleFeatures": [TestObject(
            AssembleFeatures(number_of_features=8),
            fit_table=ab.drop("c"),
            model_class="mmlspark_tpu.ops.featurize.AssembleFeaturesModel",
        )],
        "mmlspark_tpu.ops.featurize.Featurize": [TestObject(
            Featurize(feature_columns={"f1": ["a", "b"]}),
            fit_table=ab.drop("c"),
            model_class="mmlspark_tpu.core.pipeline.PipelineModel",
        )],
        "mmlspark_tpu.ops.minibatch.FixedMiniBatchTransformer": [TestObject(
            FixedMiniBatchTransformer(batch_size=2),
            transform_table=Table({"v": np.arange(5.0)}),
        )],
        "mmlspark_tpu.ops.minibatch.DynamicMiniBatchTransformer": [TestObject(
            DynamicMiniBatchTransformer(),
            transform_table=Table({"v": np.arange(5.0)}),
        )],
        "mmlspark_tpu.ops.minibatch.TimeIntervalMiniBatchTransformer": [TestObject(
            TimeIntervalMiniBatchTransformer(
                interval_ms=60_000,
                arrival_time_col="t",
            ),
            transform_table=Table({"v": np.arange(4.0),
                                   "t": np.array([0.0, 1.0, 2.0, 3.0])}),
        )],
        "mmlspark_tpu.ops.minibatch.FlattenBatch": [TestObject(
            FlattenBatch(), transform_table=batched,
        )],
    }


def _gbdt_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.gbdt import GBDTClassifier, GBDTRegressor

    return {
        "mmlspark_tpu.gbdt.estimators.GBDTClassifier": [TestObject(
            GBDTClassifier(num_iterations=5, num_leaves=7),
            fit_table=_vec_table(),
            model_class="mmlspark_tpu.gbdt.estimators.GBDTClassificationModel",
        )],
        "mmlspark_tpu.gbdt.estimators.GBDTRegressor": [TestObject(
            GBDTRegressor(num_iterations=5, num_leaves=7),
            fit_table=_reg_table(),
            model_class="mmlspark_tpu.gbdt.estimators.GBDTRegressionModel",
        )],
    }


def _nn_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.nn import DeepModelTransformer, DNNLearner, ImageFeaturizer, ModelBundle

    f_table = Table({
        "features": np.random.default_rng(0).normal(size=(12, 8)).astype(np.float32)
    })
    return {
        "mmlspark_tpu.nn.runner.DeepModelTransformer": [TestObject(
            DeepModelTransformer(input_col="features").set_model(_mlp_bundle(8, 3)),
            transform_table=f_table,
        ), TestObject(
            # async data plane knobs: pipelined non-fused loop with a
            # bucketed ragged tail (12 rows, bs 8 -> buckets 8 + 4)
            DeepModelTransformer(
                input_col="features", fused_dispatch=False,
                mini_batch_size=8, prefetch_depth=1, shape_buckets=True,
            ).set_model(_mlp_bundle(8, 3)),
            transform_table=f_table,
        )],
        "mmlspark_tpu.nn.featurizer.ImageFeaturizer": [TestObject(
            ImageFeaturizer(input_col="image").set_model(
                ModelBundle.init("simple_cnn", (8, 8, 3), num_outputs=4)
            ),
            transform_table=_image_table(n=3),
        )],
        "mmlspark_tpu.nn.trainer.DNNLearner": [TestObject(
            DNNLearner(
                architecture="mlp", model_config={"features": (8,)},
                epochs=2, batch_size=32, use_mesh=False, bfloat16=False, seed=5,
            ),
            fit_table=_vec_table(n=64, f=8),
            model_class="mmlspark_tpu.nn.trainer.DNNModel",
        ), TestObject(
            # streamed epoch loop with batch prefetch (the data plane's
            # trainer knob; fused_epochs off so the loop actually runs)
            DNNLearner(
                architecture="mlp", model_config={"features": (8,)},
                epochs=1, batch_size=16, use_mesh=False, bfloat16=False,
                seed=6, fused_epochs=False, prefetch_depth=2,
            ),
            fit_table=_vec_table(n=48, f=8),
            model_class="mmlspark_tpu.nn.trainer.DNNModel",
        )],
    }


def _image_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.image import (
        ImageSetAugmenter,
        ImageTransformer,
        ResizeImageTransformer,
        UnrollBinaryImage,
        UnrollImage,
    )

    imgs = _image_table(n=3, hw=8)
    import io as _io

    from PIL import Image

    blobs = []
    for i in range(2):
        buf = _io.BytesIO()
        Image.fromarray(
            np.full((6, 6, 3), 40 * (i + 1), np.uint8)
        ).save(buf, format="PNG")
        blobs.append(buf.getvalue())
    return {
        "mmlspark_tpu.image.transformer.ImageTransformer": [TestObject(
            ImageTransformer().resize(4, 4).gray(), transform_table=imgs,
        )],
        "mmlspark_tpu.image.transformer.ResizeImageTransformer": [TestObject(
            ResizeImageTransformer(height=4, width=4), transform_table=imgs,
        )],
        "mmlspark_tpu.image.unroll.UnrollImage": [TestObject(
            UnrollImage(), transform_table=imgs,
        )],
        "mmlspark_tpu.image.unroll.UnrollBinaryImage": [TestObject(
            UnrollBinaryImage(), transform_table=Table({"bytes": blobs}),
        )],
        "mmlspark_tpu.image.augmenter.ImageSetAugmenter": [TestObject(
            ImageSetAugmenter(), transform_table=imgs,
        )],
    }


def _text_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.text import (
        IDF,
        CountVectorizer,
        HashingTF,
        MultiNGram,
        NGram,
        PageSplitter,
        StopWordsRemover,
        TextFeaturizer,
        Tokenizer,
    )

    docs = _docs()
    toks = Tokenizer().transform(docs)
    tf = HashingTF(num_features=16).transform(toks)
    return {
        "mmlspark_tpu.text.featurizer.Tokenizer": [TestObject(
            Tokenizer(), transform_table=docs,
        )],
        "mmlspark_tpu.text.featurizer.StopWordsRemover": [TestObject(
            StopWordsRemover(input_col="tokens"), transform_table=toks,
        )],
        "mmlspark_tpu.text.featurizer.NGram": [TestObject(
            NGram(input_col="tokens", n=2), transform_table=toks,
        )],
        "mmlspark_tpu.text.featurizer.HashingTF": [TestObject(
            HashingTF(num_features=16), transform_table=toks,
        )],
        "mmlspark_tpu.text.featurizer.CountVectorizer": [TestObject(
            CountVectorizer(min_df=1),
            fit_table=toks,
            model_class="mmlspark_tpu.text.featurizer.CountVectorizerModel",
        )],
        "mmlspark_tpu.text.featurizer.IDF": [TestObject(
            IDF(),
            fit_table=tf,
            model_class="mmlspark_tpu.text.featurizer.IDFModel",
        )],
        "mmlspark_tpu.text.featurizer.TextFeaturizer": [TestObject(
            TextFeaturizer(num_features=32),
            fit_table=docs,
            model_class="mmlspark_tpu.core.pipeline.PipelineModel",
        )],
        "mmlspark_tpu.text.page_splitter.PageSplitter": [TestObject(
            PageSplitter(input_col="text", max_page_length=12, min_page_length=4),
            transform_table=docs,
        )],
        "mmlspark_tpu.text.multi_ngram.MultiNGram": [TestObject(
            MultiNGram(input_col="tokens", lengths=[1, 2]), transform_table=toks,
        )],
    }


def _automl_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.automl import (
        ComputeModelStatistics,
        ComputePerInstanceStatistics,
        DiscreteHyperParam,
        FindBestModel,
        GridSpace,
        ImageLIME,
        SuperpixelTransformer,
        TrainClassifier,
        TrainRegressor,
        TuneHyperparameters,
    )
    from mmlspark_tpu.gbdt import GBDTClassifier
    from mmlspark_tpu.nn import DeepModelTransformer, ModelBundle

    vec = _vec_table()
    good = GBDTClassifier(num_iterations=8, num_leaves=7).fit(vec)
    bad = GBDTClassifier(num_iterations=1, num_leaves=2, learning_rate=0.001).fit(vec)
    scorer = DeepModelTransformer(
        input_col="image", fetch_dict={"probability": "probability"}
    ).set_model(ModelBundle.init("simple_cnn", (8, 8, 3), num_outputs=3))
    return {
        "mmlspark_tpu.automl.train.TrainClassifier": [TestObject(
            TrainClassifier(
                model=GBDTClassifier(num_iterations=5, num_leaves=7),
                label_col="label",
            ),
            fit_table=Table({"x": np.random.default_rng(0).normal(size=60),
                             "label": ["y" if v > 0 else "n" for v in
                                       np.random.default_rng(0).normal(size=60)]}),
            model_class="mmlspark_tpu.automl.train.TrainedClassifierModel",
        )],
        "mmlspark_tpu.automl.train.TrainRegressor": [TestObject(
            TrainRegressor(
                model=__import__("mmlspark_tpu.gbdt", fromlist=["GBDTRegressor"]
                                 ).GBDTRegressor(num_iterations=5, num_leaves=7),
                label_col="label",
            ),
            fit_table=Table({"x": np.arange(40.0),
                             "label": np.arange(40.0) * 2.0}),
            model_class="mmlspark_tpu.automl.train.TrainedRegressorModel",
        )],
        "mmlspark_tpu.automl.tune.TuneHyperparameters": [TestObject(
            TuneHyperparameters(
                models=GBDTClassifier(),
                param_space=GridSpace({"num_leaves": DiscreteHyperParam([3, 7]),
                                       "num_iterations": DiscreteHyperParam([3])}),
                num_folds=2, parallelism=1, evaluation_metric="accuracy",
            ),
            fit_table=vec,
            model_class="mmlspark_tpu.automl.tune.TuneHyperparametersModel",
        )],
        "mmlspark_tpu.automl.find_best.FindBestModel": [TestObject(
            FindBestModel(models=[bad, good], evaluation_metric="accuracy"),
            fit_table=vec,
            model_class="mmlspark_tpu.automl.find_best.BestModel",
        )],
        "mmlspark_tpu.automl.metrics.ComputeModelStatistics": [TestObject(
            ComputeModelStatistics(scores_col="scores"),
            transform_table=_scored_binary(),
        )],
        "mmlspark_tpu.automl.metrics.ComputePerInstanceStatistics": [TestObject(
            ComputePerInstanceStatistics(scores_col="scores"),
            transform_table=_scored_binary(),
        )],
        "mmlspark_tpu.automl.lime.SuperpixelTransformer": [TestObject(
            SuperpixelTransformer(cell_size=4), transform_table=_image_table(n=2),
        )],
        "mmlspark_tpu.automl.lime.ImageLIME": [TestObject(
            ImageLIME(model=scorer, cell_size=4, num_samples=16, seed=1),
            transform_table=_image_table(n=1),
        )],
    }


def _recommendation_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.recommendation import (
        SAR,
        RankingAdapter,
        RankingEvaluator,
        RankingTrainValidationSplit,
        RecommendationIndexer,
        SARTopKScorer,
    )

    inter = _interactions()
    named = Table({
        "customer": ["bob", "amy", "bob", "ann"],
        "product": ["x", "y", "z", "x"],
        "rating": np.ones(4),
    })
    ranked = Table({
        "prediction": [[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]],
        "label": [[2.0, 9.0], [4.0]],
    })
    return {
        "mmlspark_tpu.recommendation.indexer.RecommendationIndexer": [TestObject(
            RecommendationIndexer(
                user_input_col="customer", user_output_col="user",
                item_input_col="product", item_output_col="item",
            ),
            fit_table=named,
            model_class="mmlspark_tpu.recommendation.indexer.RecommendationIndexerModel",
        )],
        "mmlspark_tpu.recommendation.sar.SAR": [TestObject(
            SAR(support_threshold=1),
            fit_table=inter,
            model_class="mmlspark_tpu.recommendation.sar.SARModel",
        )],
        "mmlspark_tpu.recommendation.resident.SARTopKScorer": [TestObject(
            SARTopKScorer.from_model(
                SAR(support_threshold=1).fit(inter), k=3,
            ),
            transform_table=Table({"user": np.asarray([0.0, 1.0, 5.0])}),
        )],
        "mmlspark_tpu.recommendation.ranking.RankingAdapter": [TestObject(
            RankingAdapter(recommender=SAR(support_threshold=1), k=3),
            fit_table=inter,
            model_class="mmlspark_tpu.recommendation.ranking.RankingAdapterModel",
        )],
        "mmlspark_tpu.recommendation.ranking.RankingEvaluator": [TestObject(
            RankingEvaluator(k=2), transform_table=ranked,
        )],
        "mmlspark_tpu.recommendation.ranking.RankingTrainValidationSplit": [TestObject(
            RankingTrainValidationSplit(
                recommender=SAR(support_threshold=1), k=3, min_ratings_per_user=2,
            ),
            fit_table=inter,
            model_class=(
                "mmlspark_tpu.recommendation.ranking.RankingTrainValidationSplitModel"
            ),
        )],
    }


def _with_udf(stage, fn):
    stage.udf = fn
    return stage


def _face_handler(req):
    return _json_response({"isIdentical": True, "groups": [["a"]],
                           "candidates": [], "confidence": 0.9})


def _recognize_handler(req):
    if req.method == "GET":
        return _json_response({"status": "Succeeded",
                               "recognitionResult": {"lines": []}})
    return HTTPResponseData(202, "Accepted",
                            {"Operation-Location": "http://fake/op/1"}, b"")


def _face_to(cls, values):
    from .harness import TestObject as _TO

    stage = cls(url="http://fake/face", output_col="out")
    stage.set(**values)
    stage.handler = _face_handler

    def _attach(s):
        s.handler = _face_handler

    return _TO(stage, transform_table=Table({"dummy": [1.0]}), after_load=_attach)


def _recognize_text_to(ctx):
    from mmlspark_tpu.io_http import RecognizeText

    stage = RecognizeText(url="http://fake/recognizeText", output_col="out",
                          poll_interval_s=0.0)
    stage.set(image_url="http://x/a.png")
    stage.handler = _recognize_handler

    def _attach(s):
        s.handler = _recognize_handler

    return TestObject(stage, transform_table=Table({"dummy": [1.0]}),
                      after_load=_attach)


def _bing_handler(req):
    return _json_response({"value": [{"contentUrl": "http://x/a.png"}]})


def _bing_to():
    from mmlspark_tpu.io_http import BingImageSearch

    stage = BingImageSearch(url="http://fake/bing", output_col="out")
    stage.set(query="cats")
    stage.handler = _bing_handler

    def _attach(s):
        s.handler = _bing_handler

    return TestObject(stage, transform_table=Table({"dummy": [1.0]}),
                      after_load=_attach)


def _azure_search_handler(req):
    if req.method == "GET":
        return _json_response({"name": "idx"})
    if req.url.split("?")[0].endswith("docs/index"):
        n = len(req.json()["value"])
        return _json_response({"value": [{"key": str(i), "status": True}
                                         for i in range(n)]})
    return _json_response({"name": "idx"})


def _azure_search_to():
    from mmlspark_tpu.io_http import AzureSearchWriter

    stage = AzureSearchWriter(
        service_url="http://fake/search",
        index_definition={"name": "idx", "fields": [
            {"name": "id", "type": "Edm.String", "key": True}]},
    )
    stage.handler = _azure_search_handler

    def _attach(s):
        s.handler = _azure_search_handler

    return TestObject(stage, transform_table=Table({"id": ["1", "2"]}),
                      after_load=_attach)


def _io_http_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.io_http import (
        NER,
        OCR,
        AnalyzeImage,
        CustomInputParser,
        CustomOutputParser,
        DescribeImage,
        DistributedHTTPTransformer,
        RecognizeDomainSpecificContent,
        DetectFace,
        EntityDetector,
        FindSimilarFace,
        GenerateThumbnails,
        GroupFaces,
        HTTPTransformer,
        IdentifyFaces,
        JSONInputParser,
        JSONOutputParser,
        KeyPhraseExtractor,
        LanguageDetector,
        PartitionConsolidator,
        SimpleHTTPTransformer,
        StringOutputParser,
        TagImage,
        TextSentiment,
        VerifyFaces,
    )

    url = ctx["url"]
    payloads = Table({"payload": [{"v": 1}, {"v": 2}]})
    requests_tbl = JSONInputParser(input_col="payload", url=url).transform(payloads)
    responses_tbl = Table({"response": [
        _json_response({"echo": {"v": 1}}), _json_response({"echo": {"v": 2}}),
    ]})
    text_tbl = Table({"text_col": ["good day", "bad day"]})
    img_tbl = Table({"img_url": ["http://x/a.png", "http://x/b.png"]})

    def _ta_handler(req):
        body = req.json()
        doc = body["documents"][0]
        return _json_response({"documents": [{"id": doc["id"], "score": 0.9}]})

    def _vision_handler(req):
        return _json_response({"language": "en", "regions": [], "categories": []})

    def _set_ta_handler(stage):
        stage.handler = _ta_handler

    def _set_vision_handler(stage):
        stage.handler = _vision_handler

    def _make_ta(cls):
        stage = cls(url=url + "/ta", output_col="out")
        stage.set_col(text="text_col")
        stage.handler = _ta_handler
        return TestObject(stage, transform_table=text_tbl,
                          after_load=_set_ta_handler)

    def _make_vision(cls, **kw):
        stage = cls(url=url + "/vision", output_col="out", **kw)
        stage.set_col(image_url="img_url")
        stage.handler = _vision_handler
        return TestObject(stage, transform_table=img_tbl,
                          after_load=_set_vision_handler)

    consolidator = PartitionConsolidator(input_col="v", output_col="v2", num_lanes=2)
    consolidator.fn = lambda v: v * 2

    def _set_fn(stage):
        stage.fn = lambda v: v * 2

    return {
        "mmlspark_tpu.io_http.transformer.HTTPTransformer": [TestObject(
            HTTPTransformer(concurrency=2), transform_table=requests_tbl,
            skip_output_compare="response objects carry per-call latency headers",
        )],
        "mmlspark_tpu.io_http.transformer.DistributedHTTPTransformer": [
            TestObject(
                DistributedHTTPTransformer(urls=[url], concurrency=2),
                transform_table=requests_tbl,
                skip_output_compare="response objects carry per-call "
                                    "latency headers",
            ),
            TestObject(
                DistributedHTTPTransformer(urls=[url], routing_key_col="key"),
                transform_table=requests_tbl.with_column("key", ["a", "b"]),
                skip_output_compare="response objects carry per-call "
                                    "latency headers",
            ),
        ],
        "mmlspark_tpu.io_http.transformer.SimpleHTTPTransformer": [TestObject(
            SimpleHTTPTransformer(url=url, flatten_output_field="echo.q",
                                  output_col="answer", concurrency=2),
            transform_table=Table({"input": [{"q": "hi"}, {"q": "yo"}]}),
        )],
        "mmlspark_tpu.io_http.transformer.JSONInputParser": [TestObject(
            JSONInputParser(input_col="payload", url=url), transform_table=payloads,
            skip_output_compare="output column holds HTTPRequestData objects",
        )],
        "mmlspark_tpu.io_http.transformer.JSONOutputParser": [TestObject(
            JSONOutputParser(field_path="echo.v", output_col="v"),
            transform_table=responses_tbl,
        )],
        "mmlspark_tpu.io_http.transformer.StringOutputParser": [TestObject(
            StringOutputParser(output_col="s"), transform_table=responses_tbl,
        )],
        "mmlspark_tpu.io_http.transformer.CustomInputParser": [TestObject(
            _with_udf(CustomInputParser(input_col="payload"),
                      lambda v: HTTPRequestData.from_json(url, v)),
            transform_table=payloads,
            after_load=lambda s: _with_udf(s, lambda v: HTTPRequestData.from_json(url, v)),
            skip_output_compare="output column holds HTTPRequestData objects",
        )],
        "mmlspark_tpu.io_http.transformer.CustomOutputParser": [TestObject(
            _with_udf(CustomOutputParser(), lambda r: r.json()["echo"]),
            transform_table=responses_tbl,
            after_load=lambda s: _with_udf(s, lambda r: r.json()["echo"]),
        )],
        "mmlspark_tpu.io_http.consolidator.PartitionConsolidator": [TestObject(
            consolidator, transform_table=Table({"v": np.arange(4.0)}),
            after_load=_set_fn,
        )],
        "mmlspark_tpu.io_http.cognitive.TextSentiment": [_make_ta(TextSentiment)],
        "mmlspark_tpu.io_http.cognitive.LanguageDetector": [_make_ta(LanguageDetector)],
        "mmlspark_tpu.io_http.cognitive.EntityDetector": [_make_ta(EntityDetector)],
        "mmlspark_tpu.io_http.cognitive.KeyPhraseExtractor": [_make_ta(KeyPhraseExtractor)],
        "mmlspark_tpu.io_http.cognitive.NER": [_make_ta(NER)],
        "mmlspark_tpu.io_http.cognitive.OCR": [_make_vision(OCR)],
        "mmlspark_tpu.io_http.cognitive.AnalyzeImage": [_make_vision(AnalyzeImage)],
        "mmlspark_tpu.io_http.cognitive.DetectFace": [_make_vision(DetectFace)],
        "mmlspark_tpu.io_http.cognitive.TagImage": [_make_vision(TagImage)],
        "mmlspark_tpu.io_http.cognitive.DescribeImage": [_make_vision(DescribeImage)],
        "mmlspark_tpu.io_http.cognitive.RecognizeDomainSpecificContent": [
            _make_vision(RecognizeDomainSpecificContent, model="landmarks")],
        "mmlspark_tpu.io_http.cognitive.GenerateThumbnails": [_make_vision(GenerateThumbnails)],
        "mmlspark_tpu.io_http.cognitive.RecognizeText": [_recognize_text_to(ctx)],
        "mmlspark_tpu.io_http.cognitive.FindSimilarFace": [_face_to(
            FindSimilarFace, {"face_id": "q", "face_ids": ["a", "b"]})],
        "mmlspark_tpu.io_http.cognitive.GroupFaces": [_face_to(
            GroupFaces, {"face_ids": ["a", "b", "c"]})],
        "mmlspark_tpu.io_http.cognitive.IdentifyFaces": [_face_to(
            IdentifyFaces, {"person_group_id": "pg", "face_ids": ["a"]})],
        "mmlspark_tpu.io_http.cognitive.VerifyFaces": [_face_to(
            VerifyFaces, {"face_id1": "a", "face_id2": "a"})],
        "mmlspark_tpu.io_http.cognitive.BingImageSearch": [_bing_to()],
        "mmlspark_tpu.io_http.search.AzureSearchWriter": [_azure_search_to()],
    }


def _streaming_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.streaming import (GroupedAggregator, KeyedShuffle,
                                        StreamStreamJoin, StreamTableJoin,
                                        WindowedAggregator)

    # event times span five 10s windows; with a 5s watermark delay the
    # max time (47) finalizes everything through [30,40) in one batch,
    # so the windowed fuzz exercises real emission, not an empty table
    events = Table({
        "key": ["a", "b", "a", "c", "b", "a"],
        "value": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
        "time": np.array([1.0, 5.0, 12.0, 18.0, 23.0, 47.0]),
    })
    # two-sided stream: close left/right times per key so the interval
    # join emits pairs, not an empty table
    sided = Table({
        "key": ["a", "a", "b", "b", "a", "c"],
        "time": np.array([1.0, 2.0, 3.0, 4.5, 6.0, 7.0]),
        "side": ["left", "right", "left", "right", "right", "left"],
        "value": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
    })
    static_path = str(ctx["tmpdir"] / "dim_table.csv")
    with open(static_path, "w", encoding="utf-8") as fh:
        fh.write("key,weight\na,1.5\nb,2.5\nc,3.5\n")
    return {
        "mmlspark_tpu.streaming.state.GroupedAggregator": [
            TestObject(
                GroupedAggregator(group_col="key", value_col="value",
                                  agg="sum"),
                transform_table=events,
            ),
            # the spill backend through the Param surface: tiny hot set
            # forces real parquet eviction during the fuzz transform
            TestObject(
                GroupedAggregator(group_col="key", value_col="value",
                                  agg="sum", state_backend="spill",
                                  spill_dir=str(ctx["tmpdir"] / "spill"),
                                  spill_hot_keys=1),
                transform_table=events,
            ),
        ],
        "mmlspark_tpu.streaming.state.WindowedAggregator": [TestObject(
            WindowedAggregator(time_col="time", window_s=10.0,
                               group_col="key", value_col="value",
                               agg="mean", watermark_delay_s=5.0),
            transform_table=events,
        )],
        "mmlspark_tpu.streaming.shuffle.KeyedShuffle": [TestObject(
            KeyedShuffle(key_col="key", num_partitions=4),
            transform_table=events,
        )],
        "mmlspark_tpu.streaming.joins.StreamStreamJoin": [TestObject(
            StreamStreamJoin(key_col="key", join_window_s=3.0,
                             watermark_delay_s=2.0),
            transform_table=sided,
        )],
        "mmlspark_tpu.streaming.joins.StreamTableJoin": [TestObject(
            StreamTableJoin(key_col="key", table_path=static_path,
                            how="left"),
            transform_table=events,
        )],
    }


def _resilience_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.ops.stages import DropColumns
    from mmlspark_tpu.resilience import (ChaosTransformer,
                                         CircuitBreakerTransformer)

    ab = Table({"a": np.arange(6.0), "b": np.arange(6.0) * 2})
    return {
        # seed fixed, no probabilistic faults: the fuzz transform must be
        # deterministic (save/load roundtrips compare outputs)
        "mmlspark_tpu.resilience.chaos.ChaosTransformer": [TestObject(
            ChaosTransformer(seed=7), transform_table=ab,
        )],
        "mmlspark_tpu.resilience.breaker.CircuitBreakerTransformer": [
            TestObject(
                CircuitBreakerTransformer(inner=DropColumns(cols=["b"]),
                                          min_calls=2),
                transform_table=ab,
            )],
    }


def _observability_objects(ctx) -> dict[str, list[TestObject]]:
    from mmlspark_tpu.observability import (FlightRecorderTransformer,
                                            InstrumentedTransformer)
    from mmlspark_tpu.ops.stages import DropColumns

    ab = Table({"a": np.arange(6.0), "b": np.arange(6.0) * 2})
    return {
        "mmlspark_tpu.observability.stage.InstrumentedTransformer": [
            TestObject(
                InstrumentedTransformer(inner=DropColumns(cols=["b"]),
                                        stage_name="fuzz"),
                transform_table=ab,
            )],
        # every recorder knob exercised through the Param surface;
        # tick_interval_s=0 snapshots metric deltas on EVERY transform so
        # the fuzz rings carry the densest event mix the schema allows
        "mmlspark_tpu.observability.stage.FlightRecorderTransformer": [
            TestObject(
                FlightRecorderTransformer(
                    inner=DropColumns(cols=["b"]),
                    stage_name="fuzz_recorder",
                    flight_recorder_dir=str(ctx["tmpdir"] / "flightrec"),
                    exemplars=True, ring_capacity=64, tick_interval_s=0.0),
                transform_table=ab,
            )],
    }


BUILDER_GROUPS: list[Callable] = [
    _core_objects,
    _ops_objects,
    _gbdt_objects,
    _nn_objects,
    _image_objects,
    _text_objects,
    _automl_objects,
    _recommendation_objects,
    _io_http_objects,
    _streaming_objects,
    _resilience_objects,
    _observability_objects,
]


def build_all(ctx) -> dict[str, list[TestObject]]:
    out: dict[str, list[TestObject]] = {}
    for group in BUILDER_GROUPS:
        for key, objs in group(ctx).items():
            assert key not in out, f"duplicate TestObject key {key}"
            out[key] = objs
    return out


# Model classes produced only by `fit`: the experiment fuzz of the estimator
# asserts the fitted model really is this class (coverage is verified).
COVERED_BY_ESTIMATOR: dict[str, str] = {
    "mmlspark_tpu.core.pipeline.PipelineModel": "mmlspark_tpu.core.pipeline.Pipeline",
    "mmlspark_tpu.ops.stages.ClassBalancerModel": "mmlspark_tpu.ops.stages.ClassBalancer",
    "mmlspark_tpu.ops.indexer.ValueIndexerModel": "mmlspark_tpu.ops.indexer.ValueIndexer",
    "mmlspark_tpu.ops.missing.CleanMissingDataModel": "mmlspark_tpu.ops.missing.CleanMissingData",
    "mmlspark_tpu.ops.adapter.MultiColumnAdapterModel": "mmlspark_tpu.ops.adapter.MultiColumnAdapter",
    "mmlspark_tpu.ops.featurize.AssembleFeaturesModel": "mmlspark_tpu.ops.featurize.AssembleFeatures",
    "mmlspark_tpu.gbdt.estimators.GBDTClassificationModel": "mmlspark_tpu.gbdt.estimators.GBDTClassifier",
    "mmlspark_tpu.gbdt.estimators.GBDTRegressionModel": "mmlspark_tpu.gbdt.estimators.GBDTRegressor",
    "mmlspark_tpu.nn.trainer.DNNModel": "mmlspark_tpu.nn.trainer.DNNLearner",
    "mmlspark_tpu.text.featurizer.CountVectorizerModel": "mmlspark_tpu.text.featurizer.CountVectorizer",
    "mmlspark_tpu.text.featurizer.IDFModel": "mmlspark_tpu.text.featurizer.IDF",
    "mmlspark_tpu.automl.train.TrainedClassifierModel": "mmlspark_tpu.automl.train.TrainClassifier",
    "mmlspark_tpu.automl.train.TrainedRegressorModel": "mmlspark_tpu.automl.train.TrainRegressor",
    "mmlspark_tpu.automl.tune.TuneHyperparametersModel": "mmlspark_tpu.automl.tune.TuneHyperparameters",
    "mmlspark_tpu.automl.find_best.BestModel": "mmlspark_tpu.automl.find_best.FindBestModel",
    "mmlspark_tpu.recommendation.indexer.RecommendationIndexerModel":
        "mmlspark_tpu.recommendation.indexer.RecommendationIndexer",
    "mmlspark_tpu.recommendation.sar.SARModel": "mmlspark_tpu.recommendation.sar.SAR",
    "mmlspark_tpu.recommendation.ranking.RankingAdapterModel":
        "mmlspark_tpu.recommendation.ranking.RankingAdapter",
    "mmlspark_tpu.recommendation.ranking.RankingTrainValidationSplitModel":
        "mmlspark_tpu.recommendation.ranking.RankingTrainValidationSplit",
}

# Stages that legitimately cannot be fuzzed, with the reason on record
# (FuzzingTest.scala keeps the same explicit exemption list).
EXEMPT: dict[str, str] = {}
