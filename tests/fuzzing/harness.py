"""Metamorphic fuzzing harness over the stage registry.

Reference: core/test/fuzzing/src/main/scala/Fuzzing.scala —
`TestObject` (:19-31), `ExperimentFuzzing` (:78-106), `SerializationFuzzing`
(:108-175) — and `FuzzingTest.scala:27-100`, which reflectively enumerates
every Wrappable stage and fails when one lacks a fuzzer. Here the registry
(`mmlspark_tpu.core.serialize.registry`) plays the role of JVM reflection:
every `@register_stage` class must either supply TestObjects, be declared as
the fitted-model class of a fuzzed estimator, or carry an explicit exemption.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from mmlspark_tpu.core.pipeline import Estimator, PipelineStage
from mmlspark_tpu.core.schema import Table


def qualname(cls: type) -> str:
    return f"{cls.__module__}.{cls.__name__}"


@dataclass
class TestObject:
    """A stage plus the tables needed to exercise it (Fuzzing.scala:19-31)."""

    stage: Any
    fit_table: Table | None = None          # estimators: table passed to fit
    transform_table: Table | None = None    # table passed to transform (default: fit_table)
    validation: Table | None = None         # optional expected transform output
    model_class: str | None = None          # expected qualified name of the fitted model
    skip_serialization: str | None = None   # reason serialization fuzz is impossible
    skip_output_compare: str | None = None  # reason outputs are not comparable across runs
    after_load: Callable[[Any], None] | None = None  # re-attach non-serializable hooks
    rtol: float = 1e-5

    def _transform_input(self) -> Table:
        tbl = self.transform_table if self.transform_table is not None else self.fit_table
        assert tbl is not None, "TestObject needs a transform_table or fit_table"
        return tbl


def experiment_fuzz(to: TestObject) -> tuple[Any, Table]:
    """Fit/transform must run end to end (ExperimentFuzzing, Fuzzing.scala:78-106)."""
    if isinstance(to.stage, Estimator):
        assert to.fit_table is not None, f"{type(to.stage).__name__} needs fit_table"
        model = to.stage.fit(to.fit_table)
        if to.model_class is not None:
            got = qualname(type(model))
            assert got == to.model_class, (
                f"{type(to.stage).__name__}.fit produced {got}, "
                f"declared model_class is {to.model_class}"
            )
        out = model.transform(to._transform_input())
    else:
        model = to.stage
        out = to.stage.transform(to._transform_input())
    assert isinstance(out, Table)
    if to.validation is not None:
        assert out.equals(to.validation, rtol=to.rtol), (
            f"output does not match validation table: {out!r} vs {to.validation!r}"
        )
    return model, out


def _assert_tables_close(a: Table, b: Table, rtol: float, context: str) -> None:
    assert set(a.columns) == set(b.columns), (
        f"{context}: column mismatch {sorted(a.columns)} vs {sorted(b.columns)}"
    )
    assert len(a) == len(b), f"{context}: row count {len(a)} vs {len(b)}"
    for k in a.columns:
        va, vb = a[k], b[k]
        if isinstance(va, np.ndarray) and isinstance(vb, np.ndarray) and np.issubdtype(
            va.dtype, np.floating
        ):
            np.testing.assert_allclose(
                np.asarray(va, np.float64), np.asarray(vb, np.float64),
                rtol=rtol, atol=1e-6, equal_nan=True,
                err_msg=f"{context}: column {k!r} differs",
            )
        else:
            assert _loose_eq(va, vb), f"{context}: column {k!r} differs"


def _loose_eq(a: Any, b: Any) -> bool:
    a_l = a.tolist() if hasattr(a, "tolist") else list(a)
    b_l = b.tolist() if hasattr(b, "tolist") else list(b)
    return _cell_eq(a_l, b_l)


def _cell_eq(a: Any, b: Any) -> bool:
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_cell_eq(x, y) for x, y in zip(a, b))
    if isinstance(a, float) and isinstance(b, float):
        return bool(np.isclose(a, b, rtol=1e-5, atol=1e-6, equal_nan=True))
    if hasattr(a, "__array__") or hasattr(b, "__array__"):
        return bool(np.allclose(np.asarray(a, np.float64), np.asarray(b, np.float64),
                                rtol=1e-5, atol=1e-6, equal_nan=True))
    return a == b


def serialization_fuzz(to: TestObject, tmp_path: str) -> None:
    """Save/load roundtrips of the raw stage and (for estimators) the fitted
    model; loaded stages must transform identically
    (SerializationFuzzing, Fuzzing.scala:108-175)."""
    raw_dir = os.path.join(tmp_path, "raw")
    to.stage.save(raw_dir)
    loaded = PipelineStage.load(raw_dir)
    assert type(loaded) is type(to.stage)
    if to.after_load is not None:
        to.after_load(loaded)

    if isinstance(to.stage, Estimator):
        tbl = to._transform_input()
        m1 = to.stage.fit(to.fit_table)
        o1 = m1.transform(tbl)
        m2 = loaded.fit(to.fit_table)
        o2 = m2.transform(tbl)
        if to.skip_output_compare is None:
            _assert_tables_close(o1, o2, to.rtol, "refit-after-load")
        model_dir = os.path.join(tmp_path, "model")
        m1.save(model_dir)
        m3 = PipelineStage.load(model_dir)
        if to.after_load is not None:
            to.after_load(m3)
        o3 = m3.transform(tbl)
        if to.skip_output_compare is None:
            _assert_tables_close(o1, o3, to.rtol, "fitted-model-roundtrip")
    else:
        tbl = to._transform_input()
        o1 = to.stage.transform(tbl)
        o2 = loaded.transform(tbl)
        if to.skip_output_compare is None:
            _assert_tables_close(o1, o2, to.rtol, "transformer-roundtrip")
