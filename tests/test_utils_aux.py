"""Aux-subsystem tests: plot module, datagen, storage abstraction, remote
model zoo fetch, FluentAPI sugar.

Reference: src/plot/src/main/python/plot.py:17-40, core/test/datagen
(GenerateDataset/DatasetConstraints), core/hadoop + ModelDownloader's
remote repo (ModelDownloader.scala:54-119), core/spark FluentAPI.scala:13-30.
"""

import http.server
import threading

import numpy as np
import pytest

from mmlspark_tpu.core.schema import Table
from mmlspark_tpu.plot import confusion_matrix, plot_confusion_matrix, plot_roc
from mmlspark_tpu.utils import ColumnSpec, generate_table, random_specs, storage


class TestPlot:
    def _scored(self):
        return Table({
            "label": np.array([0.0, 0.0, 1.0, 1.0, 1.0]),
            "scored_labels": np.array([0.0, 1.0, 1.0, 1.0, 0.0]),
            "scores": np.array([0.1, 0.6, 0.8, 0.9, 0.4]),
        })

    def test_confusion_matrix_counts(self):
        m = confusion_matrix(self._scored())
        assert m.tolist() == [[1, 1], [1, 2]]

    def test_plot_confusion_matrix_renders(self):
        m, ax = plot_confusion_matrix(self._scored())
        assert ax is not None and m.sum() == 5

    def test_plot_roc(self):
        (fpr, tpr, _), auc_value, ax = plot_roc(self._scored())
        assert 0.5 < auc_value <= 1.0
        assert fpr[0] == 0.0 and tpr[-1] == 1.0
        assert ax is not None

    def test_headless_skip_render(self):
        m, ax = plot_confusion_matrix(self._scored(), ax=False)
        assert ax is None and m.shape == (2, 2)


class TestDatagen:
    def test_constraints_respected(self):
        specs = [
            ColumnSpec("d", "double", low=-1, high=1, null_fraction=0.2),
            ColumnSpec("i", "int", low=0, high=9),
            ColumnSpec("b", "bool"),
            ColumnSpec("s", "string", length=4),
            ColumnSpec("c", "category", cardinality=3),
            ColumnSpec("v", "vector", length=6),
        ]
        t = generate_table(specs, 200, seed=1)
        assert t.num_rows == 200
        d = np.asarray(t["d"], np.float64)
        finite = d[np.isfinite(d)]
        assert finite.min() >= -1 and finite.max() <= 1
        assert 0.05 < np.isnan(d).mean() < 0.5
        i = np.asarray(t["i"])
        assert i.min() >= 0 and i.max() <= 9
        assert all(len(s) == 4 for s in t["s"])
        assert set(t["c"]) <= {"level_0", "level_1", "level_2"}
        assert t.meta("c")["category_values"] == ["level_0", "level_1", "level_2"]
        assert np.asarray(t["v"]).shape == (200, 6)

    def test_deterministic_by_seed(self):
        specs = random_specs(5, seed=3)
        t1 = generate_table(specs, 50, seed=7)
        t2 = generate_table(specs, 50, seed=7)
        assert t1.equals(t2)

    def test_feeds_serialization_roundtrip(self):
        """Datagen tables drive a stage save/load roundtrip (the reference's
        datagen-for-serialization-tests purpose)."""
        from mmlspark_tpu.core.pipeline import PipelineStage
        from mmlspark_tpu.ops.indexer import ValueIndexer

        t = generate_table([ColumnSpec("c", "category", cardinality=4)], 100, seed=2)
        model = ValueIndexer(input_col="c", output_col="i").fit(t)
        import tempfile

        d = tempfile.mkdtemp()
        model.save(d)
        loaded = PipelineStage.load(d)
        assert loaded.transform(t).equals(model.transform(t))

    def test_bad_spec_rejected(self):
        with pytest.raises(ValueError):
            ColumnSpec("x", "floaty")
        with pytest.raises(ValueError):
            ColumnSpec("x", "double", null_fraction=2.0)


class TestStorage:
    def test_local_roundtrip(self, tmp_path):
        p = str(tmp_path / "a" / "b.bin")
        storage.write_bytes(p, b"hello")
        assert storage.exists(p)
        assert storage.read_bytes(p) == b"hello"
        assert storage.read_bytes("file://" + p) == b"hello"
        assert not storage.exists(str(tmp_path / "nope"))

    def test_scheme_of(self):
        assert storage.scheme_of("/plain/path") == ""
        assert storage.scheme_of("file:///x") == "file"
        assert storage.scheme_of("https://h/x") == "https"
        assert storage.scheme_of("C:\\win\\path") in ("", "c")

    def test_http_read_and_exists(self, tmp_path):
        served = tmp_path / "blob.bin"
        served.write_bytes(b"remote-bytes")
        handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(
            *a, directory=str(tmp_path), **kw)
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/blob.bin"
            assert storage.exists(url)
            assert storage.read_bytes(url) == b"remote-bytes"
            assert not storage.exists(url + ".missing")
            with pytest.raises(ValueError):
                storage.write_bytes(url, b"nope")
            dest = str(tmp_path / "fetched.bin")
            storage.copy_to_local(url, dest)
            assert open(dest, "rb").read() == b"remote-bytes"
        finally:
            srv.shutdown()
            srv.server_close()

    def test_unknown_scheme(self):
        with pytest.raises(ValueError):
            storage.read_bytes("weird://x/y")

    def test_colon_local_filename_is_local(self, tmp_path):
        """'model:v2.bin'-style names are local paths, not schemes (the
        pre-abstraction zoo copied them with shutil)."""
        import os

        cwd = os.getcwd()
        os.chdir(tmp_path)
        try:
            storage.write_bytes("model:v2.bin", b"x")
            assert storage.exists("model:v2.bin")
            assert storage.read_bytes("model:v2.bin") == b"x"
        finally:
            os.chdir(cwd)


class TestRemoteZoo:
    def test_download_model_over_http(self, tmp_path):
        """ModelDownloader fetches a bundle from an http:// uri with sha256
        verification (remote repo → local repo, ModelDownloader.scala:54-119)."""
        import hashlib

        from mmlspark_tpu.nn import ModelBundle, ModelDownloader, ModelSchema

        src = tmp_path / "serve" / "tiny.model"
        src.parent.mkdir()
        ModelBundle.init("mlp", (4,), num_outputs=2).save(str(src))
        sha = hashlib.sha256(src.read_bytes()).hexdigest()
        handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(
            *a, directory=str(src.parent), **kw)
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/tiny.model"
            repo = ModelDownloader(str(tmp_path / "repo"))
            schema = ModelSchema(name="tiny-http", uri=url, sha256=sha)
            local = repo.download_model(schema)
            bundle = ModelBundle.load(local)
            assert bundle.architecture == "mlp"
            # corrupted hash still rejected over http
            bad = ModelSchema(name="bad-http", uri=url, sha256="0" * 64)
            with pytest.raises(IOError):
                repo.download_model(bad)
        finally:
            srv.shutdown()
            srv.server_close()


class TestFluentAPI:
    def test_ml_transform_and_fit(self):
        from mmlspark_tpu.ops.indexer import ValueIndexer
        from mmlspark_tpu.ops.stages import DropColumns, RenameColumn

        t = Table({"c": ["a", "b", "a"], "junk": np.arange(3.0)})
        model = t.ml_fit(ValueIndexer(input_col="c", output_col="i"))
        out = t.ml_transform(
            model,
            DropColumns(cols=["junk"]),
            RenameColumn(input_col="i", output_col="idx"),
        )
        assert out.columns == ["c", "idx"]
        assert list(np.asarray(out["idx"])) == [0.0, 1.0, 0.0]


class TestProfiling:
    """Tracing utilities (SURVEY.md §5.1: jax.profiler integration)."""

    def test_device_trace_writes_xplane(self, tmp_path):
        import jax.numpy as jnp

        from mmlspark_tpu.utils.profiling import device_trace

        target = str(tmp_path / "trace")
        with device_trace(target):
            jnp.arange(16.0).sum().block_until_ready()
        files = list((tmp_path / "trace").rglob("*"))
        assert any(f.suffix == ".pb" or "xplane" in f.name for f in files), files

    def test_device_trace_noop_without_target(self, monkeypatch):
        from mmlspark_tpu.utils.profiling import device_trace

        monkeypatch.delenv("MMLSPARK_TPU_TRACE_DIR", raising=False)
        with device_trace(None) as t:
            assert t is None

    def test_device_trace_env_var(self, tmp_path, monkeypatch):
        import jax.numpy as jnp

        from mmlspark_tpu.utils.profiling import device_trace

        monkeypatch.setenv("MMLSPARK_TPU_TRACE_DIR", str(tmp_path / "envtrace"))
        with device_trace(None) as t:
            assert t is not None
            jnp.ones(4).sum().block_until_ready()
        assert (tmp_path / "envtrace").exists()

    def test_profile_fn_and_annotate(self):
        import jax
        import jax.numpy as jnp

        from mmlspark_tpu.utils.profiling import annotate, profile_fn

        @jax.jit
        def f(v):
            with annotate("square"):
                return (v * v).sum()

        out, stats = profile_fn(f, jnp.arange(64.0), iters=2)
        assert stats["steady_s"] > 0
        assert stats["first_call_s"] >= stats["steady_s"] * 0.5
        assert stats["iter_min_s"] <= stats["iter_median_s"] <= stats["iter_max_s"]
        assert stats["iters"] == 2
        assert float(out) == float((np.arange(64.0) ** 2).sum())
