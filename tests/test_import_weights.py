"""Pretrained-weight import tests (reference ModelDownloader.scala:209+,
ImageFeaturizer.scala:92-135 — the transfer-learning ingestion story).

The synthetic checkpoint is generated from the DOCUMENTED torchvision
ResNet-50 topology (name/shape manifest below, written out from the
published architecture — bottleneck expansion 4, stride-on-conv2 a.k.a.
ResNet V1.5, downsample on each stage's first block), NOT from this
repo's importer, so a naming/transpose bug in the importer cannot be
self-consistent with the fixture. Expected activations are committed in
tests/fixtures/imported_resnet50_logits.json (regen:
MMLSPARK_TPU_REGEN_IMPORT_FIXTURE=1).
"""

import json
import os

import numpy as np
import pytest

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "imported_resnet50_logits.json")


def torchvision_resnet50_manifest() -> "dict[str, tuple[int, ...]]":
    """name -> shape for every tensor of a torchvision resnet50 state dict."""
    m: dict[str, tuple[int, ...]] = {
        "conv1.weight": (64, 3, 7, 7),
        "bn1.weight": (64,), "bn1.bias": (64,),
        "bn1.running_mean": (64,), "bn1.running_var": (64,),
        "bn1.num_batches_tracked": (),
    }
    inplanes = 64
    for li, (blocks, planes) in enumerate(
        [(3, 64), (4, 128), (6, 256), (3, 512)], start=1
    ):
        for b in range(blocks):
            p = f"layer{li}.{b}"
            m[f"{p}.conv1.weight"] = (planes, inplanes, 1, 1)
            m[f"{p}.conv2.weight"] = (planes, planes, 3, 3)
            m[f"{p}.conv3.weight"] = (planes * 4, planes, 1, 1)
            for bn, width in (("bn1", planes), ("bn2", planes),
                              ("bn3", planes * 4)):
                for leaf, shape in (("weight", (width,)), ("bias", (width,)),
                                    ("running_mean", (width,)),
                                    ("running_var", (width,)),
                                    ("num_batches_tracked", ())):
                    m[f"{p}.{bn}.{leaf}"] = shape
            if b == 0:
                m[f"{p}.downsample.0.weight"] = (planes * 4, inplanes, 1, 1)
                for leaf, shape in (("weight", (planes * 4,)),
                                    ("bias", (planes * 4,)),
                                    ("running_mean", (planes * 4,)),
                                    ("running_var", (planes * 4,)),
                                    ("num_batches_tracked", ())):
                    m[f"{p}.downsample.1.{leaf}"] = shape
            inplanes = planes * 4
    m["fc.weight"] = (1000, 2048)
    m["fc.bias"] = (1000,)
    return m


def synthetic_state_dict(seed: int = 0) -> "dict[str, np.ndarray]":
    rng = np.random.default_rng(seed)
    sd: dict[str, np.ndarray] = {}
    for name, shape in torchvision_resnet50_manifest().items():
        if name.endswith("num_batches_tracked"):
            sd[name] = np.asarray(100, np.int64)
        elif name.endswith("running_var"):
            sd[name] = (0.5 + np.abs(rng.standard_normal(shape))).astype(np.float32)
        elif name.endswith(("conv1.weight", "conv2.weight", "conv3.weight",
                            "downsample.0.weight")) or name == "conv1.weight":
            fan_in = int(np.prod(shape[1:])) or 1
            sd[name] = (rng.standard_normal(shape) / np.sqrt(fan_in)).astype(
                np.float32)
        else:
            sd[name] = (0.1 * rng.standard_normal(shape)).astype(np.float32)
    return sd


@pytest.fixture(scope="module")
def bundle(tmp_path_factory):
    from mmlspark_tpu.nn.import_weights import import_torch_resnet

    d = tmp_path_factory.mktemp("weights")
    path = os.path.join(d, "resnet50.npz")
    np.savez(path, **synthetic_state_dict())
    # small spatial size keeps the CPU forward cheap; the mapping under
    # test is shape/naming/transpose logic, which is size-independent
    return import_torch_resnet(path, input_shape=(64, 64, 3))


class TestMapping:
    def test_all_leaves_mapped_and_shapes_fit(self, bundle):
        # import_torch_resnet already validates leaf-for-leaf vs module.init;
        # reaching here means every torchvision tensor found a flax home
        assert bundle.config["num_outputs"] == 1000
        p = bundle.variables["params"]
        assert p["stem_conv"]["kernel"].shape == (7, 7, 3, 64)
        assert p["stage0_block0"]["proj_conv"]["kernel"].shape == (1, 1, 64, 256)
        assert p["head"]["kernel"].shape == (2048, 1000)
        bs = bundle.variables["batch_stats"]
        assert bs["stage3_block2"]["bn3"]["var"].shape == (2048,)

    def test_conv_transpose_is_oihw_to_hwio(self):
        from mmlspark_tpu.nn.import_weights import torch_resnet_to_flax

        sd = synthetic_state_dict()
        v = torch_resnet_to_flax(sd)
        w = sd["layer2.0.conv2.weight"]            # (128, 128, 3, 3) OIHW
        k = v["params"]["stage1_block0"]["conv2"]["kernel"]
        assert k.shape == (3, 3, 128, 128)
        np.testing.assert_array_equal(k[1, 2, 5, 7], w[7, 5, 1, 2])

    def test_fc_transposed(self):
        from mmlspark_tpu.nn.import_weights import torch_resnet_to_flax

        sd = synthetic_state_dict()
        v = torch_resnet_to_flax(sd)
        np.testing.assert_array_equal(
            v["params"]["head"]["kernel"], sd["fc.weight"].T
        )

    def test_unknown_key_raises(self):
        from mmlspark_tpu.nn.import_weights import torch_resnet_to_flax

        with pytest.raises(ValueError, match="unrecognized"):
            torch_resnet_to_flax({"classifier.weight": np.zeros((10, 10))})

    def test_missing_block_raises(self, tmp_path):
        from mmlspark_tpu.nn.import_weights import import_torch_resnet

        sd = synthetic_state_dict()
        sd.pop("layer3.4.conv2.weight")
        path = os.path.join(tmp_path, "broken.npz")
        np.savez(path, **sd)
        with pytest.raises(ValueError, match="missing"):
            import_torch_resnet(path, input_shape=(64, 64, 3))

    def test_untransposed_conv_raises(self, tmp_path):
        """A checkpoint whose convs were written HWIO (already 'converted')
        must be rejected, not silently double-transposed."""
        from mmlspark_tpu.nn.import_weights import import_torch_resnet

        sd = synthetic_state_dict()
        sd["conv1.weight"] = np.transpose(sd["conv1.weight"], (2, 3, 1, 0))
        path = os.path.join(tmp_path, "hwio.npz")
        np.savez(path, **sd)
        with pytest.raises(ValueError, match="shape mismatch"):
            import_torch_resnet(path, input_shape=(64, 64, 3))


class TestActivations:
    def test_forward_matches_committed_fixture(self, bundle):
        """The imported model's logits on a fixed input must match the
        committed expected activations — a transpose/naming regression in
        the mapper shows up as a numeric diff here."""
        import jax

        rng = np.random.default_rng(42)
        x = rng.integers(0, 256, size=(2, 64, 64, 3)).astype(np.float32)
        mean = np.asarray(bundle.preprocess["mean"], np.float32)
        std = np.asarray(bundle.preprocess["std"], np.float32)
        logits = np.asarray(jax.jit(
            lambda v, xb: bundle.module.apply(v, (xb - mean) / std,
                                              train=False)
        )(bundle.variables, x))
        assert logits.shape == (2, 1000) and np.isfinite(logits).all()
        got = logits[:, :8].tolist()
        if os.environ.get("MMLSPARK_TPU_REGEN_IMPORT_FIXTURE"):
            os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
            with open(FIXTURE, "w") as fh:
                json.dump({"logits_2x8": got}, fh, indent=2)
            pytest.skip("fixture regenerated")
        assert os.path.exists(FIXTURE), (
            "run with MMLSPARK_TPU_REGEN_IMPORT_FIXTURE=1 to create the fixture"
        )
        with open(FIXTURE) as fh:
            want = np.asarray(json.load(fh)["logits_2x8"])
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)


TRANSFORMER_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                                   "imported_transformer_logits.json")

# tiny encoder checkpoint dimensions (documented manifest, written out from
# the spec's naming contract — NOT produced by the importer under test)
T_VOCAB, T_DMODEL, T_HEADS, T_DFF, T_LAYERS, T_MAXLEN, T_OUT = (
    37, 16, 4, 32, 2, 24, 3
)


def transformer_manifest() -> "dict[str, tuple[int, ...]]":
    m: dict[str, tuple[int, ...]] = {
        "embeddings.word_embeddings.weight": (T_VOCAB, T_DMODEL),
        "embeddings.position_embeddings.weight": (T_MAXLEN, T_DMODEL),
        "final_layer_norm.weight": (T_DMODEL,),
        "final_layer_norm.bias": (T_DMODEL,),
        "classifier.weight": (T_OUT, T_DMODEL),
        "classifier.bias": (T_OUT,),
    }
    for i in range(T_LAYERS):
        p = f"encoder.layer.{i}"
        m[f"{p}.attention.ln.weight"] = (T_DMODEL,)
        m[f"{p}.attention.ln.bias"] = (T_DMODEL,)
        for proj in ("query", "key", "value"):
            m[f"{p}.attention.self.{proj}.weight"] = (T_DMODEL, T_DMODEL)
            m[f"{p}.attention.self.{proj}.bias"] = (T_DMODEL,)
        m[f"{p}.attention.output.dense.weight"] = (T_DMODEL, T_DMODEL)
        m[f"{p}.attention.output.dense.bias"] = (T_DMODEL,)
        m[f"{p}.mlp.ln.weight"] = (T_DMODEL,)
        m[f"{p}.mlp.ln.bias"] = (T_DMODEL,)
        m[f"{p}.intermediate.dense.weight"] = (T_DFF, T_DMODEL)
        m[f"{p}.intermediate.dense.bias"] = (T_DFF,)
        m[f"{p}.output.dense.weight"] = (T_DMODEL, T_DFF)
        m[f"{p}.output.dense.bias"] = (T_DMODEL,)
    return m


def synthetic_transformer_state_dict(seed: int = 1) -> "dict[str, np.ndarray]":
    rng = np.random.default_rng(seed)
    sd = {}
    for name, shape in transformer_manifest().items():
        if name.endswith("ln.weight") or name == "final_layer_norm.weight":
            sd[name] = (1.0 + 0.05 * rng.standard_normal(shape)).astype(
                np.float32)
        else:
            sd[name] = (0.2 * rng.standard_normal(shape)).astype(np.float32)
    return sd


class TestTransformerImport:
    @pytest.fixture(scope="class")
    def tbundle(self, tmp_path_factory):
        from mmlspark_tpu.nn.import_weights import import_torch_transformer

        d = tmp_path_factory.mktemp("tweights")
        path = os.path.join(d, "encoder.npz")
        np.savez(path, **synthetic_transformer_state_dict())
        return import_torch_transformer(path, num_heads=T_HEADS)

    def test_dims_inferred_from_checkpoint(self, tbundle):
        cfg = tbundle.config
        assert cfg["vocab_size"] == T_VOCAB
        assert cfg["d_model"] == T_DMODEL
        assert cfg["num_layers"] == T_LAYERS
        assert cfg["d_ff"] == T_DFF
        assert cfg["max_len"] == T_MAXLEN
        assert cfg["num_outputs"] == T_OUT

    def test_qkv_reshape_layout(self):
        """torch (out,in) q/k/v weights land as flax (in, H, out/H) with
        the head split on the OUTPUT axis after the transpose."""
        from mmlspark_tpu.nn.import_weights import torch_transformer_to_flax

        sd = synthetic_transformer_state_dict()
        v = torch_transformer_to_flax(sd, num_heads=T_HEADS)
        w = sd["encoder.layer.0.attention.self.query.weight"]
        k = v["params"]["attn_0"]["query"]["kernel"]
        dh = T_DMODEL // T_HEADS
        assert k.shape == (T_DMODEL, T_HEADS, dh)
        # out index o = h*dh + j; kernel[i, h, j] == w[o, i]
        np.testing.assert_array_equal(k[3, 2, 1], w[2 * dh + 1, 3])
        out_k = v["params"]["attn_0"]["out"]["kernel"]
        assert out_k.shape == (T_HEADS, dh, T_DMODEL)
        wo = sd["encoder.layer.0.attention.output.dense.weight"]
        np.testing.assert_array_equal(out_k[2, 1, 5], wo[5, 2 * dh + 1])

    def test_unknown_key_raises(self):
        from mmlspark_tpu.nn.import_weights import torch_transformer_to_flax

        sd = synthetic_transformer_state_dict()
        sd["pooler.dense.weight"] = np.zeros((4, 4), np.float32)
        with pytest.raises(ValueError, match="unrecognized"):
            torch_transformer_to_flax(sd, num_heads=T_HEADS)

    def test_missing_layer_raises(self, tmp_path):
        from mmlspark_tpu.nn.import_weights import import_torch_transformer

        sd = synthetic_transformer_state_dict()
        sd.pop("encoder.layer.1.mlp.ln.weight")
        path = os.path.join(tmp_path, "broken.npz")
        np.savez(path, **sd)
        with pytest.raises(ValueError, match="missing"):
            import_torch_transformer(path, num_heads=T_HEADS)

    def test_bad_head_count_raises(self, tmp_path):
        from mmlspark_tpu.nn.import_weights import import_torch_transformer

        path = os.path.join(tmp_path, "enc.npz")
        np.savez(path, **synthetic_transformer_state_dict())
        with pytest.raises(ValueError, match="num_heads"):
            import_torch_transformer(path, num_heads=5)

    def test_forward_matches_committed_fixture(self, tbundle):
        import jax

        tokens = np.arange(2 * 12).reshape(2, 12) % T_VOCAB
        logits = np.asarray(jax.jit(
            lambda v, xb: tbundle.module.apply(v, xb, train=False)
        )(tbundle.variables, tokens.astype(np.int32)))
        assert logits.shape == (2, T_OUT) and np.isfinite(logits).all()
        got = logits.tolist()
        if os.environ.get("MMLSPARK_TPU_REGEN_IMPORT_FIXTURE"):
            os.makedirs(os.path.dirname(TRANSFORMER_FIXTURE), exist_ok=True)
            with open(TRANSFORMER_FIXTURE, "w") as fh:
                json.dump({"logits_2x3": got}, fh, indent=2)
            pytest.skip("fixture regenerated")
        assert os.path.exists(TRANSFORMER_FIXTURE), (
            "run with MMLSPARK_TPU_REGEN_IMPORT_FIXTURE=1 to create the fixture"
        )
        with open(TRANSFORMER_FIXTURE) as fh:
            want = np.asarray(json.load(fh)["logits_2x3"])
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)

    def test_zoo_dispatches_transformer(self, tmp_path):
        from mmlspark_tpu.nn.zoo import ModelDownloader, ModelSchema

        src = os.path.join(tmp_path, "src", "encoder.npz")
        os.makedirs(os.path.dirname(src))
        np.savez(src, **synthetic_transformer_state_dict())
        dl = ModelDownloader(os.path.join(tmp_path, "repo"))
        schema = ModelSchema(
            name="tiny_encoder", uri=src, architecture="transformer",
            num_outputs=T_OUT,
            extra={"config": {"num_heads": T_HEADS}},
        )
        dest = dl.import_external(schema)
        assert os.path.exists(dest)
        loaded = dl.load_bundle("tiny_encoder")
        assert loaded.architecture == "transformer"
        assert loaded.config["num_heads"] == T_HEADS


class TestZooAndFeaturizer:
    def test_zoo_import_external_roundtrip(self, tmp_path):
        from safetensors.numpy import save_file

        from mmlspark_tpu.nn.zoo import ModelDownloader, ModelSchema

        src = os.path.join(tmp_path, "src", "resnet50.safetensors")
        os.makedirs(os.path.dirname(src))
        save_file(synthetic_state_dict(), src)
        repo = os.path.join(tmp_path, "repo")
        dl = ModelDownloader(repo)
        schema = ModelSchema(
            name="resnet50_pretrained", uri=src, architecture="resnet50",
            input_shape=(64, 64, 3), num_outputs=1000,
        )
        dest = dl.import_external(schema)
        assert os.path.exists(dest)
        loaded = dl.load_bundle("resnet50_pretrained")
        assert loaded.architecture == "resnet50"
        assert loaded.variables["params"]["head"]["kernel"].shape == (2048, 1000)
        # idempotent: second call is a no-op hit on the converted bundle
        assert dl.import_external(schema) == dest

    def test_featurizer_runs_on_imported_model(self, bundle):
        """ImageFeaturizer over imported weights — the reference's
        transfer-learning flow (ImageFeaturizer.scala:92-135) off a real
        external checkpoint format."""
        from mmlspark_tpu.core.schema import Table
        from mmlspark_tpu.nn.featurizer import ImageFeaturizer

        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, size=(4, 64, 64, 3), dtype=np.uint8)
        feat = ImageFeaturizer(
            input_col="image", output_col="features",
            layer_name="pooled_features",
        ).set_model(bundle)
        out = feat.transform(Table({"image": imgs}))
        arr = np.asarray(out["features"])
        assert arr.shape == (4, 2048) and np.isfinite(arr).all()
