"""Pretrained-weight import tests (reference ModelDownloader.scala:209+,
ImageFeaturizer.scala:92-135 — the transfer-learning ingestion story).

The synthetic checkpoint is generated from the DOCUMENTED torchvision
ResNet-50 topology (name/shape manifest below, written out from the
published architecture — bottleneck expansion 4, stride-on-conv2 a.k.a.
ResNet V1.5, downsample on each stage's first block), NOT from this
repo's importer, so a naming/transpose bug in the importer cannot be
self-consistent with the fixture. Expected activations are committed in
tests/fixtures/imported_resnet50_logits.json (regen:
MMLSPARK_TPU_REGEN_IMPORT_FIXTURE=1).
"""

import json
import os

import numpy as np
import pytest

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "imported_resnet50_logits.json")


def torchvision_resnet50_manifest() -> "dict[str, tuple[int, ...]]":
    """name -> shape for every tensor of a torchvision resnet50 state dict."""
    m: dict[str, tuple[int, ...]] = {
        "conv1.weight": (64, 3, 7, 7),
        "bn1.weight": (64,), "bn1.bias": (64,),
        "bn1.running_mean": (64,), "bn1.running_var": (64,),
        "bn1.num_batches_tracked": (),
    }
    inplanes = 64
    for li, (blocks, planes) in enumerate(
        [(3, 64), (4, 128), (6, 256), (3, 512)], start=1
    ):
        for b in range(blocks):
            p = f"layer{li}.{b}"
            m[f"{p}.conv1.weight"] = (planes, inplanes, 1, 1)
            m[f"{p}.conv2.weight"] = (planes, planes, 3, 3)
            m[f"{p}.conv3.weight"] = (planes * 4, planes, 1, 1)
            for bn, width in (("bn1", planes), ("bn2", planes),
                              ("bn3", planes * 4)):
                for leaf, shape in (("weight", (width,)), ("bias", (width,)),
                                    ("running_mean", (width,)),
                                    ("running_var", (width,)),
                                    ("num_batches_tracked", ())):
                    m[f"{p}.{bn}.{leaf}"] = shape
            if b == 0:
                m[f"{p}.downsample.0.weight"] = (planes * 4, inplanes, 1, 1)
                for leaf, shape in (("weight", (planes * 4,)),
                                    ("bias", (planes * 4,)),
                                    ("running_mean", (planes * 4,)),
                                    ("running_var", (planes * 4,)),
                                    ("num_batches_tracked", ())):
                    m[f"{p}.downsample.1.{leaf}"] = shape
            inplanes = planes * 4
    m["fc.weight"] = (1000, 2048)
    m["fc.bias"] = (1000,)
    return m


def synthetic_state_dict(seed: int = 0) -> "dict[str, np.ndarray]":
    rng = np.random.default_rng(seed)
    sd: dict[str, np.ndarray] = {}
    for name, shape in torchvision_resnet50_manifest().items():
        if name.endswith("num_batches_tracked"):
            sd[name] = np.asarray(100, np.int64)
        elif name.endswith("running_var"):
            sd[name] = (0.5 + np.abs(rng.standard_normal(shape))).astype(np.float32)
        elif name.endswith(("conv1.weight", "conv2.weight", "conv3.weight",
                            "downsample.0.weight")) or name == "conv1.weight":
            fan_in = int(np.prod(shape[1:])) or 1
            sd[name] = (rng.standard_normal(shape) / np.sqrt(fan_in)).astype(
                np.float32)
        else:
            sd[name] = (0.1 * rng.standard_normal(shape)).astype(np.float32)
    return sd


@pytest.fixture(scope="module")
def bundle(tmp_path_factory):
    from mmlspark_tpu.nn.import_weights import import_torch_resnet

    d = tmp_path_factory.mktemp("weights")
    path = os.path.join(d, "resnet50.npz")
    np.savez(path, **synthetic_state_dict())
    # small spatial size keeps the CPU forward cheap; the mapping under
    # test is shape/naming/transpose logic, which is size-independent
    return import_torch_resnet(path, input_shape=(64, 64, 3))


class TestMapping:
    def test_all_leaves_mapped_and_shapes_fit(self, bundle):
        # import_torch_resnet already validates leaf-for-leaf vs module.init;
        # reaching here means every torchvision tensor found a flax home
        assert bundle.config["num_outputs"] == 1000
        p = bundle.variables["params"]
        assert p["stem_conv"]["kernel"].shape == (7, 7, 3, 64)
        assert p["stage0_block0"]["proj_conv"]["kernel"].shape == (1, 1, 64, 256)
        assert p["head"]["kernel"].shape == (2048, 1000)
        bs = bundle.variables["batch_stats"]
        assert bs["stage3_block2"]["bn3"]["var"].shape == (2048,)

    def test_conv_transpose_is_oihw_to_hwio(self):
        from mmlspark_tpu.nn.import_weights import torch_resnet_to_flax

        sd = synthetic_state_dict()
        v = torch_resnet_to_flax(sd)
        w = sd["layer2.0.conv2.weight"]            # (128, 128, 3, 3) OIHW
        k = v["params"]["stage1_block0"]["conv2"]["kernel"]
        assert k.shape == (3, 3, 128, 128)
        np.testing.assert_array_equal(k[1, 2, 5, 7], w[7, 5, 1, 2])

    def test_fc_transposed(self):
        from mmlspark_tpu.nn.import_weights import torch_resnet_to_flax

        sd = synthetic_state_dict()
        v = torch_resnet_to_flax(sd)
        np.testing.assert_array_equal(
            v["params"]["head"]["kernel"], sd["fc.weight"].T
        )

    def test_unknown_key_raises(self):
        from mmlspark_tpu.nn.import_weights import torch_resnet_to_flax

        with pytest.raises(ValueError, match="unrecognized"):
            torch_resnet_to_flax({"classifier.weight": np.zeros((10, 10))})

    def test_missing_block_raises(self, tmp_path):
        from mmlspark_tpu.nn.import_weights import import_torch_resnet

        sd = synthetic_state_dict()
        sd.pop("layer3.4.conv2.weight")
        path = os.path.join(tmp_path, "broken.npz")
        np.savez(path, **sd)
        with pytest.raises(ValueError, match="missing"):
            import_torch_resnet(path, input_shape=(64, 64, 3))

    def test_untransposed_conv_raises(self, tmp_path):
        """A checkpoint whose convs were written HWIO (already 'converted')
        must be rejected, not silently double-transposed."""
        from mmlspark_tpu.nn.import_weights import import_torch_resnet

        sd = synthetic_state_dict()
        sd["conv1.weight"] = np.transpose(sd["conv1.weight"], (2, 3, 1, 0))
        path = os.path.join(tmp_path, "hwio.npz")
        np.savez(path, **sd)
        with pytest.raises(ValueError, match="shape mismatch"):
            import_torch_resnet(path, input_shape=(64, 64, 3))


class TestActivations:
    def test_forward_matches_committed_fixture(self, bundle):
        """The imported model's logits on a fixed input must match the
        committed expected activations — a transpose/naming regression in
        the mapper shows up as a numeric diff here."""
        import jax

        rng = np.random.default_rng(42)
        x = rng.integers(0, 256, size=(2, 64, 64, 3)).astype(np.float32)
        mean = np.asarray(bundle.preprocess["mean"], np.float32)
        std = np.asarray(bundle.preprocess["std"], np.float32)
        logits = np.asarray(jax.jit(
            lambda v, xb: bundle.module.apply(v, (xb - mean) / std,
                                              train=False)
        )(bundle.variables, x))
        assert logits.shape == (2, 1000) and np.isfinite(logits).all()
        got = logits[:, :8].tolist()
        if os.environ.get("MMLSPARK_TPU_REGEN_IMPORT_FIXTURE"):
            os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
            with open(FIXTURE, "w") as fh:
                json.dump({"logits_2x8": got}, fh, indent=2)
            pytest.skip("fixture regenerated")
        assert os.path.exists(FIXTURE), (
            "run with MMLSPARK_TPU_REGEN_IMPORT_FIXTURE=1 to create the fixture"
        )
        with open(FIXTURE) as fh:
            want = np.asarray(json.load(fh)["logits_2x8"])
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)


class TestZooAndFeaturizer:
    def test_zoo_import_external_roundtrip(self, tmp_path):
        from safetensors.numpy import save_file

        from mmlspark_tpu.nn.zoo import ModelDownloader, ModelSchema

        src = os.path.join(tmp_path, "src", "resnet50.safetensors")
        os.makedirs(os.path.dirname(src))
        save_file(synthetic_state_dict(), src)
        repo = os.path.join(tmp_path, "repo")
        dl = ModelDownloader(repo)
        schema = ModelSchema(
            name="resnet50_pretrained", uri=src, architecture="resnet50",
            input_shape=(64, 64, 3), num_outputs=1000,
        )
        dest = dl.import_external(schema)
        assert os.path.exists(dest)
        loaded = dl.load_bundle("resnet50_pretrained")
        assert loaded.architecture == "resnet50"
        assert loaded.variables["params"]["head"]["kernel"].shape == (2048, 1000)
        # idempotent: second call is a no-op hit on the converted bundle
        assert dl.import_external(schema) == dest

    def test_featurizer_runs_on_imported_model(self, bundle):
        """ImageFeaturizer over imported weights — the reference's
        transfer-learning flow (ImageFeaturizer.scala:92-135) off a real
        external checkpoint format."""
        from mmlspark_tpu.core.schema import Table
        from mmlspark_tpu.nn.featurizer import ImageFeaturizer

        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, size=(4, 64, 64, 3), dtype=np.uint8)
        feat = ImageFeaturizer(
            input_col="image", output_col="features",
            layer_name="pooled_features",
        ).set_model(bundle)
        out = feat.transform(Table({"image": imgs}))
        arr = np.asarray(out["features"])
        assert arr.shape == (4, 2048) and np.isfinite(arr).all()
