"""Worker for the multi-host rendezvous test (run as a subprocess).

Exercises the product path: `initialize_runtime` (the jax.distributed
rendezvous that replaces the reference's driver-socket handshake and
ssh/MPI, SURVEY.md §5.8) -> global mesh over ALL processes' devices ->
cross-process psum on the data axis.
"""

import os
import sys


def main() -> None:
    rank, n_procs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from mmlspark_tpu.parallel.mesh import initialize_runtime, make_mesh

    initialize_runtime(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=n_procs,
        process_id=rank,
    )

    import numpy as np
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = jax.devices()                     # global across processes
    mesh = make_mesh(n_data=len(devs))
    psum = jax.jit(shard_map(
        lambda x: jax.lax.psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(),
    ))
    # per-process local shards -> one global row-sharded array
    sharding = NamedSharding(mesh, P("data"))
    shards = [
        jax.device_put(np.full((1, 1), float(rank + 1), np.float32), d)
        for d in jax.local_devices()
    ]
    garr = jax.make_array_from_single_device_arrays(
        (len(devs), 1), sharding, shards
    )
    out = psum(garr)
    val = float(np.asarray(out.addressable_data(0))[0, 0])
    print(f"RESULT rank={rank} n_devices={len(devs)} "
          f"n_local={len(jax.local_devices())} psum={val}", flush=True)


if __name__ == "__main__":
    main()
