"""Worker for the multi-host rendezvous test (run as a subprocess).

Exercises the product path: `initialize_runtime` (the jax.distributed
rendezvous that replaces the reference's driver-socket handshake and
ssh/MPI, SURVEY.md §5.8) -> global mesh over ALL processes' devices ->
cross-process psum on the data axis.
"""

import os
import sys


def main() -> None:
    rank, n_procs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from mmlspark_tpu.parallel.mesh import initialize_runtime, make_mesh

    initialize_runtime(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=n_procs,
        process_id=rank,
    )

    import numpy as np
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5: shard_map lives under experimental
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = jax.devices()                     # global across processes
    mesh = make_mesh(n_data=len(devs))
    psum = jax.jit(shard_map(
        lambda x: jax.lax.psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(),
    ))
    # per-process local shards -> one global row-sharded array
    sharding = NamedSharding(mesh, P("data"))
    shards = [
        jax.device_put(np.full((1, 1), float(rank + 1), np.float32), d)
        for d in jax.local_devices()
    ]
    garr = jax.make_array_from_single_device_arrays(
        (len(devs), 1), sharding, shards
    )
    out = psum(garr)
    val = float(np.asarray(out.addressable_data(0))[0, 0])

    # -- distributed GBDT fit over the cross-process mesh ----------------
    # The reference's data-parallel tree learner guarantees every worker
    # ends with an identical model (LightGBMClassifier.scala:82-85); here
    # the same guarantee must hold across real process boundaries: the
    # 4-device 2-process fit must equal the plain local fit.
    from mmlspark_tpu.core.schema import Table
    from mmlspark_tpu.gbdt.estimators import GBDTClassifier
    from mmlspark_tpu.parallel.mesh import use_mesh

    rng = np.random.default_rng(0)           # identical data on every rank
    x = rng.normal(size=(256, 6))
    yl = (x[:, 0] - 0.5 * x[:, 1] + 0.2 * rng.normal(size=256) > 0)
    tbl = Table({"features": x, "label": yl.astype(np.float64)})
    single = GBDTClassifier(num_iterations=2, num_leaves=7).fit(tbl)
    with use_mesh(mesh):
        dist = GBDTClassifier(num_iterations=2, num_leaves=7,
                              use_mesh=True).fit(tbl)
    struct_ok = bool(
        np.array_equal(dist.booster.feature, single.booster.feature)
        and np.array_equal(dist.booster.left, single.booster.left)
    )
    pred_ok = bool(np.allclose(
        np.asarray(dist.booster.predict(x)),
        np.asarray(single.booster.predict(x)), rtol=1e-3, atol=1e-5,
    ))
    # byte-level model identity across ranks (thresholds + leaf values, not
    # just structure): hash of the serialized model text
    import hashlib

    model_hash = hashlib.sha256(dist.booster.to_text().encode()).hexdigest()[:16]

    print(f"RESULT rank={rank} n_devices={len(devs)} "
          f"n_local={len(jax.local_devices())} psum={val} "
          f"gbdt_struct={int(struct_ok)} gbdt_pred={int(pred_ok)} "
          f"model_hash={model_hash}", flush=True)


if __name__ == "__main__":
    main()
