"""The COMMITTED model zoo (model_zoo/) must serve real artifacts out of
the box (VERDICT r4 #8 — the reference ships a stocked zoo its
ModelDownloader pulls from, ModelDownloader.scala:209+; here the stocked
content is this framework's own reference models, trained on the vendored
real datasets by tools/build_zoo.py).

These gates pin: the index parses with verified hashes, the GBDT artifacts
load through the LightGBM-interchange format and still predict well, and
the ResNet-20 bundle scores the real digits holdout at its committed
accuracy — all WITHOUT any training step.
"""

import os

import numpy as np
import pytest

ZOO = os.path.join(os.path.dirname(__file__), os.pardir, "model_zoo")

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(ZOO, "index.json")),
    reason="model_zoo/ not stocked (run tools/build_zoo.py)",
)

EXPECTED = {"gbdt_wdbc", "gbdt_diabetes", "gbdt_adult_census_synthetic",
            "resnet20_digits"}


@pytest.fixture(scope="module")
def zoo():
    from mmlspark_tpu.nn.zoo import ModelDownloader

    return ModelDownloader(ZOO)


def _load_csv(name):
    from mmlspark_tpu.utils.datagen import load_label_csv

    return load_label_csv(os.path.join(
        os.path.dirname(__file__), "benchmarks", "data", f"{name}.csv"))


def _split(y, seed=0):
    # the stocked zoo's shared train/holdout contract — evaluating on any
    # other split would silently score training rows
    from mmlspark_tpu.utils.datagen import holdout_split

    return holdout_split(len(y), seed=seed)


class TestIndexIntegrity:
    def test_expected_models_stocked(self, zoo):
        names = {s.name for s in zoo.models()}
        assert EXPECTED <= names, f"missing: {EXPECTED - names}"

    def test_artifacts_exist_and_hashes_verify(self, zoo):
        from mmlspark_tpu.nn.zoo import _sha256

        for s in zoo.models():
            path = zoo.local_path(s.name)
            assert os.path.exists(path), s.name
            assert s.sha256, f"{s.name} has no committed sha256"
            assert _sha256(path) == s.sha256, f"{s.name} hash mismatch"

    def test_uris_are_repo_relative(self, zoo):
        # a committed index must resolve from any checkout path
        for s in zoo.models():
            assert "://" not in s.uri and not os.path.isabs(s.uri), (
                f"{s.name} uri {s.uri!r} is not repo-relative")


class TestGBDTArtifacts:
    def test_wdbc_booster_predicts(self, zoo):
        from mmlspark_tpu.automl.metrics import auc

        b = zoo.load_booster("gbdt_wdbc")
        x, y = _load_csv("breast_cancer_wdbc")
        tr, te = _split(y)
        holdout = auc(y[te], np.asarray(b.predict(x[te])))
        assert holdout > 0.97, holdout

    def test_diabetes_booster_predicts(self, zoo):
        b = zoo.load_booster("gbdt_diabetes")
        x, y = _load_csv("diabetes")
        tr, te = _split(y)
        rmse = float(np.sqrt(np.mean(
            (np.asarray(b.predict(x[te])) - y[te]) ** 2)))
        assert rmse < 62.0, rmse

    def test_artifact_is_lightgbm_interchange_format(self, zoo):
        # the stocked artifact IS the interchange story (docs/scope.md):
        # actual LightGBM can load this file as-is
        with open(zoo.local_path("gbdt_wdbc")) as fh:
            head = fh.read(64)
        assert head.startswith("tree\n"), head

    def test_load_booster_rejects_nn_bundles(self, zoo):
        with pytest.raises(ValueError, match="not a\n?.*gbdt|gbdt"):
            zoo.load_booster("resnet20_digits")


class TestResNetBundle:
    def test_digits_holdout_accuracy(self, zoo):
        from mmlspark_tpu.core.schema import Table
        from mmlspark_tpu.nn import DeepModelTransformer

        from mmlspark_tpu.utils.datagen import digits_to_images

        bundle = zoo.load_bundle("resnet20_digits")
        x, y = _load_csv("digits")
        img = digits_to_images(x)
        tr, te = _split(y)
        runner = DeepModelTransformer(
            input_col="image", mini_batch_size=256,
            fetch_dict={"probs": "probability"},
        ).set_model(bundle)
        probs = np.asarray(
            runner.transform(Table({"image": img[te]}))["probs"])
        acc = float((probs.argmax(axis=1) == y[te]).mean())
        # committed holdout accuracy (build_zoo r5) is ~0.947; the gate
        # keeps a small window under it
        assert acc > 0.9, acc

    def test_schema_metadata(self, zoo):
        s = zoo.get_model("resnet20_digits")
        assert s.architecture == "resnet20_cifar"
        assert tuple(s.input_shape) == (8, 8, 3)
        assert s.num_outputs == 10
        assert s.class_labels == [str(d) for d in range(10)]
